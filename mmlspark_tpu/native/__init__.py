"""ctypes bindings for the host-side C++ library (``native/``).

The reference loads its native hot loops from jar-shipped shared objects
(``core/env/NativeLoader.java``); here the ``.so`` is built by
``make -C native`` and discovered next to the repo (or via
``MMLSPARK_TPU_NATIVE`` for installed layouts). Every entry point has a
numpy fallback, so the library is an acceleration, not a dependency:

- :func:`apply_bins_native` — float64 features -> uint8 bins
  (bit-identical to ``lightgbm.binning.apply_bins``);
- :func:`murmur3_bytes_native` / :func:`murmur3_ints_native` /
  :func:`murmur3_strings_native` — MurmurHash3 matching ``ops.hashing``
  (the strings entry hashes a whole packed array of byte strings per call).

Set ``MMLSPARK_TPU_NATIVE=off`` to force the numpy fallbacks (CI runs the
suite both ways so the fallback path stays load-bearing).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_LIB: Optional[ctypes.CDLL] = None
_LOAD_ATTEMPTED = False

#: MMLSPARK_TPU_NATIVE values that force the numpy fallback paths.
_DISABLE_VALUES = ("off", "0", "disable", "disabled", "none")


def _repo_root() -> str:
    here = os.path.dirname(os.path.abspath(__file__))  # mmlspark_tpu/native/
    return os.path.dirname(os.path.dirname(here))


def native_disabled() -> bool:
    return os.environ.get("MMLSPARK_TPU_NATIVE", "").lower() in _DISABLE_VALUES


def _candidate_paths():
    env = os.environ.get("MMLSPARK_TPU_NATIVE")
    if env:
        yield env
    yield os.path.join(_repo_root(), "native", "libmmlspark_native.so")


def load_library(path: Optional[str] = None) -> Optional[ctypes.CDLL]:
    """Load the native library; None when unavailable. Auto-discovery is
    memoized; an explicit ``path`` always loads fresh (so ``build`` can
    swap in a rebuilt .so) and never poisons later auto-discovery."""
    global _LIB, _LOAD_ATTEMPTED
    if native_disabled():
        return None
    if path is None:
        if _LIB is not None:
            return _LIB
        if _LOAD_ATTEMPTED:
            return None
        _LOAD_ATTEMPTED = True
        paths = list(_candidate_paths())
    else:
        paths = [path]
    for p in paths:
        if p and os.path.exists(p):
            lib = ctypes.CDLL(p)
            lib.apply_bins_u8.argtypes = [
                ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32,
            ]
            lib.apply_bins_u8.restype = None
            lib.murmur3_x86_32.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_uint32,
            ]
            lib.murmur3_x86_32.restype = ctypes.c_uint32
            lib.murmur3_ints_u32.argtypes = [
                ctypes.POINTER(ctypes.c_uint32), ctypes.c_int64,
                ctypes.c_uint32, ctypes.POINTER(ctypes.c_uint32),
            ]
            lib.murmur3_ints_u32.restype = None
            # Older prebuilt .so may predate the strings entry; probe so a
            # stale library degrades to the numpy fallback instead of an
            # AttributeError at call time.
            try:
                lib.murmur3_strings_u32.argtypes = [
                    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                    ctypes.POINTER(ctypes.c_uint8),
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_int32),
                    ctypes.c_int64, ctypes.c_uint32,
                    ctypes.POINTER(ctypes.c_uint32),
                ]
                lib.murmur3_strings_u32.restype = None
            except AttributeError:
                lib.murmur3_strings_u32 = None
            try:
                lib.murmur3_split_hash_u32.argtypes = [
                    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                    ctypes.POINTER(ctypes.c_uint8),
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.c_int64, ctypes.c_uint32,
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.POINTER(ctypes.c_int64),
                    ctypes.POINTER(ctypes.c_uint8),
                ]
                lib.murmur3_split_hash_u32.restype = ctypes.c_int64
            except AttributeError:
                lib.murmur3_split_hash_u32 = None
            _LIB = lib
            return lib
    return None


def native_available() -> bool:
    return load_library() is not None


def build(repo_root: Optional[str] = None) -> str:
    """Compile the library with the in-tree Makefile (g++ required)."""
    root = repo_root or _repo_root()
    native_dir = os.path.join(root, "native")
    subprocess.run(["make", "-C", native_dir], check=True, capture_output=True)
    global _LIB, _LOAD_ATTEMPTED
    _LIB = None  # drop any stale handle so the rebuilt .so takes over
    _LOAD_ATTEMPTED = False
    path = os.path.join(native_dir, "libmmlspark_native.so")
    if load_library(path) is None:
        raise RuntimeError(f"built {path} but could not load it")
    return path


# -- entry points (native with numpy fallback) -------------------------------


def apply_bins_native(X: np.ndarray, edges: np.ndarray, max_bin: int) -> Optional[np.ndarray]:
    """uint8 bins via C++; None when the library is unavailable or shapes
    exceed its contract (edges per feature must fit the 256-slot buffer)."""
    lib = load_library()
    if lib is None or edges.shape[1] > 256:
        return None
    Xc = np.ascontiguousarray(X, dtype=np.float64)
    ec = np.ascontiguousarray(edges, dtype=np.float64)
    n, f = Xc.shape
    if ec.shape[0] != f:
        raise ValueError(f"edges rows {ec.shape[0]} != features {f}")
    out = np.empty((n, f), dtype=np.uint8)
    lib.apply_bins_u8(
        Xc.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int64(n), ctypes.c_int64(f),
        ec.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int64(ec.shape[1]),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int32(max_bin),
    )
    return out


def murmur3_bytes_native(data: bytes, seed: int = 0) -> Optional[int]:
    lib = load_library()
    if lib is None:
        return None
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else (ctypes.c_uint8 * 1)()
    return int(
        lib.murmur3_x86_32(
            ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8)),
            ctypes.c_int64(len(data)),
            ctypes.c_uint32(seed & 0xFFFFFFFF),
        )
    )


def murmur3_strings_native(
    buf: np.ndarray,
    starts: np.ndarray,
    lens: np.ndarray,
    seed: int = 0,
    prefix: bytes = b"",
) -> Optional[np.ndarray]:
    """Hash a packed array of byte strings (string i is
    ``buf[starts[i] : starts[i] + lens[i]]``) with ``prefix`` virtually
    prepended to each — ONE library call per featurizer column. None when
    the library is unavailable."""
    lib = load_library()
    if lib is None or getattr(lib, "murmur3_strings_u32", None) is None:
        return None
    bc = np.ascontiguousarray(buf, dtype=np.uint8)
    sc = np.ascontiguousarray(starts, dtype=np.int64)
    lc = np.ascontiguousarray(lens, dtype=np.int32)
    if sc.shape != lc.shape:
        raise ValueError(f"starts shape {sc.shape} != lens shape {lc.shape}")
    pbuf = (
        (ctypes.c_uint8 * len(prefix)).from_buffer_copy(prefix)
        if prefix
        else (ctypes.c_uint8 * 1)()
    )
    out = np.empty(sc.size, dtype=np.uint32)
    lib.murmur3_strings_u32(
        ctypes.cast(pbuf, ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int64(len(prefix)),
        bc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        sc.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lc.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(sc.size),
        ctypes.c_uint32(seed & 0xFFFFFFFF),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    return out


def murmur3_split_hash_native(
    buf: np.ndarray,
    row_starts: np.ndarray,
    row_lens: np.ndarray,
    seed: int = 0,
    prefix: bytes = b"",
):
    """Fused whitespace-split + murmur over packed string-column rows: ONE
    C pass replaces the numpy splitter's full-buffer mask passes and the
    separate batch hash call. Returns ``(hashes uint32, counts int64,
    suspect uint8)`` — per-row token counts, with rows that may contain
    non-ASCII whitespace flagged (count 0) for a Python re-split. None when
    the library lacks the entry."""
    lib = load_library()
    if lib is None or getattr(lib, "murmur3_split_hash_u32", None) is None:
        return None
    bc = np.ascontiguousarray(buf, dtype=np.uint8)
    sc = np.ascontiguousarray(row_starts, dtype=np.int64)
    lc = np.ascontiguousarray(row_lens, dtype=np.int64)
    if sc.shape != lc.shape:
        raise ValueError(f"row_starts shape {sc.shape} != row_lens shape {lc.shape}")
    if bc.size == 0:
        bc = np.zeros(1, dtype=np.uint8)  # keep the data pointer valid
    pbuf = (
        (ctypes.c_uint8 * len(prefix)).from_buffer_copy(prefix)
        if prefix
        else (ctypes.c_uint8 * 1)()
    )
    max_tokens = (int(lc.sum()) + sc.size) // 2 + 1
    hashes = np.empty(max_tokens, dtype=np.uint32)
    counts = np.empty(sc.size, dtype=np.int64)
    suspect = np.empty(sc.size, dtype=np.uint8)
    total = lib.murmur3_split_hash_u32(
        ctypes.cast(pbuf, ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int64(len(prefix)),
        bc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        sc.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lc.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(sc.size),
        ctypes.c_uint32(seed & 0xFFFFFFFF),
        hashes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        suspect.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return hashes[:total], counts, suspect


def murmur3_ints_native(values: np.ndarray, seed: int = 0) -> Optional[np.ndarray]:
    lib = load_library()
    if lib is None:
        return None
    vc = np.ascontiguousarray(values, dtype=np.uint32)
    out = np.empty(vc.shape, dtype=np.uint32)
    lib.murmur3_ints_u32(
        vc.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        ctypes.c_int64(vc.size),
        ctypes.c_uint32(seed & 0xFFFFFFFF),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
    )
    return out.reshape(values.shape)
