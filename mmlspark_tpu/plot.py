"""Plot helpers: confusion matrix and ROC curve.

Parity target: the reference's hand-written Python plotting surface
(``/root/reference/src/main/python/mmlspark/plot/plot.py:17-59``), which
renders a row-normalized confusion-matrix heatmap with per-cell counts and
an accuracy banner, and a basic ROC curve.  This module re-derives both
from first principles on numpy (no sklearn dependency for the math — the
confusion matrix and the ROC sweep are computed here, matching the pinned
implementations in ``train/statistics.py``), and accepts either a
:class:`~mmlspark_tpu.data.table.Table` or anything pandas-shaped.

Matplotlib is imported lazily so headless installs that never plot pay
nothing; callers in tests force the ``Agg`` backend.
"""

from __future__ import annotations

import itertools
from typing import Any, Optional, Sequence

import numpy as np

__all__ = ["confusion_matrix", "confusionMatrix", "roc", "roc_points"]


def _columns(df: Any, y_col: str, y_hat_col: str):
    """Extract the two columns — Table, pandas frame, and plain mappings all
    support ``[]`` access."""
    return np.asarray(df[y_col]), np.asarray(df[y_hat_col])


def _confusion_counts(y: np.ndarray, y_hat: np.ndarray, labels: Sequence[Any]):
    """Count matrix with rows = true label, cols = predicted label. Rows
    whose true OR predicted value is outside ``labels`` are dropped, the
    sklearn ``confusion_matrix(..., labels=...)`` behavior. Vectorized via
    sorted-label searchsorted (the np.add.at pattern of
    ``train/statistics.py``), so million-row tables stay out of the
    interpreter loop."""
    labels_arr = np.asarray(labels)
    k = len(labels_arr)
    order = np.argsort(labels_arr, kind="stable")
    slabels = labels_arr[order]

    def to_index(vals):
        pos = np.searchsorted(slabels, vals)
        pos = np.clip(pos, 0, k - 1)
        ok = slabels[pos] == vals
        return order[pos], ok

    yi, ok_y = to_index(y)
    pi, ok_p = to_index(y_hat)
    keep = ok_y & ok_p
    cm = np.zeros((k, k), dtype=np.int64)
    np.add.at(cm, (yi[keep], pi[keep]), 1)
    return cm


def roc_points(y: np.ndarray, scores: np.ndarray):
    """ROC sweep: (fpr, tpr, thresholds), scores descending.

    Same convention as the reference's sklearn ``roc_curve`` call: one
    point per distinct score, prepended with (0, 0) at threshold +inf.
    """
    y = np.asarray(y, dtype=np.float64)
    scores = np.asarray(scores, dtype=np.float64)
    if y.size == 0:
        return np.zeros(1), np.zeros(1), np.array([np.inf])
    order = np.argsort(-scores, kind="stable")
    y, scores = y[order], scores[order]
    # Cut after the last occurrence of each distinct score value.
    distinct = np.where(np.diff(scores))[0]
    cuts = np.r_[distinct, y.size - 1]
    tps = np.cumsum(y)[cuts]
    fps = (cuts + 1) - tps
    pos = max(tps[-1], 1.0)
    neg = max(fps[-1], 1.0)
    tpr = np.r_[0.0, tps / pos]
    fpr = np.r_[0.0, fps / neg]
    thresholds = np.r_[np.inf, scores[cuts]]
    return fpr, tpr, thresholds


def confusion_matrix(
    df: Any,
    y_col: str,
    y_hat_col: str,
    labels: Optional[Sequence[Any]] = None,
    ax: Any = None,
):
    """Render the reference-style confusion-matrix heatmap.

    Row-normalized blue heatmap, raw counts in each cell, accuracy banner
    above the axes (``plot.py:25-43`` in the reference).  Returns the
    matplotlib Axes so callers can save or compose the figure.
    """
    import matplotlib.pyplot as plt

    y, y_hat = _columns(df, y_col, y_hat_col)
    if labels is None:
        labels = sorted(set(y.tolist()) | set(y_hat.tolist()))
    accuracy = float(np.mean(y == y_hat))
    cm = _confusion_counts(y, y_hat, labels)
    row_sums = np.maximum(cm.sum(axis=1, keepdims=True), 1)
    cmn = cm.astype(np.float64) / row_sums

    if ax is None:
        ax = plt.gca()
    ax.text(-0.3, -0.55, f"Accuracy = {round(accuracy * 100, 1)}%", fontsize=18)
    ticks = np.arange(len(labels))
    ax.set_xticks(ticks, labels=[str(l) for l in labels], rotation=0)
    ax.set_yticks(ticks, labels=[str(l) for l in labels], rotation=90)
    image = ax.imshow(cmn, interpolation="nearest", cmap=plt.cm.Blues, vmin=0, vmax=1)
    for i, j in itertools.product(range(cm.shape[0]), range(cm.shape[1])):
        ax.text(
            j,
            i,
            int(cm[i, j]),
            horizontalalignment="center",
            fontsize=18,
            color="white" if cmn[i, j] > 0.1 else "black",
        )
    ax.figure.colorbar(image, ax=ax)
    ax.set_xlabel("Predicted Label", fontsize=18)
    ax.set_ylabel("True Label", fontsize=18)
    return ax


# Reference-parity alias (plot.py:17 names it camelCase).
confusionMatrix = confusion_matrix


def roc(df: Any, y_col: str, y_hat_col: str, thresh: float = 0.5, ax: Any = None):
    """Render the ROC curve (reference ``plot.py:45-59``).

    ``y_col`` is binarized at ``thresh`` exactly as the reference does
    (labels above the threshold count as positive), then swept against the
    raw scores in ``y_hat_col``.  Returns the Axes.
    """
    import matplotlib.pyplot as plt

    y, y_hat = _columns(df, y_col, y_hat_col)
    y_bin = (np.asarray(y, dtype=np.float64) > thresh).astype(np.int64)
    fpr, tpr, _ = roc_points(y_bin, y_hat)
    if ax is None:
        ax = plt.gca()
    ax.plot(fpr, tpr)
    ax.set_xlabel("False Positive Rate", fontsize=20)
    ax.set_ylabel("True Positive Rate", fontsize=20)
    return ax
