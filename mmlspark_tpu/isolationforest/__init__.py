"""Isolation Forest (reference ``isolationforest/IsolationForest.scala:9-58``,
a thin re-export of LinkedIn's ``isolation-forest`` Spark estimator)."""

from mmlspark_tpu.isolationforest.forest import IsolationForest, IsolationForestModel

__all__ = ["IsolationForest", "IsolationForestModel"]
