"""Isolation forest — anomaly detection, TPU-native.

The reference wraps LinkedIn's JVM ``isolation-forest`` estimator
(``isolationforest/IsolationForest.scala:9-58``; param surface from
``com.linkedin.relevance.isolationforest.IsolationForestParams``). This is a
from-scratch implementation: isolation trees are built host-side on
subsamples (cheap, O(numEstimators × maxSamples log maxSamples)), then
packed into flat arrays so *scoring* — the per-row hot path — is a single
jitted program: every tree descends in lockstep through a fixed
``max_depth`` ``lax.fori_loop`` of gathers (no data-dependent control
flow), vmapped over trees and batched over rows.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mmlspark_tpu.core.params import (
    HasFeaturesCol,
    HasPredictionCol,
    Param,
    gt,
    to_float,
    to_int,
    to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.data.table import Table


def _harmonic(n: float) -> float:
    return float(np.log(n) + 0.5772156649015329)


def c_factor(n: float) -> float:
    """Average unsuccessful-search path length in a BST of n nodes — the
    isolation-forest normalizer c(n)."""
    if n <= 1.0:
        return 0.0
    if n == 2.0:
        return 1.0
    return 2.0 * _harmonic(n - 1.0) - 2.0 * (n - 1.0) / n


class _TreeArrays:
    """One isolation tree as flat arrays (node-major)."""

    __slots__ = ("feature", "threshold", "left", "right", "path_adjust")

    def __init__(self, n_nodes: int):
        self.feature = np.zeros(n_nodes, dtype=np.int32)
        self.threshold = np.zeros(n_nodes, dtype=np.float32)
        self.left = np.zeros(n_nodes, dtype=np.int32)
        self.right = np.zeros(n_nodes, dtype=np.int32)
        # depth + c(leaf size) at leaves; 0 while internal
        self.path_adjust = np.zeros(n_nodes, dtype=np.float32)


def _build_tree(X: np.ndarray, rng: np.random.Generator, max_depth: int) -> _TreeArrays:
    """Grow one isolation tree: uniform random feature + uniform random split
    between node min/max, until isolation or the height cap."""
    nodes: List[Tuple] = []  # (feature, threshold, left, right, path_adjust)

    def grow(idx: np.ndarray, depth: int) -> int:
        me = len(nodes)
        nodes.append(None)
        n = len(idx)
        if depth >= max_depth or n <= 1:
            nodes[me] = (0, 0.0, -1, -1, depth + c_factor(float(n)))
            return me
        sub = X[idx]
        lo, hi = sub.min(axis=0), sub.max(axis=0)
        usable = np.where(hi > lo)[0]
        if len(usable) == 0:  # all duplicate rows: treated as isolated
            nodes[me] = (0, 0.0, -1, -1, depth + c_factor(float(n)))
            return me
        f = int(usable[rng.integers(len(usable))])
        thr = float(rng.uniform(lo[f], hi[f]))
        mask = sub[:, f] < thr
        li = grow(idx[mask], depth + 1)
        ri = grow(idx[~mask], depth + 1)
        nodes[me] = (f, thr, li, ri, 0.0)
        return me

    grow(np.arange(len(X)), 0)
    t = _TreeArrays(len(nodes))
    for i, (f, thr, li, ri, adj) in enumerate(nodes):
        t.feature[i] = f
        t.threshold[i] = thr
        # leaves self-loop so the fixed-depth descent is a no-op afterwards
        t.left[i] = li if li >= 0 else i
        t.right[i] = ri if ri >= 0 else i
        t.path_adjust[i] = adj
    return t


@partial(jax.jit, static_argnames=("max_depth",))
def _path_lengths(features, thresholds, lefts, rights, adjusts, X, max_depth):
    """(n_trees, n_nodes) packed trees × (n_rows, d) -> (n_rows,) mean path
    length. Lockstep descent: max_depth rounds of gathers, no branching."""

    def one_tree(feat, thr, left, right, adjust):
        def descend(x):
            def step(_, node):
                f = feat[node]
                go_left = x[f] < thr[node]
                return jnp.where(go_left, left[node], right[node])

            node = jax.lax.fori_loop(0, max_depth, step, jnp.int32(0))
            return adjust[node]

        return jax.vmap(descend)(X)  # (n_rows,)

    paths = jax.vmap(one_tree)(features, thresholds, lefts, rights, adjusts)
    return paths.mean(axis=0)


class IsolationForest(HasFeaturesCol, HasPredictionCol, Estimator):
    """Param surface mirrors LinkedIn's ``IsolationForestParams``."""

    numEstimators = Param("Number of isolation trees", default=100,
                          converter=to_int, validator=gt(0))
    maxSamples = Param("Subsample size per tree (<=1.0: fraction of rows)",
                       default=256.0, converter=to_float, validator=gt(0))
    maxFeatures = Param("Feature subsample per tree (<=1.0: fraction)",
                        default=1.0, converter=to_float, validator=gt(0))
    bootstrap = Param("Sample with replacement", default=False)
    contamination = Param("Expected outlier fraction (0 = use scoreThreshold)",
                          default=0.0, converter=to_float)
    scoreThreshold = Param("Outlier score cut when contamination=0",
                           default=0.5, converter=to_float)
    scoreCol = Param("Output anomaly-score column", default="outlierScore",
                     converter=to_str)
    randomSeed = Param("RNG seed", default=1, converter=to_int)

    def __init__(self, **kwargs):
        kwargs.setdefault("predictionCol", "predictedLabel")
        super().__init__(**kwargs)

    def _fit(self, table: Table) -> "IsolationForestModel":
        X = np.asarray(table.column(self.getFeaturesCol()), dtype=np.float32)
        n, d = X.shape
        rng = np.random.default_rng(self.getRandomSeed())
        ms = self.getMaxSamples()
        sample_n = int(round(ms * n)) if ms <= 1.0 else int(ms)
        sample_n = max(2, min(sample_n, n))
        mf = self.getMaxFeatures()
        feat_n = int(round(mf * d)) if mf <= 1.0 else int(mf)
        feat_n = max(1, min(feat_n, d))
        max_depth = int(np.ceil(np.log2(sample_n)))

        trees: List[_TreeArrays] = []
        feat_maps: List[np.ndarray] = []
        for _ in range(self.getNumEstimators()):
            rows = (
                rng.integers(n, size=sample_n)
                if self.getBootstrap()
                else rng.choice(n, size=sample_n, replace=False)
            )
            feats = (
                np.arange(d)
                if feat_n == d
                else np.sort(rng.choice(d, size=feat_n, replace=False))
            )
            t = _build_tree(X[np.ix_(rows, feats)], rng, max_depth)
            # remap tree-local feature ids to global column ids
            t.feature = feats[t.feature].astype(np.int32)
            trees.append(t)
            feat_maps.append(feats)

        # pack to (n_trees, max_nodes); leaf self-loops pad safely
        max_nodes = max(len(t.feature) for t in trees)
        packed = {
            "feature": np.zeros((len(trees), max_nodes), dtype=np.int32),
            "threshold": np.zeros((len(trees), max_nodes), dtype=np.float32),
            "left": np.zeros((len(trees), max_nodes), dtype=np.int32),
            "right": np.zeros((len(trees), max_nodes), dtype=np.int32),
            "path_adjust": np.zeros((len(trees), max_nodes), dtype=np.float32),
        }
        for i, t in enumerate(trees):
            m = len(t.feature)
            packed["feature"][i, :m] = t.feature
            packed["threshold"][i, :m] = t.threshold
            packed["left"][i, :m] = t.left
            packed["right"][i, :m] = t.right
            packed["path_adjust"][i, :m] = t.path_adjust
            # pad nodes self-loop at node m-1's adjust (never reached)
            packed["left"][i, m:] = np.arange(m, max_nodes)
            packed["right"][i, m:] = np.arange(m, max_nodes)

        model = IsolationForestModel(
            featuresCol=self.getFeaturesCol(),
            predictionCol=self.getPredictionCol(),
            scoreCol=self.getScoreCol(),
            trees=packed,
            numSamples=sample_n,
            maxDepth=max_depth,
            outlierScoreThreshold=self.getScoreThreshold(),
        )
        if self.getContamination() > 0.0:
            scores = model._scores(X)
            thr = float(np.quantile(scores, 1.0 - self.getContamination()))
            model.set("outlierScoreThreshold", thr)
        model.parent = self
        return model


class IsolationForestModel(HasFeaturesCol, HasPredictionCol, Model):
    trees = Param("Packed tree arrays", is_complex=True, default=None)
    numSamples = Param("Per-tree subsample size", default=256)
    maxDepth = Param("Tree height cap", default=8)
    outlierScoreThreshold = Param("Score cut for predictedLabel", default=0.5)
    scoreCol = Param("Output anomaly-score column", default="outlierScore",
                     converter=to_str)

    def _scores(self, X: np.ndarray) -> np.ndarray:
        t = self.getTrees()
        mean_path = _path_lengths(
            jnp.asarray(t["feature"]),
            jnp.asarray(t["threshold"]),
            jnp.asarray(t["left"]),
            jnp.asarray(t["right"]),
            jnp.asarray(t["path_adjust"]),
            jnp.asarray(X, dtype=jnp.float32),
            self.getMaxDepth(),
        )
        cn = c_factor(float(self.getNumSamples()))
        return np.asarray(2.0 ** (-np.asarray(mean_path, dtype=np.float64) / cn))

    def transform(self, table: Table) -> Table:
        X = np.asarray(table.column(self.getFeaturesCol()), dtype=np.float32)
        scores = self._scores(X)
        labels = (scores >= self.getOutlierScoreThreshold()).astype(np.float64)
        return table.with_columns({
            self.getScoreCol(): scores,
            self.getPredictionCol(): labels,
        })
