"""Hyperparameter spaces (reference ``automl/HyperparamBuilder.scala:11-57``
and ``automl/DefaultHyperparams.scala:13``)."""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Sequence

import numpy as np


class Dist:
    """A distribution over one hyperparameter's values."""

    def get_next(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError


class DiscreteHyperParam(Dist):
    """Uniform over an explicit value list (``DiscreteHyperParam``)."""

    def __init__(self, values: Sequence[Any]):
        if not values:
            raise ValueError("DiscreteHyperParam needs at least one value")
        self.values = list(values)

    def get_next(self, rng: np.random.Generator) -> Any:
        return self.values[int(rng.integers(len(self.values)))]


class IntRangeHyperParam(Dist):
    """Uniform integer in [min, max) (``IntRangeHyperParam``)."""

    def __init__(self, min: int, max: int):
        if max <= min:
            raise ValueError(f"empty range [{min}, {max})")
        self.min, self.max = int(min), int(max)

    def get_next(self, rng: np.random.Generator) -> int:
        return int(rng.integers(self.min, self.max))


class DoubleRangeHyperParam(Dist):
    """Uniform float in [min, max) (``DoubleRangeHyperParam``)."""

    def __init__(self, min: float, max: float):
        if max <= min:
            raise ValueError(f"empty range [{min}, {max})")
        self.min, self.max = float(min), float(max)

    def get_next(self, rng: np.random.Generator) -> float:
        return float(rng.uniform(self.min, self.max))


class HyperparamBuilder:
    """Collects (param name, Dist) pairs into a :class:`RandomSpace`
    (``HyperparamBuilder`` + ``RandomSpace``)."""

    def __init__(self):
        self._dists: Dict[str, Dist] = {}

    def add_hyperparam(self, name: str, dist: Dist) -> "HyperparamBuilder":
        self._dists[name] = dist
        return self

    def build(self) -> "RandomSpace":
        return RandomSpace(self._dists)


class RandomSpace:
    """Samples param maps from per-param distributions (``RandomSpace``)."""

    def __init__(self, dists: Dict[str, Dist], seed: int = 0):
        self.dists = dict(dists)
        self.seed = seed

    def param_maps(self, n: int) -> Iterator[Dict[str, Any]]:
        rng = np.random.default_rng(self.seed)
        for _ in range(n):
            yield {k: d.get_next(rng) for k, d in self.dists.items()}


class GridSpace:
    """Exhaustive cross-product over discrete values (``GridSpace``)."""

    def __init__(self, grids: Dict[str, Sequence[Any]]):
        self.grids = {k: list(v) for k, v in grids.items()}

    def param_maps(self, n: int = -1) -> Iterator[Dict[str, Any]]:
        import itertools

        keys = list(self.grids)
        count = 0
        for combo in itertools.product(*(self.grids[k] for k in keys)):
            if 0 <= n <= count:
                return
            count += 1
            yield dict(zip(keys, combo))


class DefaultHyperparams:
    """Reasonable sweep ranges for the framework's learners
    (``automl/DefaultHyperparams.scala:13``)."""

    @staticmethod
    def lightgbm() -> Dict[str, Dist]:
        return {
            "numLeaves": DiscreteHyperParam([15, 31, 63]),
            "numIterations": DiscreteHyperParam([50, 100, 200]),
            "learningRate": DoubleRangeHyperParam(0.01, 0.3),
            "featureFraction": DoubleRangeHyperParam(0.6, 1.0),
        }

    @staticmethod
    def sgd() -> Dict[str, Dist]:
        # param names match the VW estimator surface (``l2``, not the
        # reference's ``l2Regularization`` — that drift made the space
        # unusable against the real estimators)
        return {
            "learningRate": DoubleRangeHyperParam(0.005, 0.5),
            "l2": DoubleRangeHyperParam(1e-8, 1e-2),
            "numPasses": DiscreteHyperParam([1, 3, 5]),
        }

    @staticmethod
    def vw() -> Dict[str, Dist]:
        """Text-learner space for the VW estimators: the vmapped lanes
        (``learningRate``/``powerT``/``l1``/``l2``) plus ``numPasses``,
        so a random draw shape-buckets into few compiled programs."""
        return {
            "learningRate": DoubleRangeHyperParam(0.05, 1.0),
            "powerT": DiscreteHyperParam([0.0, 0.5]),
            "l1": DiscreteHyperParam([0.0, 1e-6, 1e-4]),
            "l2": DoubleRangeHyperParam(1e-8, 1e-3),
            "numPasses": DiscreteHyperParam([1, 3, 5]),
        }
