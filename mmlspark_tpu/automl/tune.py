"""TuneHyperparameters / FindBestModel
(reference ``automl/TuneHyperparameters.scala:37`` and
``automl/FindBestModel.scala:55``).

Randomized search with k-fold cross validation; candidate fits run on a
bounded thread pool (``getExecutionContext``/future-per-paramMap,
``TuneHyperparameters.scala:95-187``). JAX releases the GIL during device
execution, so pool threads overlap host featurization with on-chip fits —
the role the reference's driver-side pool played for Spark jobs.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.params import HasLabelCol, Param, gt, to_int, to_str
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.train.statistics import ComputeModelStatistics

# metric name -> (output column of ComputeModelStatistics, higher is better, kind)
_METRICS: Dict[str, Tuple[str, bool, str]] = {
    "accuracy": ("accuracy", True, "classification"),
    "precision": ("precision", True, "classification"),
    "recall": ("recall", True, "classification"),
    "AUC": ("AUC", True, "classification"),
    "mse": ("mean_squared_error", False, "regression"),
    "rmse": ("root_mean_squared_error", False, "regression"),
    "mae": ("mean_absolute_error", False, "regression"),
    "r2": ("R^2", True, "regression"),
}


def _evaluate(scored: Table, label_col: str, metric: str) -> float:
    # The metric name fixes the task kind: 'auto' detection misclassifies
    # integer-valued regression targets (counts, ratings) as classification.
    col, _, kind = _METRICS[metric]
    stats = ComputeModelStatistics(
        labelCol=label_col, evaluationMetric=kind
    ).transform(scored)
    if col not in stats:
        raise ValueError(
            f"metric {metric!r} not produced — got columns {stats.columns}"
        )
    return float(stats.column(col)[0])


def _is_larger_better(metric: str) -> bool:
    return _METRICS[metric][1]


class TuneHyperparameters(HasLabelCol, Estimator):
    """Randomized hyperparameter search over one or more estimators with
    k-fold CV; best (estimator, param map) refitted on the full data."""

    models = Param("Estimators to sweep", is_complex=True)
    paramSpace = Param("Per-estimator dict of param Dists, or one shared dict",
                       is_complex=True, default=None)
    evaluationMetric = Param("Metric name", default="accuracy", converter=to_str,
                             validator=lambda v: v in _METRICS)
    numFolds = Param("CV folds", default=3, converter=to_int, validator=gt(1))
    numRuns = Param("Sampled param maps per estimator", default=10,
                    converter=to_int, validator=gt(0))
    parallelism = Param("Concurrent candidate fits", default=1, converter=to_int,
                        validator=gt(0))
    seed = Param("RNG seed (sampling + fold split)", default=0, converter=to_int)
    sweepMode = Param(
        "Candidate execution plane: 'auto' routes batchable candidates "
        "through the many-models sweep (shape-bucketed vmapped fits, "
        "mmlspark_tpu.sweep) and falls back to the thread pool on any "
        "error; 'batched' requires the sweep plane; 'threadpool' forces "
        "the sequential candidate-at-a-time baseline",
        default="auto", converter=to_str,
        validator=lambda v: v in ("auto", "batched", "threadpool"),
    )

    def _folds(self, n: int) -> List[np.ndarray]:
        rng = np.random.default_rng(self.getSeed())
        perm = rng.permutation(n)
        return np.array_split(perm, self.getNumFolds())

    def _cv_metric(self, est: Estimator, params: Dict[str, Any],
                   table: Table, folds: List[np.ndarray]) -> float:
        label_col = self.getLabelCol()
        metric = self.getEvaluationMetric()
        n = table.num_rows
        scores = []
        for fold in folds:
            mask = np.zeros(n, dtype=bool)
            mask[fold] = True
            train, valid = table.filter(~mask), table.filter(mask)
            model = est.copy(params).fit(train)
            scores.append(_evaluate(model.transform(valid), label_col, metric))
        return float(np.mean(scores))

    def _fit(self, table: Table) -> "TuneHyperparametersModel":
        estimators = self.getModels()
        if isinstance(estimators, Estimator):
            estimators = [estimators]
        if not estimators:
            raise ValueError("no estimators to tune")
        space = self.getParamSpace() or {}
        rng = np.random.default_rng(self.getSeed())
        folds = self._folds(table.num_rows)

        candidates: List[Tuple[Estimator, Dict[str, Any]]] = []
        for est in estimators:
            dists = space.get(est.uid, space) if space else {}
            # tolerate {param: Dist} directly or per-estimator nesting
            if dists and all(hasattr(d, "get_next") for d in dists.values()):
                for _ in range(self.getNumRuns()):
                    candidates.append(
                        (est, {k: d.get_next(rng) for k, d in dists.items()})
                    )
            else:
                candidates.append((est, {}))

        def run(cand: Tuple[Estimator, Dict[str, Any]]) -> float:
            est, params = cand
            return self._cv_metric(est, params, table, folds)

        metrics: Optional[List[float]] = None
        mode = self.getSweepMode()
        if mode in ("auto", "batched"):
            # many-models plane: per fold, candidates sharing a shape-
            # bucket fit K-at-once in one compiled program instead of
            # candidate-at-a-time (singleton buckets degrade to the same
            # per-candidate fit the thread pool would run)
            try:
                from mmlspark_tpu.sweep.batched import cv_metrics_batched

                metrics = cv_metrics_batched(
                    candidates, table, folds, self.getLabelCol(),
                    self.getEvaluationMetric(),
                )
            except Exception:
                if mode == "batched":
                    raise
                metrics = None  # auto: the thread-pool baseline still works
        if metrics is None:
            if self.getParallelism() > 1:
                with ThreadPoolExecutor(max_workers=self.getParallelism()) as pool:
                    metrics = list(pool.map(run, candidates))
            else:
                metrics = [run(c) for c in candidates]

        higher = _is_larger_better(self.getEvaluationMetric())
        # NaN metrics (single-class CV fold, constant labels) rank as worst,
        # never best; an all-NaN sweep is an error, not a silent winner.
        metrics_arr = np.asarray(metrics, dtype=np.float64)
        if np.isnan(metrics_arr).all():
            raise ValueError(
                "all candidate metrics are NaN — check folds/label distribution"
            )
        ranked = np.where(np.isnan(metrics_arr), -np.inf if higher else np.inf, metrics_arr)
        best_i = int(np.argmax(ranked) if higher else np.argmin(ranked))
        best_est, best_params = candidates[best_i]
        best_model = best_est.copy(best_params).fit(table)
        model = TuneHyperparametersModel(
            bestModel=best_model,
            bestMetric=float(metrics[best_i]),
            allMetrics=[float(m) for m in metrics],
            bestParams=best_params,
        )
        model.parent = self
        return model


class TuneHyperparametersModel(Model):
    bestModel = Param("Winning fitted model", is_complex=True, default=None)
    bestMetric = Param("Winning CV metric", default=float("nan"))
    allMetrics = Param("CV metric per candidate", default=None)
    bestParams = Param("Winning param map", default=None)

    def transform(self, table: Table) -> Table:
        return self.getBestModel().transform(table)


class FindBestModel(HasLabelCol, Estimator):
    """Evaluates already-fitted models on a dataset, keeps the best
    (``FindBestModel.scala:55-130``)."""

    models = Param("Fitted models (Transformers) to evaluate", is_complex=True)
    evaluationMetric = Param("Metric name", default="accuracy", converter=to_str,
                             validator=lambda v: v in _METRICS)

    def _fit(self, table: Table) -> "BestModel":
        models = self.getModels()
        if not models:
            raise ValueError("no trained models to evaluate")
        metric = self.getEvaluationMetric()
        label_col = self.getLabelCol()
        higher = _is_larger_better(metric)
        rows = []
        best_val, best_model, best_scored = None, None, None
        for m in models:
            scored = m.transform(table)
            val = _evaluate(scored, label_col, metric)
            rows.append((m.uid, val))
            if best_val is None or ((val > best_val) == higher and val != best_val):
                best_val, best_model, best_scored = val, m, scored
        model = BestModel(
            bestModel=best_model,
            bestModelMetrics=best_val,
            allModelMetrics=rows,
        )
        model.parent = self
        return model


class BestModel(Model):
    bestModel = Param("Winning transformer", is_complex=True, default=None)
    bestModelMetrics = Param("Winning metric value", default=float("nan"))
    allModelMetrics = Param("(uid, metric) per candidate", default=None)

    def transform(self, table: Table) -> Table:
        return self.getBestModel().transform(table)

    def get_evaluated_models(self) -> Table:
        rows = self.getAllModelMetrics() or []
        return Table({
            "model": np.array([r[0] for r in rows], dtype=object),
            "metric": np.array([r[1] for r in rows], dtype=np.float64),
        })
