"""AutoML (reference ``automl/``, SURVEY.md §2.13)."""

from mmlspark_tpu.automl.hyperparam import (
    DefaultHyperparams,
    DiscreteHyperParam,
    Dist,
    DoubleRangeHyperParam,
    GridSpace,
    HyperparamBuilder,
    IntRangeHyperParam,
    RandomSpace,
)
from mmlspark_tpu.automl.tune import (
    BestModel,
    FindBestModel,
    TuneHyperparameters,
    TuneHyperparametersModel,
)

__all__ = [
    "BestModel",
    "DefaultHyperparams",
    "DiscreteHyperParam",
    "Dist",
    "DoubleRangeHyperParam",
    "FindBestModel",
    "GridSpace",
    "HyperparamBuilder",
    "IntRangeHyperParam",
    "RandomSpace",
    "TuneHyperparameters",
    "TuneHyperparametersModel",
]
