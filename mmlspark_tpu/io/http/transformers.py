"""HTTP-on-Table transformers.

Reference: ``io/http/HTTPTransformer.scala:79-129`` (request column →
response column over partition-mapped async clients),
``io/http/SimpleHTTPTransformer.scala:64-166`` (input parser →
HTTPTransformer → output parser with optional error column),
``io/http/Parsers.scala:24-232`` (JSON/Custom input & output parsers),
``io/http/PartitionConsolidator.scala:17-132`` (funnel many partitions
through few shared clients).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.params import HasInputCol, HasOutputCol, Param, gt, to_float, to_int, to_str
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.io.http.clients import AsyncHTTPClient
from mmlspark_tpu.io.http.schema import HTTPRequestData, HTTPResponseData


class HTTPTransformer(HasInputCol, HasOutputCol, Transformer):
    """Column of :class:`HTTPRequestData` -> column of
    :class:`HTTPResponseData`, sent with bounded concurrency."""

    concurrency = Param("Max in-flight requests", default=8, converter=to_int,
                        validator=gt(0))
    timeout = Param("Per-request timeout seconds", default=60.0,
                    converter=to_float, validator=gt(0))

    def transform(self, table: Table) -> Table:
        client = AsyncHTTPClient(
            concurrency=self.getConcurrency(), timeout=self.getTimeout()
        )
        requests = list(table.column(self.getInputCol()))
        responses = client.send_all(requests)
        out = np.empty(len(responses), dtype=object)
        out[:] = responses
        return table.with_column(self.getOutputCol(), out)


class JSONInputParser(HasInputCol, HasOutputCol, Transformer):
    """Row value -> JSON POST :class:`HTTPRequestData`
    (``Parsers.scala:24-77``)."""

    url = Param("Target URL", converter=to_str)
    method = Param("HTTP method", default="POST", converter=to_str)
    headers = Param("Extra headers dict", default=None)

    def transform(self, table: Table) -> Table:
        col = table.column(self.getInputCol())
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col):
            payload = v
            if isinstance(v, np.ndarray):
                payload = v.tolist()
            out[i] = HTTPRequestData.from_json(
                self.getUrl(), payload, self.getMethod(), self.getHeaders()
            )
        return table.with_column(self.getOutputCol(), out)


class CustomInputParser(HasInputCol, HasOutputCol, Transformer):
    """UDF row -> request (``Parsers.scala:79-109``)."""

    udf = Param("value -> HTTPRequestData function", is_complex=True, default=None)

    def transform(self, table: Table) -> Table:
        fn: Callable[[Any], HTTPRequestData] = self.getUdf()
        col = table.column(self.getInputCol())
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col):
            out[i] = fn(v)
        return table.with_column(self.getOutputCol(), out)


class JSONOutputParser(HasInputCol, HasOutputCol, Transformer):
    """Response -> parsed JSON object column (``Parsers.scala:111-160``)."""

    def transform(self, table: Table) -> Table:
        col = table.column(self.getInputCol())
        out = np.empty(len(col), dtype=object)
        for i, resp in enumerate(col):
            out[i] = None if resp is None else resp.json()
        return table.with_column(self.getOutputCol(), out)


class StringOutputParser(HasInputCol, HasOutputCol, Transformer):
    """Response -> body text column (``Parsers.scala:162-189``)."""

    def transform(self, table: Table) -> Table:
        col = table.column(self.getInputCol())
        out = np.array([None if r is None else r.text() for r in col], dtype=object)
        return table.with_column(self.getOutputCol(), out)


class CustomOutputParser(HasInputCol, HasOutputCol, Transformer):
    """UDF response -> value (``Parsers.scala:191-232``)."""

    udf = Param("HTTPResponseData -> value function", is_complex=True, default=None)

    def transform(self, table: Table) -> Table:
        fn: Callable[[HTTPResponseData], Any] = self.getUdf()
        col = table.column(self.getInputCol())
        out = np.empty(len(col), dtype=object)
        for i, resp in enumerate(col):
            out[i] = None if resp is None else fn(resp)
        return table.with_column(self.getOutputCol(), out)


class SimpleHTTPTransformer(HasInputCol, HasOutputCol, Transformer):
    """inputParser -> HTTPTransformer -> outputParser, with failed rows
    (non-2xx) routed to ``errorCol`` instead of the output
    (``SimpleHTTPTransformer.scala:64-166``)."""

    inputParser = Param("Transformer producing HTTPRequestData", is_complex=True,
                        default=None)
    outputParser = Param("Transformer consuming HTTPResponseData", is_complex=True,
                         default=None)
    errorCol = Param("Error column name", default=None)
    concurrency = Param("Max in-flight requests", default=8, converter=to_int)
    timeout = Param("Per-request timeout seconds", default=60.0, converter=to_float)

    def transform(self, table: Table) -> Table:
        from mmlspark_tpu.data.table import find_unused_column_name

        req_col = find_unused_column_name("_request", table)
        resp_col = find_unused_column_name("_response", table)
        err_col = self.getErrorCol() or f"{self.getOutputCol()}_error"

        parser = self.getInputParser()
        if parser is None:
            raise ValueError("inputParser is required")
        parsed = parser.copy(
            {"inputCol": self.getInputCol(), "outputCol": req_col}
        ).transform(table)
        with_resp = HTTPTransformer(
            inputCol=req_col,
            outputCol=resp_col,
            concurrency=self.getConcurrency(),
            timeout=self.getTimeout(),
        ).transform(parsed)

        responses = with_resp.column(resp_col)
        errors = np.empty(len(responses), dtype=object)
        ok = np.empty(len(responses), dtype=object)
        for i, r in enumerate(responses):
            if r is not None and 200 <= r.status_code < 300:
                ok[i] = r
                errors[i] = None
            else:
                ok[i] = None
                errors[i] = None if r is None else f"HTTP {r.status_code}: {r.text()[:200]}"

        out_parser = self.getOutputParser() or JSONOutputParser()
        result = out_parser.copy(
            {"inputCol": resp_col, "outputCol": self.getOutputCol()}
        ).transform(with_resp.with_column(resp_col, ok))
        result = result.with_column(err_col, errors)
        return result.drop(req_col, resp_col)


class PartitionConsolidator(HasInputCol, HasOutputCol, Transformer):
    """Rate-limit-friendly funnel: all rows share one client with a global
    concurrency cap (``PartitionConsolidator.scala:17-132`` routed many
    partitions through few executor-shared connections; with columnar
    Tables the consolidation is the single shared AsyncHTTPClient)."""

    concurrency = Param("Global in-flight cap", default=1, converter=to_int,
                        validator=gt(0))
    timeout = Param("Per-request timeout seconds", default=60.0, converter=to_float)

    _shared: Dict[Tuple[int, float], AsyncHTTPClient] = {}

    def transform(self, table: Table) -> Table:
        # per-JVM SharedVariable analogue (io/http/SharedVariable.scala:65);
        # keyed by (concurrency, timeout) so a different timeout never
        # silently reuses another transformer's client.
        key = (self.getConcurrency(), self.getTimeout())
        client = self._shared.setdefault(
            key, AsyncHTTPClient(concurrency=key[0], timeout=key[1])
        )
        requests = list(table.column(self.getInputCol()))
        responses = client.send_all(requests)
        out = np.empty(len(responses), dtype=object)
        out[:] = responses
        return table.with_column(self.getOutputCol(), out)
