"""HTTP-on-Table (reference ``io/http/``, SURVEY.md §2.15)."""

from mmlspark_tpu.io.http.clients import AsyncHTTPClient, HTTPClient
from mmlspark_tpu.io.http.schema import (
    EntityData,
    HeaderData,
    HTTPRequestData,
    HTTPResponseData,
    StatusLineData,
)
from mmlspark_tpu.io.http.transformers import (
    CustomInputParser,
    CustomOutputParser,
    HTTPTransformer,
    JSONInputParser,
    JSONOutputParser,
    PartitionConsolidator,
    SimpleHTTPTransformer,
    StringOutputParser,
)

from mmlspark_tpu.io.http.forwarding import PortForwarder

__all__ = [
    "AsyncHTTPClient",
    "PortForwarder",
    "CustomInputParser",
    "CustomOutputParser",
    "EntityData",
    "HTTPClient",
    "HTTPRequestData",
    "HTTPResponseData",
    "HTTPTransformer",
    "HeaderData",
    "JSONInputParser",
    "JSONOutputParser",
    "PartitionConsolidator",
    "SimpleHTTPTransformer",
    "StatusLineData",
    "StringOutputParser",
]
