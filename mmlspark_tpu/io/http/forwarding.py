"""Local TCP port forwarding.

Reference: ``io/http/PortForwarding.scala`` — jsch SSH tunnels so serving
endpoints behind VNETs are reachable from the driver. The SSH transport is
explicitly descoped here (no ssh client dependency, and TPU-VM meshes talk
over plain ICI/DCN); what survives is the in-cluster use case: a plain
socket relay that forwards a local port to a remote host:port, so a driver
process can expose a worker's serving endpoint under its own address
(the ``forwardToServer`` pattern minus the SSH hop).
"""

from __future__ import annotations

import socket
import threading
from typing import Optional


class PortForwarder:
    """Forward connections on a local port to ``remote_host:remote_port``
    with bidirectional byte relays (one daemon thread per direction)."""

    def __init__(
        self,
        remote_host: str,
        remote_port: int,
        local_host: str = "127.0.0.1",
        local_port: int = 0,
        backlog: int = 32,
    ):
        self.remote = (remote_host, int(remote_port))
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((local_host, int(local_port)))
        self._listener.listen(backlog)
        self.local_host, self.local_port = self._listener.getsockname()
        self._stopping = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.local_host}:{self.local_port}/"

    @staticmethod
    def _relay(src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            # Half-close only: EOF on this direction must not tear down the
            # opposite relay (a client finishing its request still awaits
            # the response on the other leg).
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            try:
                upstream = socket.create_connection(self.remote, timeout=10)
            except OSError:
                client.close()
                continue
            threading.Thread(
                target=self._relay, args=(client, upstream), daemon=True
            ).start()
            threading.Thread(
                target=self._relay, args=(upstream, client), daemon=True
            ).start()

    def start(self) -> "PortForwarder":
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopping.set()
        try:
            self._listener.close()
        except OSError:
            pass

    def __enter__(self) -> "PortForwarder":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
