"""HTTP client stack: pooled sync client + bounded-concurrency async client
with status-aware retry/backoff.

Reference: ``io/http/Clients.scala`` (``BaseClient``/``AsyncClient`` with
bounded-concurrency futures, ``:63``), ``io/http/HTTPClients.scala``
(``HTTPClient`` pooled connections ``:26-62``; ``HandlingUtils.advanced``
retry handler honoring ``Retry-After`` on 429, ``:64-151``).

urllib-based (stdlib); connection pooling comes from keep-alive handled by
the OS — the concurrency lever here is the thread pool, mirroring the
reference's future pool per partition.
"""

from __future__ import annotations

import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable, List, Optional, Sequence

from mmlspark_tpu.io.http.schema import (
    EntityData,
    HeaderData,
    HTTPRequestData,
    HTTPResponseData,
    StatusLineData,
)

RETRY_STATUSES = (408, 429, 500, 502, 503, 504)


def _do_request(request: HTTPRequestData, timeout: float) -> HTTPResponseData:
    req = urllib.request.Request(
        request.url,
        data=request.entity.content if request.entity else None,
        headers=request.header_map(),
        method=request.method,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read()
            return HTTPResponseData(
                statusLine=StatusLineData("HTTP/1.1", resp.status, resp.reason or ""),
                headers=[HeaderData(k, v) for k, v in resp.headers.items()],
                entity=EntityData(content=body,
                                  contentType=resp.headers.get("Content-Type")),
            )
    except urllib.error.HTTPError as e:
        body = e.read() if hasattr(e, "read") else b""
        return HTTPResponseData(
            statusLine=StatusLineData("HTTP/1.1", e.code, str(e.reason)),
            headers=[HeaderData(k, v) for k, v in (e.headers or {}).items()],
            entity=EntityData(content=body),
        )


class HTTPClient:
    """Synchronous client with ``HandlingUtils.advanced`` retry semantics:
    retry on transport errors and retryable statuses with exponential
    backoff, honoring ``Retry-After`` on 429
    (``io/http/HTTPClients.scala:73-138``)."""

    def __init__(self, retries: Sequence[float] = (0.1, 0.5, 1.0),
                 timeout: float = 60.0):
        self.retries = list(retries)
        self.timeout = timeout

    def send(self, request: HTTPRequestData) -> HTTPResponseData:
        last: Optional[HTTPResponseData] = None
        for attempt in range(len(self.retries) + 1):
            try:
                resp = _do_request(request, self.timeout)
            except Exception as e:  # transport error (conn refused, timeout)
                if attempt >= len(self.retries):
                    raise
                time.sleep(self.retries[attempt])
                continue
            if resp.status_code not in RETRY_STATUSES or attempt >= len(self.retries):
                return resp
            last = resp
            wait = self.retries[attempt]
            if resp.status_code == 429:
                retry_after = resp.header_map().get("Retry-After")
                if retry_after is not None:
                    try:
                        wait = max(wait, float(retry_after))
                    except ValueError:
                        pass
            time.sleep(wait)
        return last  # pragma: no cover


class AsyncHTTPClient:
    """Bounded-concurrency batch sender (``AsyncClient``,
    ``io/http/Clients.scala:63``): N in-flight requests, results in input
    order. ``None`` requests pass through as ``None`` (null rows)."""

    def __init__(self, concurrency: int = 8,
                 retries: Sequence[float] = (0.1, 0.5, 1.0),
                 timeout: float = 60.0):
        if concurrency < 1:
            raise ValueError("concurrency must be >= 1")
        self.concurrency = concurrency
        self._client = HTTPClient(retries=retries, timeout=timeout)

    def send_all(
        self, requests: Iterable[Optional[HTTPRequestData]]
    ) -> List[Optional[HTTPResponseData]]:
        requests = list(requests)
        if not requests:
            return []
        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            return list(
                pool.map(
                    lambda r: None if r is None else self._client.send(r),
                    requests,
                )
            )
