"""HTTP client stack: pooled sync client + bounded-concurrency async client
with status-aware retry/backoff behind the shared resilience layer.

Reference: ``io/http/Clients.scala`` (``BaseClient``/``AsyncClient`` with
bounded-concurrency futures, ``:63``), ``io/http/HTTPClients.scala``
(``HTTPClient`` pooled connections ``:26-62``; ``HandlingUtils.advanced``
retry handler honoring ``Retry-After``, ``:64-151``).

The retry loop itself now lives in
:class:`~mmlspark_tpu.resilience.policy.RetryPolicy` (one loop for the
whole codebase); this module adds the wire specifics the policy can't
know:

- a **per-host circuit breaker** consulted before every attempt — under a
  down dependency the attempts stop locally (:class:`BreakerOpenError`)
  instead of storming it, and half-open probes re-detect recovery;
- **deadline propagation**: the ambient
  :class:`~mmlspark_tpu.resilience.budget.Deadline` caps the socket
  timeout and rides outbound as ``X-Deadline-Ms``, so a downstream hop
  knows how much budget the caller has left;
- ``Retry-After`` honored on 503 as well as 429, including HTTP-date
  values, and retry exhaustion on a retryable status returns the last
  response **with a warning log** (the old silent ``return last``
  fall-through hid every terminal 5xx);
- seeded **HTTP fault injection** (``FaultPlan.http_storm`` et al.) is
  enacted here, before the socket, so chaos tests run with no server.

urllib-based (stdlib); connection pooling comes from keep-alive handled by
the OS — the concurrency lever here is the thread pool, mirroring the
reference's future pool per partition.
"""

from __future__ import annotations

import logging
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterable, List, Optional, Sequence

from mmlspark_tpu.io.http.schema import (
    EntityData,
    HeaderData,
    HTTPRequestData,
    HTTPResponseData,
    StatusLineData,
)
from mmlspark_tpu.resilience.breaker import (
    BreakerOpenError,
    BreakerRegistry,
    shared_breakers,
)
from mmlspark_tpu.resilience.budget import (
    DEADLINE_HEADER,
    DeadlineExceededError,
    RetryBudget,
    current_deadline,
)
from mmlspark_tpu.resilience.policy import RETRY_STATUSES, RetryPolicy

logger = logging.getLogger("mmlspark_tpu.io.http")

#: statuses a breaker counts as dependency failure — 429 is the dependency
#: *protecting itself* (it is up), so throttles never trip a breaker
BREAKER_FAILURE_STATUSES = (408, 500, 502, 503, 504)


def _injected_fault(url: str):
    """Enact any ambient HTTP fault directive for this request. Returns a
    synthetic response (storm), None (no fault / after a delay), or raises
    (reset)."""
    from mmlspark_tpu.runtime.faults import current_faults

    plan = current_faults()
    if plan is None:
        return None
    directive = plan.apply_on_http(url)
    if directive is None:
        return None
    kind = directive["kind"]
    if kind == "reset":
        raise ConnectionResetError(f"injected connection reset for {url}")
    if kind == "delay":
        import time

        time.sleep(directive["seconds"])
        return None
    headers = []
    if directive.get("retry_after") is not None:
        headers.append(HeaderData("Retry-After", str(directive["retry_after"])))
    return HTTPResponseData(
        statusLine=StatusLineData(
            "HTTP/1.1", directive["status"], "injected fault"
        ),
        headers=headers,
        entity=EntityData(content=b'{"error": "injected fault"}'),
    )


def _do_request(
    request: HTTPRequestData,
    timeout: float,
    extra_headers: Optional[Dict[str, str]] = None,
) -> HTTPResponseData:
    fault = _injected_fault(request.url)
    if fault is not None:
        return fault
    # net chaos sits BELOW the storm layer: storms answer without a
    # socket, net directives degrade the socket itself (unreachable,
    # stalled, timed out) or garble the bytes that come back
    from mmlspark_tpu.runtime.faults import check_net

    net = check_net(request.url)
    headers = request.header_map()
    if extra_headers:
        headers.update(extra_headers)
    req = urllib.request.Request(
        request.url,
        data=request.entity.content if request.entity else None,
        headers=headers,
        method=request.method,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = resp.read()
            if net is not None and net.get("kind") == "corrupt":
                from mmlspark_tpu.runtime.netchaos import corrupt_bytes

                body = corrupt_bytes(body)
            return HTTPResponseData(
                statusLine=StatusLineData("HTTP/1.1", resp.status, resp.reason or ""),
                headers=[HeaderData(k, v) for k, v in resp.headers.items()],
                entity=EntityData(content=body,
                                  contentType=resp.headers.get("Content-Type")),
            )
    except urllib.error.HTTPError as e:
        body = e.read() if hasattr(e, "read") else b""
        return HTTPResponseData(
            statusLine=StatusLineData("HTTP/1.1", e.code, str(e.reason)),
            headers=[HeaderData(k, v) for k, v in (e.headers or {}).items()],
            entity=EntityData(content=body),
        )


class HTTPClient:
    """Synchronous client with ``HandlingUtils.advanced`` retry semantics
    behind the resilience layer: per-host breaker, retry budget, ambient
    deadline, ``Retry-After`` on 429/503 (delta-seconds or HTTP-date).

    ``retries`` keeps the legacy fixed-wait schedule; pass ``policy`` for
    seeded full-jitter backoff. ``breakers=None`` disables the breaker
    (unit tests of pure retry behavior); the default is the process-shared
    per-host registry."""

    def __init__(
        self,
        retries: Sequence[float] = (0.1, 0.5, 1.0),
        timeout: float = 60.0,
        policy: Optional[RetryPolicy] = None,
        budget: Optional[RetryBudget] = None,
        breakers: Optional[BreakerRegistry] = "shared",  # type: ignore[assignment]
    ):
        self.timeout = timeout
        self.policy = policy or RetryPolicy.from_legacy_waits(
            retries, retry_statuses=RETRY_STATUSES
        )
        if budget is not None:
            self.policy.budget = budget
        self.breakers: Optional[BreakerRegistry] = (
            shared_breakers() if breakers == "shared" else breakers
        )

    def send(self, request: HTTPRequestData) -> HTTPResponseData:
        policy = self.policy
        if policy.budget is not None:
            policy.budget.record_request()
        breaker = (
            self.breakers.for_url(request.url)
            if self.breakers is not None else None
        )
        last: Optional[HTTPResponseData] = None
        last_exc: Optional[Exception] = None
        attempt = 0
        while True:
            dl = current_deadline()
            if dl is not None and dl.expired:
                raise DeadlineExceededError(
                    f"deadline expired before attempt {attempt + 1} to "
                    f"{request.url}"
                )
            if breaker is not None and not breaker.allow():
                raise BreakerOpenError(
                    breaker.name, retry_after=breaker.retry_after()
                )
            extra = None
            timeout = self.timeout
            if dl is not None:
                # forward the remaining budget; cap the socket wait to it
                extra = {DEADLINE_HEADER: dl.to_header()}
                timeout = max(1e-3, min(self.timeout, dl.remaining()))
            resp: Optional[HTTPResponseData] = None
            try:
                resp = _do_request(request, timeout, extra_headers=extra)
            except Exception as e:  # transport error (conn refused/reset/timeout)
                last_exc = e
                logger.debug(
                    "transport error on %s (%s: %s)",
                    request.url, type(e).__name__, e,
                )
                if breaker is not None:
                    breaker.record_failure()
            else:
                last_exc = None
                if breaker is not None:
                    if resp.status_code in BREAKER_FAILURE_STATUSES:
                        breaker.record_failure()
                    else:
                        breaker.record_success()
                if not policy.retryable(resp.status_code):
                    return resp
                last = resp
            if not policy.allow_retry(attempt):
                break
            wait = policy.next_wait(
                attempt,
                status=resp.status_code if resp is not None else None,
                headers=resp.header_map() if resp is not None else None,
            )
            policy.sleep(wait)
            attempt += 1
        if last_exc is not None:
            raise last_exc
        assert last is not None
        # terminal retryable status: return it LOUDLY (the old code fell
        # through to a silent `return last`)
        logger.warning(
            "giving up on %s %s after %d attempts: terminal HTTP %d",
            request.method, request.url, attempt + 1, last.status_code,
        )
        return last


class AsyncHTTPClient:
    """Bounded-concurrency batch sender (``AsyncClient``,
    ``io/http/Clients.scala:63``): N in-flight requests, results in input
    order. ``None`` requests pass through as ``None`` (null rows). A call
    rejected by an open breaker degrades to a synthetic local 503 carrying
    ``Retry-After`` — error-column semantics, not a crashed batch."""

    def __init__(self, concurrency: int = 8,
                 retries: Sequence[float] = (0.1, 0.5, 1.0),
                 timeout: float = 60.0,
                 policy: Optional[RetryPolicy] = None,
                 budget: Optional[RetryBudget] = None,
                 breakers: Optional[BreakerRegistry] = "shared"):  # type: ignore[assignment]
        if concurrency < 1:
            raise ValueError("concurrency must be >= 1")
        self.concurrency = concurrency
        self._client = HTTPClient(
            retries=retries, timeout=timeout, policy=policy, budget=budget,
            breakers=breakers,
        )

    def _send_one(
        self, request: Optional[HTTPRequestData]
    ) -> Optional[HTTPResponseData]:
        if request is None:
            return None
        try:
            return self._client.send(request)
        except BreakerOpenError as e:
            return HTTPResponseData(
                statusLine=StatusLineData("HTTP/1.1", 503, "breaker open"),
                headers=[HeaderData("Retry-After", f"{e.retry_after:.3f}")],
                entity=EntityData(content=(
                    b'{"error": "circuit breaker open"}'
                )),
            )

    def send_all(
        self, requests: Iterable[Optional[HTTPRequestData]]
    ) -> List[Optional[HTTPResponseData]]:
        requests = list(requests)
        if not requests:
            return []
        with ThreadPoolExecutor(max_workers=self.concurrency) as pool:
            return list(pool.map(self._send_one, requests))
