"""HTTP request/response as typed records.

Reference: ``io/http/HTTPSchema.scala`` (``HTTPRequestData:162``,
``HTTPResponseData:90``, ``HeaderData:26``, ``EntityData:38``,
``StatusLineData:76`` — full HTTP messages as Spark StructTypes via
SparkBindings). Here they are plain dataclasses stored in object columns;
the Table analogue of the struct columns.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class HeaderData:
    name: str
    value: str


@dataclass
class EntityData:
    content: bytes = b""
    contentType: Optional[str] = None
    contentEncoding: Optional[str] = None
    isChunked: bool = False
    isRepeatable: bool = True
    isStreaming: bool = False

    def text(self, encoding: str = "utf-8") -> str:
        return self.content.decode(encoding, errors="replace")

    def json(self):
        return json.loads(self.text())


@dataclass
class StatusLineData:
    protocolVersion: str
    statusCode: int
    reasonPhrase: str


@dataclass
class HTTPRequestData:
    """One HTTP request (``HTTPRequestData`` case class)."""

    url: str
    method: str = "GET"
    headers: List[HeaderData] = field(default_factory=list)
    entity: Optional[EntityData] = None

    @classmethod
    def from_json(cls, url: str, payload, method: str = "POST",
                  headers: Optional[Dict[str, str]] = None) -> "HTTPRequestData":
        """Row -> JSON POST (the ``JSONInputParser`` construction,
        ``io/http/Parsers.scala:24-77``)."""
        hdrs = [HeaderData(k, v) for k, v in (headers or {}).items()]
        hdrs.append(HeaderData("Content-Type", "application/json"))
        body = json.dumps(payload).encode("utf-8")
        return cls(url=url, method=method, headers=hdrs,
                   entity=EntityData(content=body, contentType="application/json"))

    def header_map(self) -> Dict[str, str]:
        return {h.name: h.value for h in self.headers}


@dataclass
class HTTPResponseData:
    """One HTTP response (``HTTPResponseData`` case class)."""

    statusLine: StatusLineData
    headers: List[HeaderData] = field(default_factory=list)
    entity: Optional[EntityData] = None

    @property
    def status_code(self) -> int:
        return self.statusLine.statusCode

    def header_map(self) -> Dict[str, str]:
        return {h.name: h.value for h in self.headers}

    def text(self) -> str:
        return self.entity.text() if self.entity else ""

    def json(self):
        return self.entity.json() if self.entity else None
