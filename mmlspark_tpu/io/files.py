"""File ingest: (path, bytes) tables and decoded image tables.

Re-design of ``io/binary/BinaryFileFormat.scala:34-189`` (Hadoop binary file
source with zip inspection + subsampling) and ``io/image/ImageUtils.scala``
(decode helpers) as host-side readers producing columnar Tables.
"""

from __future__ import annotations

import fnmatch
import io as _stdlib_io
import logging
import os
import zipfile
from typing import List, Optional, Tuple

import numpy as np

from mmlspark_tpu.data.table import Table

logger = logging.getLogger("mmlspark_tpu.io")


def _walk(path: str, recursive: bool, pattern: Optional[str]) -> List[str]:
    if os.path.isfile(path):
        return [path]
    out: List[str] = []
    if recursive:
        for root, _, files in os.walk(path):
            for f in sorted(files):
                out.append(os.path.join(root, f))
    else:
        for f in sorted(os.listdir(path)):
            full = os.path.join(path, f)
            if os.path.isfile(full):
                out.append(full)
    if pattern:
        out = [p for p in out if fnmatch.fnmatch(os.path.basename(p), pattern)]
    return out


def read_binary_files(
    path: str,
    recursive: bool = True,
    sample_ratio: float = 1.0,
    inspect_zip: bool = True,
    seed: int = 0,
    pattern: Optional[str] = None,
) -> Table:
    """Directory/file -> Table[path, bytes]. Zip members become rows with
    ``path!entry`` naming, like the reference's zip inspection."""
    paths = _walk(path, recursive, pattern)
    rng = np.random.default_rng(seed)
    names: List[str] = []
    blobs: List[bytes] = []
    for p in paths:
        if inspect_zip and zipfile.is_zipfile(p):
            with zipfile.ZipFile(p) as zf:
                for entry in zf.namelist():
                    if entry.endswith("/"):
                        continue
                    if sample_ratio < 1.0 and rng.random() > sample_ratio:
                        continue
                    names.append(f"{p}!{entry}")
                    blobs.append(zf.read(entry))
        else:
            if sample_ratio < 1.0 and rng.random() > sample_ratio:
                continue
            names.append(p)
            with open(p, "rb") as f:
                blobs.append(f.read())
    byte_col = np.empty(len(blobs), dtype=object)
    for i, b in enumerate(blobs):
        byte_col[i] = b
    return Table({"path": np.array(names, dtype=object), "bytes": byte_col})


def decode_image(data: bytes) -> Optional[np.ndarray]:
    """bytes -> HWC uint8 array (RGB), or None when undecodable —
    the reference emits null-image rows rather than failing the job."""
    try:
        from PIL import Image

        with Image.open(_stdlib_io.BytesIO(data)) as im:
            return np.asarray(im.convert("RGB"))
    except Exception as e:
        # PIL raises a zoo of per-codec errors; null-row semantics want
        # them all, but not silently.
        logger.debug("undecodable image (%s: %s)", type(e).__name__, e)
        return None


def read_images(
    path: str,
    recursive: bool = True,
    sample_ratio: float = 1.0,
    drop_invalid: bool = True,
    seed: int = 0,
    pattern: Optional[str] = None,
) -> Table:
    """Directory -> Table[path, image] with HWC uint8 RGB image arrays."""
    files = read_binary_files(
        path, recursive=recursive, sample_ratio=sample_ratio, seed=seed,
        pattern=pattern,
    )
    images = [decode_image(b) for b in files.column("bytes")]
    keep = [i for i, im in enumerate(images) if im is not None or not drop_invalid]
    image_col = np.empty(len(keep), dtype=object)
    for j, i in enumerate(keep):
        image_col[j] = images[i]
    return Table(
        {
            "path": files.column("path")[keep],
            "image": image_col,
        }
    )
