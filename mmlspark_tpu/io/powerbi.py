"""PowerBI streaming-dataset writer.

Reference: ``io/powerbi/PowerBIWriter.scala:27-114`` — rows POSTed to a
PowerBI push-dataset REST URL in batches, with the client-stack backoff
(429 ``Retry-After`` honored by :class:`HTTPClient`). The reference wires
this as a DataFrameWriter format; here it is a plain writer function plus
a Transformer wrapper so it composes into pipelines.
"""

from __future__ import annotations

import json
from typing import List, Optional, Sequence

from mmlspark_tpu.core.params import Param, gt, to_int, to_str
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.io.http.clients import HTTPClient
from mmlspark_tpu.io.http.schema import EntityData, HeaderData, HTTPRequestData


from mmlspark_tpu.data.table import row_as_json_dict as _row_dict  # noqa: E402


def write_to_powerbi(
    table: Table,
    url: str,
    batch_size: int = 100,
    retries: Sequence[float] = (0.2, 0.8, 3.2),
    client: Optional[HTTPClient] = None,
) -> List[int]:
    """POST the table to a PowerBI push URL in ``batch_size`` chunks of
    ``[{row}, ...]`` JSON arrays (the body shape PowerBI's REST API takes).
    Returns the per-batch status codes; raises on the first non-2xx after
    the retry budget."""
    client = client or HTTPClient(retries=retries)
    statuses: List[int] = []
    n = table.num_rows
    for start in range(0, n, batch_size):
        rows = [_row_dict(table, r) for r in range(start, min(start + batch_size, n))]
        resp = client.send(
            HTTPRequestData(
                url=url,
                method="POST",
                headers=[HeaderData("Content-Type", "application/json")],
                entity=EntityData(
                    content=json.dumps(rows).encode("utf-8"),
                    contentType="application/json",
                ),
            )
        )
        if resp.status_code // 100 != 2:
            raise RuntimeError(
                f"PowerBI write failed at batch {start // batch_size}: "
                f"HTTP {resp.status_code} {resp.text()[:200]}"
            )
        statuses.append(resp.status_code)
    return statuses


class PowerBIWriter(Transformer):
    """Pipeline-stage wrapper: passes the table through unchanged after
    pushing it (the streaming-sink usage of ``PowerBIWriter.scala``)."""

    url = Param("PowerBI push-dataset URL", default=None, converter=to_str)
    batchSize = Param("Rows per POST", default=100, converter=to_int, validator=gt(0))

    def transform(self, table: Table) -> Table:
        if not self.getUrl():
            raise ValueError("PowerBIWriter requires url")
        write_to_powerbi(table, self.getUrl(), batch_size=self.getBatchSize())
        return table
