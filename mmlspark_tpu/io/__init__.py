"""IO: binary/image file ingest and (later) HTTP client/serving stacks
(reference ``io/`` — SURVEY.md §2.5, §2.15, §2.16)."""

from mmlspark_tpu.io.files import read_binary_files, read_images

__all__ = ["read_binary_files", "read_images"]
