"""IO: binary/image file ingest and (later) HTTP client/serving stacks
(reference ``io/`` — SURVEY.md §2.5, §2.15, §2.16)."""

from mmlspark_tpu.io.files import read_binary_files, read_images
from mmlspark_tpu.io.powerbi import PowerBIWriter, write_to_powerbi

__all__ = ["PowerBIWriter", "read_binary_files", "read_images", "write_to_powerbi"]
