"""Mini-batching transformers (``stages/MiniBatchTransformer.scala:43-174``,
``stages/Batchers.scala:12-131``).

In the reference these exist to amortize JNI dispatch: rows are grouped so
one native call evaluates many rows. On TPU the same batching amortizes XLA
dispatch and fills the MXU — `DNNModel` turns each batched row into one
device step. A batched Table column is an object array whose elements are
the per-batch arrays (ragged in the last batch).

The reference's background-thread iterator machinery (`Batchers.scala`)
disappears: batching a columnar Table is pure slicing.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

from mmlspark_tpu.core.params import Param, gt, to_bool, to_int
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.core.schema import ColType, require_column
from mmlspark_tpu.data.table import Table


def _batch_bounds(n: int, sizes: List[int]) -> List[tuple]:
    bounds, lo = [], 0
    i = 0
    while lo < n:
        size = sizes[min(i, len(sizes) - 1)]
        bounds.append((lo, min(lo + size, n)))
        lo += size
        i += 1
    return bounds


class _MiniBatchBase(Transformer):
    """Shared schema rule for the batchers: every column keeps its name but
    becomes an object column whose elements are the per-batch arrays."""

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return {name: ColType(np.dtype(object)) for name in schema}


def _batch_table(table: Table, bounds: List[tuple]) -> Table:
    from mmlspark_tpu.observability.events import BatchFormed, get_bus

    cols: Dict[str, np.ndarray] = {}
    for name in table.columns:
        col = table.column(name)
        out = np.empty(len(bounds), dtype=object)
        for i, (lo, hi) in enumerate(bounds):
            out[i] = col[lo:hi]
        cols[name] = out
    bus = get_bus()
    if bus.active:
        for i, (lo, hi) in enumerate(bounds):
            bus.publish(BatchFormed(epoch=i, size=hi - lo))
    batched = Table(cols)
    batched.num_partitions = table.num_partitions
    return batched


class FixedMiniBatchTransformer(_MiniBatchBase):
    """Group every ``batchSize`` consecutive rows into one batch row
    (``stages/MiniBatchTransformer.scala:139``)."""

    batchSize = Param("Rows per batch", default=10, converter=to_int, validator=gt(0))
    maxBufferSize = Param(
        "Kept for parity; columnar batching needs no buffer", default=-1,
        converter=to_int,
    )
    buffered = Param("Kept for parity (background buffering thread)",
                     default=False, converter=to_bool)

    def transform(self, table: Table) -> Table:
        return _batch_table(
            table, _batch_bounds(table.num_rows, [self.getBatchSize()])
        )


class DynamicMiniBatchTransformer(_MiniBatchBase):
    """Batch whatever is available, up to ``maxBatchSize``
    (``stages/MiniBatchTransformer.scala:43``). Without a streaming queue the
    whole partition is 'available': each logical partition becomes one batch,
    capped at ``maxBatchSize`` rows."""

    maxBatchSize = Param(
        "Maximum rows per batch", default=2**31 - 1, converter=to_int, validator=gt(0)
    )

    def transform(self, table: Table) -> Table:
        cap = self.getMaxBatchSize()
        bounds: List[tuple] = []
        for lo, hi in table.partition_bounds():
            while lo < hi:
                bounds.append((lo, min(lo + cap, hi)))
                lo += cap
        return _batch_table(table, bounds)


class TimeIntervalMiniBatchTransformer(_MiniBatchBase):
    """Batch rows arriving within ``millisToWait`` of each other
    (``stages/MiniBatchTransformer.scala:95``). Materialized Tables have no
    arrival times; an explicit ``timestampCol`` (epoch millis) partitions rows
    into interval-gap batches, else one batch per partition."""

    millisToWait = Param(
        "Interval in milliseconds", default=1000, converter=to_int, validator=gt(0)
    )
    maxBatchSize = Param(
        "Maximum rows per batch", default=2**31 - 1, converter=to_int, validator=gt(0)
    )
    timestampCol = Param("Optional epoch-millis column defining arrival times",
                         default=None)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        ts_col = self.getTimestampCol()
        if ts_col is not None:
            require_column(schema, ts_col, type(self).__name__, numeric=True)
        return super().transform_schema(schema)

    def transform(self, table: Table) -> Table:
        cap = self.getMaxBatchSize()
        ts_col = self.getTimestampCol()
        bounds: List[tuple] = []
        if ts_col is not None:
            ts = table.column(ts_col).astype(np.int64)
            lo = 0
            for i in range(1, table.num_rows + 1):
                boundary = (
                    i == table.num_rows
                    or ts[i] - ts[i - 1] > self.getMillisToWait()
                    or i - lo >= cap
                )
                if boundary:
                    bounds.append((lo, i))
                    lo = i
        else:
            for lo, hi in table.partition_bounds():
                while lo < hi:
                    bounds.append((lo, min(lo + cap, hi)))
                    lo += cap
        return _batch_table(table, bounds)


class FlattenBatch(Transformer):
    """Invert mini-batching: explode every batched column back to one row per
    element (``stages/MiniBatchTransformer.scala:159``)."""

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        # batched (object) columns re-flatten to their element dtype, which
        # is data-dependent; non-object columns are repeated unchanged
        out: Dict[str, Any] = {}
        for name, col in schema.items():
            dtype = getattr(col, "dtype", None)
            out[name] = ColType() if dtype == np.dtype(object) else col
        return out

    def transform(self, table: Table) -> Table:
        if table.num_rows == 0:
            return table
        lengths = None
        for name in table.columns:
            col = table.column(name)
            if col.dtype == object:
                lens = np.array([len(v) for v in col], dtype=np.int64)
                if lengths is None:
                    lengths = lens
                elif not np.array_equal(lengths, lens):
                    raise ValueError(
                        f"batched column {name!r} lengths disagree with other columns"
                    )
        if lengths is None:
            raise ValueError("no batched (object) columns to flatten")
        cols: Dict[str, Any] = {}
        for name in table.columns:
            col = table.column(name)
            if col.dtype == object:
                parts = [np.asarray(v) for v in col]
                if any(p.dtype == object or p.ndim == 0 for p in parts):
                    flat: List[Any] = []
                    for v in col:
                        flat.extend(list(v))
                    cols[name] = flat
                else:
                    cols[name] = np.concatenate(parts)
            else:
                cols[name] = np.repeat(col, lengths, axis=0)
        out = Table(cols)
        out.num_partitions = table.num_partitions
        return out
