"""Column/row manipulation stages (reference ``stages/``, 19 files — SURVEY.md §2.11).

Each class re-designs one reference transformer for the columnar Table:
row-wise UDF loops become whole-column numpy/JAX operations, and Spark
repartitioning becomes logical partition hints consumed by the mesh
data-parallel shard mapping.
"""

from __future__ import annotations

import logging
import time
import unicodedata
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.params import (
    HasInputCol,
    HasInputCols,
    HasLabelCol,
    HasOutputCol,
    HasOutputCols,
    Param,
    ge,
    gt,
    one_of,
    to_bool,
    to_int,
    to_list_str,
    to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.core.schema import ColType, add_column, require_column
from mmlspark_tpu.data.table import Table

logger = logging.getLogger("mmlspark_tpu.stages")


class Cacher(Transformer):
    """Materialization point (``stages/Cacher.scala``). Tables are already
    host-materialized, so this is an explicit no-op kept for pipeline parity."""

    disable = Param("If true, do not cache", default=False, converter=to_bool)

    def transform(self, table: Table) -> Table:
        return table


class DropColumns(Transformer):
    """Drop the listed columns (``stages/DropColumns.scala``)."""

    cols = Param("Columns to remove", converter=to_list_str)

    def transform(self, table: Table) -> Table:
        for c in self.getCols():
            table.column(c)  # raise on missing, like the reference's verifySchema
        return table.drop(*self.getCols())

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        name = type(self).__name__
        for c in self.getCols():
            require_column(schema, c, name)
        return {k: v for k, v in schema.items() if k not in set(self.getCols())}


class SelectColumns(Transformer):
    """Keep only the listed columns (``stages/SelectColumns.scala``)."""

    cols = Param("Columns to keep", converter=to_list_str)

    def transform(self, table: Table) -> Table:
        return table.select(*self.getCols())

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        name = type(self).__name__
        return {
            c: require_column(schema, c, name) for c in self.getCols()
        }


class RenameColumn(Transformer):
    """Rename ``inputCol`` to ``outputCol`` (``stages/RenameColumn.scala``)."""

    inputCol = Param("Column to rename", converter=to_str)
    outputCol = Param("New column name", converter=to_str)

    def transform(self, table: Table) -> Table:
        return table.rename(self.getInputCol(), self.getOutputCol())

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        name = type(self).__name__
        src, dst = self.getInputCol(), self.getOutputCol()
        col = require_column(schema, src, name)
        rest = {k: v for k, v in schema.items() if k != src}
        return add_column(rest, dst, col, name)


class Repartition(Transformer):
    """Change the logical partition count (``stages/Repartition.scala``).

    Partitions map rows onto mesh data-parallel shards
    (`Table.partition_bounds`), standing in for Spark partitions feeding
    `ClusterUtil`-derived worker counts."""

    n = Param("Number of partitions", converter=to_int, validator=gt(0))
    disable = Param("If true, pass through unchanged", default=False, converter=to_bool)

    def transform(self, table: Table) -> Table:
        if self.getDisable():
            return table
        return table.repartition(self.getN())


class StratifiedRepartition(HasLabelCol, Transformer):
    """Rebalance rows so every partition sees every label value
    (``stages/StratifiedRepartition.scala:29``).

    The reference re-keys rows round-robin within each label and invokes a
    range partitioner; with contiguous Table partitions the equivalent is a
    label-round-robin row ordering: rows of each label are dealt one at a
    time across partitions, guaranteeing each contiguous shard holds an
    (almost) proportional slice of every label — which is what keeps
    per-device GBDT histograms from collapsing to single-class."""

    mode = Param(
        "equal, original, or mixed distribution of labels",
        default="mixed",
        converter=to_str,
        validator=one_of("equal", "original", "mixed"),
    )
    seed = Param("Random seed", default=0, converter=to_int)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        require_column(schema, self.getLabelCol(), type(self).__name__)
        return dict(schema)

    def transform(self, table: Table) -> Table:
        if table.num_rows == 0:
            return table
        labels = table.column(self.getLabelCol()).astype(str)
        nparts = table.num_partitions
        rng = np.random.default_rng(self.getSeed())
        values, counts = np.unique(labels, return_counts=True)
        # Per-label resampling fraction (sampleByKeyExact-with-replacement
        # analogue, StratifiedRepartition.scala:48-58,70-73).
        max_count = max(int(counts.max()), nparts)
        mode = self.getMode()
        if mode == "equal":
            fractions = max_count / counts
        elif mode == "original":
            fractions = np.ones(len(values))
        else:  # mixed heuristic: partial upsampling toward equal
            fractions = np.sqrt(max_count / counts)
        # Resample each label, split its rows into nparts near-even chunks,
        # and give chunk p to partition p — every partition receives a slice
        # of every label (whenever a label has ≥ nparts rows). The resulting
        # per-partition sizes are pinned on the Table so partition_bounds
        # reflects the actual groups (RangePartitioner's role).
        per_part: List[List[np.ndarray]] = [[] for _ in range(nparts)]
        for val, frac in zip(values, fractions):
            idx = np.flatnonzero(labels == val)
            target = max(1, int(round(len(idx) * frac)))
            if target > len(idx):
                idx = np.concatenate([idx, rng.choice(idx, target - len(idx))])
            rng.shuffle(idx)
            for p, chunk in enumerate(np.array_split(idx, nparts)):
                per_part[p].append(chunk)
        part_rows = [np.concatenate(chunks) for chunks in per_part]
        out = table.take(np.concatenate(part_rows))
        return out.with_partition_sizes([len(r) for r in part_rows])


class ClassBalancer(HasInputCol, HasOutputCol, Estimator):
    """Adds a weight column inversely proportional to label frequency
    (``stages/ClassBalancer.scala:27``)."""

    outputCol = Param("Weight column name", default="weight", converter=to_str)
    broadcastJoin = Param(
        "Whether to broadcast the weight table (no-op hint here)",
        default=True,
        converter=to_bool,
    )

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _weight_col_schema(self, schema)

    def _fit(self, table: Table) -> "ClassBalancerModel":
        col = table.column(self.getInputCol())
        values, counts = np.unique(col.astype(str), return_counts=True)
        weights = counts.max() / counts.astype(np.float64)
        model = ClassBalancerModel(
            inputCol=self.getInputCol(),
            outputCol=self.getOutputCol(),
            weights={str(v): float(w) for v, w in zip(values, weights)},
        )
        model.parent = self
        return model


class ClassBalancerModel(HasInputCol, HasOutputCol, Model):
    weights = Param("label value -> weight", default={})

    def transform(self, table: Table) -> Table:
        col = table.column(self.getInputCol()).astype(str)
        w = self.getWeights()
        out = np.array([w.get(v, 1.0) for v in col], dtype=np.float64)
        return table.with_column(self.getOutputCol(), out)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _weight_col_schema(self, schema)


def _weight_col_schema(
    stage: Any, schema: Dict[str, Any]
) -> Dict[str, Any]:
    name = type(stage).__name__
    require_column(schema, stage.getInputCol(), name)
    out = stage.getOutputCol()
    return add_column(
        schema,
        out,
        ColType(np.dtype(np.float64), ()),
        name,
        replace=out == stage.getInputCol(),
    )


class Explode(HasInputCol, HasOutputCol, Transformer):
    """One output row per element of a ragged/list column
    (``stages/Explode.scala``); other columns are repeated."""

    def transform(self, table: Table) -> Table:
        col = table.column(self.getInputCol())
        out_name = self.getOutputCol() if self.isDefined("outputCol") else self.getInputCol()
        lengths = np.array([len(v) for v in col], dtype=np.int64)
        repeat_idx = np.repeat(np.arange(table.num_rows), lengths)
        flat: List[Any] = []
        for v in col:
            flat.extend(list(v))
        base = table.drop(self.getInputCol()).take(repeat_idx)
        return base.with_column(out_name, flat)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        name = type(self).__name__
        src = self.getInputCol()
        require_column(schema, src, name)
        out = self.getOutputCol() if self.isDefined("outputCol") else src
        rest = {k: v for k, v in schema.items() if k != src}
        # element dtype of a ragged column is data-dependent -> unknown
        return add_column(rest, out, ColType(), name)


class Lambda(Transformer):
    """Arbitrary ``Table -> Table`` function as a pipeline stage
    (``stages/Lambda.scala:21``). The function is a complex param
    (pickle-serialized), like the reference's UDF ComplexParam."""

    transformFunc = Param("Table -> Table function", is_complex=True)
    transformSchemaFunc = Param(
        "schema -> schema function (optional)", default=None, is_complex=True
    )

    def transform(self, table: Table) -> Table:
        return self.getTransformFunc()(table)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        f = self.getTransformSchemaFunc()
        return f(schema) if f is not None else dict(schema)


class UDFTransformer(HasInputCol, HasInputCols, HasOutputCol, Transformer):
    """Applies a column function to one or many input columns
    (``stages/UDFTransformer.scala``). ``udf`` receives whole column
    arrays (vectorized), not scalar rows."""

    udf = Param("Column-level function", is_complex=True)

    def transform(self, table: Table) -> Table:
        f = self.getUdf()
        if self.isDefined("inputCols") and self.isSet("inputCols"):
            args = [table.column(c) for c in self.getInputCols()]
        else:
            args = [table.column(self.getInputCol())]
        return table.with_column(self.getOutputCol(), f(*args))

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        name = type(self).__name__
        if self.isDefined("inputCols") and self.isSet("inputCols"):
            ins = list(self.getInputCols())
        else:
            ins = [self.getInputCol()]
        for c in ins:
            require_column(schema, c, name)
        out = self.getOutputCol()
        # the udf's output dtype is opaque to static analysis
        return add_column(schema, out, ColType(), name, replace=out in ins)


class MultiColumnAdapter(HasInputCols, HasOutputCols, Transformer, Estimator):
    """Map a single-column stage over many column pairs
    (``stages/MultiColumnAdapter.scala:18``)."""

    baseStage = Param("Stage to replicate per column", is_complex=True)

    def _pairs(self) -> List[tuple]:
        ins, outs = self.getInputCols(), self.getOutputCols()
        if len(ins) != len(outs):
            raise ValueError(
                f"inputCols ({len(ins)}) and outputCols ({len(outs)}) must align"
            )
        return list(zip(ins, outs))

    def _stage_for(self, in_col: str, out_col: str):
        stage = self.getBaseStage().copy()
        stage.set("inputCol", in_col)
        stage.set("outputCol", out_col)
        return stage

    def transform(self, table: Table) -> Table:
        for in_col, out_col in self._pairs():
            table = self._stage_for(in_col, out_col).transform(table)
        return table

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        for in_col, out_col in self._pairs():
            schema = self._stage_for(in_col, out_col).transform_schema(schema)
        return schema

    def _fit(self, table: Table) -> Model:
        from mmlspark_tpu.core.pipeline import PipelineModel

        fitted: List[Transformer] = []
        cur = table
        for in_col, out_col in self._pairs():
            stage = self._stage_for(in_col, out_col)
            if isinstance(stage, Estimator):
                m = stage.fit(cur)
            else:
                m = stage
            cur = m.transform(cur)
            fitted.append(m)
        model = PipelineModel(stages=fitted)
        model.parent = self
        return model


class TextPreprocessor(HasInputCol, HasOutputCol, Transformer):
    """Trie-based substring mapping (``stages/TextPreprocessor.scala:96``):
    longest-match replacement of every ``map`` key found in the text."""

    map = Param("substring -> replacement", default={})
    normFunc = Param(
        "Normalization applied before matching: identity|lowerCase|upperCase",
        default="identity",
        converter=to_str,
        validator=one_of("identity", "lowerCase", "upperCase"),
    )

    # Per-character case mapping (Java Character.toLowerCase semantics):
    # chars whose case-fold changes length (e.g. 'İ') are left as-is so
    # match offsets on the normalized text stay valid in the original.
    @staticmethod
    def _char_map(s: str, f: Callable[[str], str]) -> str:
        return "".join(c2 if len(c2 := f(c)) == 1 else c for c in s)

    _NORM_FUNCS = {
        "identity": lambda s: s,
        "lowerCase": lambda s: TextPreprocessor._char_map(s, str.lower),
        "upperCase": lambda s: TextPreprocessor._char_map(s, str.upper),
    }

    def transform(self, table: Table) -> Table:
        import re

        norm = self._NORM_FUNCS[self.getNormFunc()]
        # Keys are normalized at build time, like the reference Trie inserts
        # normFunc-mapped keys (TextPreprocessor.scala:29-38); matching runs
        # on the normalized text but unmatched spans keep their original form
        # (Trie.mapText appends the original chars).
        mapping = {norm(k): v for k, v in self.getMap().items()}
        col = table.column(self.getInputCol())
        if mapping:
            # Longest-first alternation == greedy trie longest-match.
            pattern = re.compile(
                "|".join(re.escape(k) for k in sorted(mapping, key=len, reverse=True))
            )

            def apply(s: str) -> str:
                normed = norm(s)
                out, pos = [], 0
                for m in pattern.finditer(normed):
                    out.append(s[pos : m.start()])
                    out.append(mapping[m.group(0)])
                    pos = m.end()
                out.append(s[pos:])
                return "".join(out)
        else:
            def apply(s: str) -> str:
                return s
        out = np.array([apply(str(s)) for s in col], dtype=object)
        return table.with_column(self.getOutputCol(), out)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _text_out_schema(self, schema)


class UnicodeNormalize(HasInputCol, HasOutputCol, Transformer):
    """Unicode NFKD/NFC normalization + optional lower-casing
    (``stages/UnicodeNormalize.scala``)."""

    form = Param(
        "Normalization form", default="NFKD", converter=to_str,
        validator=one_of("NFC", "NFD", "NFKC", "NFKD"),
    )
    lower = Param("Lower-case the text", default=True, converter=to_bool)

    def transform(self, table: Table) -> Table:
        col = table.column(self.getInputCol())
        form = self.getForm()
        lower = self.getLower()

        def norm(s: Any) -> Any:
            if s is None:
                return None
            s = unicodedata.normalize(form, str(s))
            return s.lower() if lower else s

        out = np.array([norm(s) for s in col], dtype=object)
        return table.with_column(self.getOutputCol(), out)

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return _text_out_schema(self, schema)


def _text_out_schema(stage: Any, schema: Dict[str, Any]) -> Dict[str, Any]:
    """input col must exist; output is a fresh object (string) column,
    overwriting in place when outputCol == inputCol."""
    name = type(stage).__name__
    src = stage.getInputCol()
    require_column(schema, src, name)
    out = stage.getOutputCol()
    return add_column(
        schema, out, ColType(np.dtype(object)), name, replace=out == src
    )


class Timer(Estimator):
    """Wraps a stage; logs fit/transform wall time (``stages/Timer.scala:57``).

    The TPU-side analogue of the reference's driver-side timing; pair with
    ``mmlspark_tpu.core.utils.StopWatch`` for finer phases and with
    ``jax.profiler`` for on-device traces (SURVEY.md §5 tracing)."""

    stage = Param("The wrapped stage", is_complex=True)
    logToScala = Param("Log with the framework logger", default=True, converter=to_bool)
    disableMaterialization = Param(
        "Kept for reference parity; Tables are always materialized",
        default=True,
        converter=to_bool,
    )

    def transform_schema(self, schema: Dict[str, Any]) -> Dict[str, Any]:
        return self.getStage().transform_schema(schema)

    def _log(self, msg: str) -> str:
        if self.getLogToScala():
            logger.info(msg)
        return msg

    def fit(self, table: Table, params: Optional[Dict[str, Any]] = None) -> Model:
        if params:
            return self.copy(params).fit(table)
        stage = self.getStage()
        if isinstance(stage, Estimator):
            t0 = time.perf_counter()
            inner = stage.fit(table)
            self._log(
                f"{type(stage).__name__}.fit took {time.perf_counter() - t0:.3f}s"
            )
        else:
            inner = stage
        model = TimerModel(stage=inner, logToScala=self.getLogToScala())
        model.parent = self
        return model

    def _fit(self, table: Table) -> Model:
        return self.fit(table)

    def transform(self, table: Table) -> Table:
        # Transformer-style use: time the wrapped transformer directly.
        return self.fit(table).transform(table)


class TimerModel(Model):
    stage = Param("The wrapped fitted stage", is_complex=True)
    logToScala = Param("Log with the framework logger", default=True, converter=to_bool)

    def transform(self, table: Table) -> Table:
        stage = self.getStage()
        t0 = time.perf_counter()
        out = stage.transform(table)
        msg = f"{type(stage).__name__}.transform took {time.perf_counter() - t0:.3f}s"
        if self.getLogToScala():
            logger.info(msg)
        return out


class EnsembleByKey(Transformer):
    """Aggregate scalar/vector columns grouped by key columns
    (``stages/EnsembleByKey.scala:22``)."""

    keys = Param("Grouping key columns", converter=to_list_str)
    cols = Param("Columns to aggregate", converter=to_list_str)
    colNames = Param("Output names (default: '<strategy>(<col>)')", converter=to_list_str)
    strategy = Param(
        "Aggregation strategy", default="mean", converter=to_str, validator=one_of("mean")
    )
    collapseGroup = Param(
        "If true, one row per key; else broadcast the aggregate back to all rows",
        default=True,
        converter=to_bool,
    )
    vectorDims = Param("Kept for parity; dims inferred from data", default=None)

    def transform(self, table: Table) -> Table:
        keys, cols = self.getKeys(), self.getCols()
        if self.isDefined("colNames") and self.isSet("colNames"):
            names = self.getColNames()
        else:
            names = [f"{self.getStrategy()}({c})" for c in cols]
        key_arrays = [table.column(k) for k in keys]
        composite = np.array(
            ["\x00".join(str(a[i]) for a in key_arrays) for i in range(table.num_rows)]
        )
        uniq, first_idx, inverse = np.unique(
            composite, return_index=True, return_inverse=True
        )
        agg: Dict[str, np.ndarray] = {}
        for c, name in zip(cols, names):
            col = table.column(c)
            dense = np.stack([np.asarray(v, dtype=np.float64) for v in col]) \
                if col.dtype == object else col.astype(np.float64)
            if dense.ndim == 1:
                sums = np.zeros(len(uniq))
                np.add.at(sums, inverse, dense)
            else:
                sums = np.zeros((len(uniq),) + dense.shape[1:])
                np.add.at(sums, inverse, dense)
            counts = np.bincount(inverse, minlength=len(uniq)).astype(np.float64)
            agg[name] = sums / counts.reshape((-1,) + (1,) * (sums.ndim - 1))
        if self.getCollapseGroup():
            out = table.select(*keys).take(first_idx)
            for name, values in agg.items():
                out = out.with_column(name, values)
            return out
        out = table
        for name, values in agg.items():
            out = out.with_column(name, values[inverse])
        return out


class SummarizeData(Transformer):
    """Per-column summary statistics table (``stages/SummarizeData.scala:100``):
    counts, missing, basic moments, and error-bounded quantiles."""

    counts = Param("Include count stats", default=True, converter=to_bool)
    basic = Param("Include basic stats", default=True, converter=to_bool)
    sample = Param("Include sample stats", default=True, converter=to_bool)
    percentiles = Param("Include percentiles", default=True, converter=to_bool)
    errorThreshold = Param(
        "Quantile error (0 = exact)", default=0.0, validator=ge(0.0)
    )

    _PERCENTILES = [0.005, 0.01, 0.05, 0.25, 0.5, 0.75, 0.95, 0.99, 0.995]

    def transform(self, table: Table) -> Table:
        rows: List[Dict[str, Any]] = []
        n = table.num_rows
        for name in table.columns:
            col = table.column(name)
            row: Dict[str, Any] = {"Feature": name}
            is_numeric = col.ndim == 1 and np.issubdtype(col.dtype, np.number)
            if col.dtype == object:
                missing = sum(1 for v in col if v is None)
            elif np.issubdtype(col.dtype, np.floating):
                missing = int(np.isnan(col).sum())
            else:
                missing = 0
            if self.getCounts():
                row["Count"] = float(n)
                row["Unique Value Count"] = float(len(np.unique(col.astype(str))) if col.ndim == 1 else n)
                row["Missing Value Count"] = float(missing)
            if is_numeric:
                valid = col[~np.isnan(col.astype(np.float64))].astype(np.float64)
                if self.getBasic():
                    row["Max"] = float(valid.max()) if len(valid) else np.nan
                    row["Min"] = float(valid.min()) if len(valid) else np.nan
                    row["Mean"] = float(valid.mean()) if len(valid) else np.nan
                if self.getSample():
                    row["Sample Variance"] = (
                        float(valid.var(ddof=1)) if len(valid) > 1 else np.nan
                    )
                    row["Sample Standard Deviation"] = (
                        float(valid.std(ddof=1)) if len(valid) > 1 else np.nan
                    )
                if self.getPercentiles():
                    for p in self._PERCENTILES:
                        row[f"Quantile {p}"] = (
                            float(np.quantile(valid, p)) if len(valid) else np.nan
                        )
            rows.append(row)
        all_keys: List[str] = []
        for r in rows:
            for k in r:
                if k not in all_keys:
                    all_keys.append(k)
        cols = {
            k: np.array(
                [r.get(k, np.nan) for r in rows],
                dtype=object if k == "Feature" else np.float64,
            )
            for k in all_keys
        }
        return Table(cols)
