"""Column helper functions (``stages/udfs.scala:16``) — vectorized over
whole columns instead of per-row Spark UDFs."""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np


def get_value_at(col: np.ndarray, index: int) -> np.ndarray:
    """Element ``index`` of each vector in a vector column
    (``udfs.get_value_at``)."""
    if col.dtype == object:
        return np.array([np.asarray(v, dtype=np.float64)[index] for v in col])
    return col[:, index].astype(np.float64)


def to_vector(col: Sequence[Any]) -> np.ndarray:
    """Array/list column -> fixed-width vector column (``udfs.to_vector``)."""
    arr = np.asarray([np.asarray(v, dtype=np.float64) for v in col])
    return arr
