"""DNNModel — batched deep-network inference transformer.

Re-design of ``CNTKModel`` (``cntk/CNTKModel.scala:145-531``) for TPU:

- the serialized CNTK ``Function`` broadcast to executors becomes a jittable
  ``applyFn(params, inputs) -> outputs`` plus a ``params`` pytree placed on
  device once per transform (the ``rebroadcastCNTKModel`` analogue,
  ``CNTKModel.scala:411-413``);
- mini-batching is ON by default (reference wraps with
  ``FixedMiniBatchTransformer(batchSize=10)`` then ``FlattenBatch``,
  ``CNTKModel.scala:374,496-528``) — here every batch is right-padded to a
  single static shape so XLA compiles ONE program and the MXU sees full
  tiles;
- ``feedDict``/``fetchDict`` map model input/output names to columns
  (``CNTKModel.scala:225-367``); the single-input/single-output convenience
  setters mirror ``setInputCol``/``setOutputCol``;
- input coercion float/double/vector (``CNTKModel.scala:417-460``) becomes
  dtype casting on the padded host batch.

Optionally shards each batch over the mesh ``data`` axis — the reference's
per-partition embarrassing parallelism (``CNTKModelUtils.applyModel``,
``CNTKModel.scala:30-140``) expressed as one SPMD program.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.params import Param, gt, to_bool, to_int, to_str
from mmlspark_tpu.core.pipeline import Model
from mmlspark_tpu.data.table import Table


def _stack_batch(col: np.ndarray, pad_to: int, dtype: Any) -> np.ndarray:
    """Rows of a column -> one padded [pad_to, ...] device-ready batch."""
    rows = [np.asarray(v) for v in col]
    batch = np.stack(rows).astype(dtype)
    if len(rows) < pad_to:
        pad = np.zeros((pad_to - len(rows),) + batch.shape[1:], dtype=batch.dtype)
        batch = np.concatenate([batch, pad])
    return batch


class DNNModel(Model):
    """Applies a jittable network to feature columns in device batches."""

    applyFn = Param(
        "Jittable (params, {name: array}) -> {name: array} | array",
        default=None, is_complex=True,
    )
    modelParams = Param("Model parameter pytree", default=None, is_complex=True)
    feedDict = Param(
        "model input name -> feature column name", default={},
    )
    fetchDict = Param(
        "output column name -> model output name", default={},
    )
    batchSize = Param(
        "Rows per device batch (static shape; last batch padded)",
        default=64,
        converter=to_int,
        validator=gt(0),
    )
    miniBatcher = Param(
        "Batch rows before eval (CNTKModel batches by default)",
        default=True,
        converter=to_bool,
    )
    inputDtype = Param("Cast inputs to this dtype", default="float32", converter=to_str)
    paramShardings = Param(
        "Tensor-parallel map: param key -> axis index sharded over the mesh "
        "'model' axis (None = fully replicated params)",
        default=None, is_complex=True,
    )
    meshConfig = Param(
        "MeshConfig for shardOverMesh (None = all devices on the data axis)",
        default=None, is_complex=True,
    )
    shardOverMesh = Param(
        "Shard each batch over the mesh 'data' axis", default=False, converter=to_bool
    )
    pipelineStageFn = Param(
        "Pipeline mode: jittable (stage_params, h) -> h applying ONE stage; "
        "modelParams must carry a leading stage axis sharded over the mesh "
        "'pipe' axis (GPipe microbatch schedule, ops/pipeline_parallel.py)",
        default=None, is_complex=True,
    )
    numMicrobatches = Param(
        "Pipeline mode: microbatches per batch (bubble fraction "
        "(p-1)/(m+p-1))",
        default=4, converter=to_int, validator=gt(0),
    )
    expertFn = Param(
        "MoE mode: jittable (expert_params, x) -> y applying ONE expert; "
        "modelParams must be {'experts': pytree with leading E axis, "
        "'gate': (D, E) array} — top-1 masked-dense dispatch over the mesh "
        "'expert' axis (ops/expert_parallel.py)",
        default=None, is_complex=True,
    )

    # -- convenience single input/output API (CNTKModel.scala:302-367) -------

    def setInputCol(self, value: str) -> "DNNModel":
        feeds = dict(self.getFeedDict())
        feeds["input"] = value
        return self.setFeedDict(feeds)

    def setOutputCol(self, value: str) -> "DNNModel":
        fetches = dict(self.getFetchDict())
        fetches[value] = "output"
        return self.setFetchDict(fetches)

    def getInputCol(self) -> str:
        return next(iter(self.getFeedDict().values()))

    def getOutputCol(self) -> str:
        return next(iter(self.getFetchDict().keys()))

    # -- evaluation ----------------------------------------------------------

    def _jitted(self):
        import jax

        modes = [
            name for name, v in [
                ("applyFn", self.getApplyFn()),
                ("pipelineStageFn", self.getPipelineStageFn()),
                ("expertFn", self.getExpertFn()),
            ] if v is not None
        ]
        if len(modes) != 1:
            raise ValueError(
                "exactly one of applyFn / pipelineStageFn / expertFn must be "
                f"set (got {modes or 'none'})"
            )
        if self.getPipelineStageFn() is not None:
            return self._jitted_pipeline()
        if self.getExpertFn() is not None:
            return self._jitted_moe()

        apply_fn = self.getApplyFn()
        if self.getShardOverMesh():
            from jax.sharding import NamedSharding, PartitionSpec as P

            from mmlspark_tpu.parallel.mesh import make_mesh

            mesh_config = self.getMeshConfig()
            mesh = make_mesh(mesh_config)
            batch_sharding = NamedSharding(mesh, P("data"))
            replicated = NamedSharding(mesh, P())
            # Tensor parallelism: paramShardings maps a param-pytree key to
            # the axis index sharded over the mesh "model" axis (e.g. the
            # output-features dim of a Linear weight). XLA then partitions
            # the matmuls and inserts the all-gather/reduce-scatter
            # collectives (the TP recipe: annotate shardings, let GSPMD
            # place the collectives).
            tp: Dict[str, int] = self.getParamShardings() or {}
            if tp and not isinstance(self.getModelParams(), dict):
                raise ValueError(
                    "paramShardings requires modelParams to be a flat dict "
                    f"of arrays (got {type(self.getModelParams()).__name__})"
                )
            for key, axis in tp.items():
                val = self.getModelParams().get(key)
                if val is None:
                    raise ValueError(f"paramShardings key {key!r} not in modelParams")
                if np.ndim(val) <= axis:
                    raise ValueError(
                        f"paramShardings[{key!r}]={axis} out of range for a "
                        f"{np.ndim(val)}-d param"
                    )

            def shard_for(key, value):
                if key in tp:
                    spec = [None] * np.ndim(value)
                    spec[tp[key]] = "model"
                    return NamedSharding(mesh, P(*spec))
                return replicated

            def place_params(params):
                """Commit weights to their FINAL shardings once, outside the
                compiled call — so the in-program device_put is a no-op
                rather than a per-batch broadcast/reshard over ICI."""
                if isinstance(params, dict):
                    return {
                        k: jax.device_put(v, shard_for(k, v))
                        for k, v in params.items()
                    }
                return jax.device_put(params, replicated)

            def run(params, inputs):
                inputs = {
                    k: jax.device_put(v, batch_sharding) for k, v in inputs.items()
                }
                return apply_fn(params, inputs)

            return jax.jit(run), mesh, place_params
        return jax.jit(apply_fn), None, None

    def _single_feed(self, inputs: Dict[str, Any]):
        if len(inputs) != 1:
            raise ValueError(
                "pipeline/MoE modes take exactly one feed column "
                f"(got {sorted(inputs)})"
            )
        return next(iter(inputs.values()))

    def _jitted_pipeline(self):
        """Pipeline mode: the batch flows through p stages, one per device
        on the mesh 'pipe' axis (GPipe microbatch schedule); falls back to a
        sequential stage scan when the pipe axis is 1."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mmlspark_tpu.ops.pipeline_parallel import pipeline_apply
        from mmlspark_tpu.parallel.mesh import AXIS_PIPE, make_mesh

        stage_fn = self.getPipelineStageFn()
        m = self.getNumMicrobatches()
        mesh = make_mesh(self.getMeshConfig())
        staged = NamedSharding(mesh, P(AXIS_PIPE))

        def place_params(params):
            # leading (stage) axis onto the pipe mesh axis, once
            return jax.tree.map(lambda a: jax.device_put(a, staged), params)

        def run(params, inputs):
            x = self._single_feed(inputs)
            return {"output": pipeline_apply(stage_fn, params, x, mesh, m)}

        return jax.jit(run), mesh, place_params

    def _jitted_moe(self):
        """MoE mode: top-1 gated experts, one per device on the mesh
        'expert' axis (masked-dense dispatch + psum combine); sequential
        expert scan when the expert axis is 1."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from mmlspark_tpu.ops.expert_parallel import moe_apply
        from mmlspark_tpu.parallel.mesh import AXIS_EXPERT, make_mesh

        expert_fn = self.getExpertFn()
        mesh = make_mesh(self.getMeshConfig())
        exp_sh = NamedSharding(mesh, P(AXIS_EXPERT))
        rep = NamedSharding(mesh, P())

        def place_params(params):
            if not isinstance(params, dict) or "experts" not in params or "gate" not in params:
                raise ValueError(
                    "MoE mode needs modelParams = {'experts': pytree with a "
                    "leading expert axis, 'gate': (D, E) array}"
                )
            return {
                "experts": jax.tree.map(
                    lambda a: jax.device_put(a, exp_sh), params["experts"]
                ),
                "gate": jax.device_put(params["gate"], rep),
            }

        def run(params, inputs):
            x = self._single_feed(inputs)
            gate_logits = x @ params["gate"]
            return {
                "output": moe_apply(
                    expert_fn, params["experts"], x, gate_logits, mesh
                )
            }

        return jax.jit(run), mesh, place_params

    def transform(self, table: Table) -> Table:
        import jax

        feeds: Dict[str, str] = self.getFeedDict()
        fetches: Dict[str, str] = self.getFetchDict()
        if not feeds or not fetches:
            raise ValueError("feedDict and fetchDict must both be set")
        batch_size = self.getBatchSize()
        if self.getShardOverMesh():
            from mmlspark_tpu.parallel.mesh import make_mesh

            n_dev = make_mesh(self.getMeshConfig()).shape.get("data", 1)
            batch_size = max(batch_size, n_dev)
            batch_size += (-batch_size) % n_dev
        if self.getPipelineStageFn() is not None:
            # GPipe schedule splits each batch into numMicrobatches
            m = self.getNumMicrobatches()
            batch_size = max(batch_size, m)
            batch_size += (-batch_size) % m
        dtype = np.dtype(self.getInputDtype())
        n = table.num_rows
        fn, _, place_params = self._jitted()
        # Pin weights on device ONCE, with their final shardings when the
        # mesh is in play: numpy param leaves would re-transfer (and sharded
        # ones re-broadcast) on every batch dispatch.
        import jax.numpy as jnp

        if place_params is not None:
            params = place_params(self.getModelParams())
        else:
            params = jax.tree.map(jnp.asarray, self.getModelParams())

        out_cols: Dict[str, List[np.ndarray]] = {name: [] for name in fetches}
        bounds = (
            [(lo, min(lo + batch_size, n)) for lo in range(0, n, batch_size)]
            if self.getMiniBatcher()
            else [(0, n)]
        )
        for lo, hi in bounds:
            pad_to = batch_size if self.getMiniBatcher() else n
            if self.getPipelineStageFn() is not None:
                # GPipe needs batch % microbatches == 0 even un-minibatched
                pad_to += (-pad_to) % self.getNumMicrobatches()
            inputs = {
                model_in: _stack_batch(table.column(col)[lo:hi], pad_to, dtype)
                for model_in, col in feeds.items()
            }
            outputs = fn(params, inputs)
            if not isinstance(outputs, dict):
                outputs = {"output": outputs}
            for col_name, model_out in fetches.items():
                if model_out not in outputs:
                    raise KeyError(
                        f"model returned {sorted(outputs)}, no output {model_out!r}"
                    )
                arr = np.asarray(jax.device_get(outputs[model_out]))[: hi - lo]
                out_cols[col_name].append(arr)
        result = table
        for col_name, parts in out_cols.items():
            result = result.with_column(col_name, np.concatenate(parts))
        return result
