"""Minimal vendored ONNX protobuf wire-format codec (no ``onnx`` package).

The image ships no ``onnx`` bindings, so this module hand-decodes the
protobuf wire format for exactly the message subset the graph walker in
:mod:`onnx_import` needs: ModelProto → GraphProto → Node/Tensor/Attribute/
ValueInfo. Field numbers follow the public ``onnx.proto3`` schema. A
matching minimal writer exists so tests can author .onnx files in-process.

Wire format recap: a message is a sequence of (tag, payload) where
``tag = (field_number << 3) | wire_type`` and wire types are 0 varint,
1 fixed64, 2 length-delimited, 5 fixed32. Repeated scalars may arrive
packed (wire type 2).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

import numpy as np

# -- low-level reader --------------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _iter_fields(buf: bytes):
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wtype = tag >> 3, tag & 7
        if wtype == 0:
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:
            val = buf[pos : pos + 8]
            pos += 8
        elif wtype == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos : pos + ln]
            pos += ln
        elif wtype == 5:
            val = buf[pos : pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        yield field, wtype, val


def _unpack_varints(buf: bytes) -> List[int]:
    out, pos = [], 0
    while pos < len(buf):
        v, pos = _read_varint(buf, pos)
        out.append(v)
    return out


def _signed(v: int) -> int:
    """Interpret a varint as two's-complement int64 (proto int64 encoding)."""
    return v - (1 << 64) if v >= (1 << 63) else v


# -- message decoders --------------------------------------------------------

# TensorProto.DataType → numpy
TENSOR_DTYPES = {
    1: np.float32,
    2: np.uint8,
    3: np.int8,
    4: np.uint16,
    5: np.int16,
    6: np.int32,
    7: np.int64,
    9: np.bool_,
    10: np.float16,
    11: np.float64,
    12: np.uint32,
    13: np.uint64,
}


def decode_tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    dims: List[int] = []
    dtype_code = 1
    name = ""
    raw = None
    float_data: List[float] = []
    int32_data: List[int] = []
    int64_data: List[int] = []
    double_data: List[float] = []
    for field, wtype, val in _iter_fields(buf):
        if field == 1:  # dims
            if wtype == 2:
                dims.extend(_signed(v) for v in _unpack_varints(val))
            else:
                dims.append(_signed(val))
        elif field == 2:
            dtype_code = val
        elif field == 4:  # float_data (packed fixed32)
            if wtype == 2:
                float_data.extend(struct.unpack(f"<{len(val)//4}f", val))
            else:
                float_data.append(struct.unpack("<f", val)[0])
        elif field == 5:
            if wtype == 2:
                int32_data.extend(_signed(v) for v in _unpack_varints(val))
            else:
                int32_data.append(_signed(val))
        elif field == 7:
            if wtype == 2:
                int64_data.extend(_signed(v) for v in _unpack_varints(val))
            else:
                int64_data.append(_signed(val))
        elif field == 8:
            name = val.decode()
        elif field == 9:
            raw = bytes(val)
        elif field == 10:  # double_data (packed fixed64)
            if wtype == 2:
                double_data.extend(struct.unpack(f"<{len(val)//8}d", val))
            else:
                double_data.append(struct.unpack("<d", val)[0])
    np_dtype = TENSOR_DTYPES.get(dtype_code)
    if np_dtype is None:
        raise ValueError(f"unsupported TensorProto data_type {dtype_code}")
    if raw is not None:
        arr = np.frombuffer(raw, dtype=np_dtype)
    elif float_data:
        arr = np.asarray(float_data, dtype=np_dtype)
    elif double_data:
        arr = np.asarray(double_data, dtype=np_dtype)
    elif int64_data:
        arr = np.asarray(int64_data, dtype=np_dtype)
    elif int32_data:
        arr = np.asarray(int32_data, dtype=np_dtype)
    else:
        arr = np.zeros(0, dtype=np_dtype)
    return name, arr.reshape(dims) if dims else arr


def decode_attribute(buf: bytes) -> Tuple[str, Any]:
    name = ""
    out: Any = None
    atype = 0
    floats: List[float] = []
    ints: List[int] = []
    strings: List[bytes] = []
    for field, wtype, val in _iter_fields(buf):
        if field == 1:
            name = val.decode()
        elif field == 2:  # f (fixed32)
            out = struct.unpack("<f", val)[0]
        elif field == 3:  # i
            out = _signed(val)
        elif field == 4:  # s
            out = bytes(val)
        elif field == 5:  # t
            out = decode_tensor(val)[1]
        elif field == 7:  # floats
            if wtype == 2:
                floats.extend(struct.unpack(f"<{len(val)//4}f", val))
            else:
                floats.append(struct.unpack("<f", val)[0])
        elif field == 8:  # ints
            if wtype == 2:
                ints.extend(_signed(v) for v in _unpack_varints(val))
            else:
                ints.append(_signed(val))
        elif field == 9:  # strings
            strings.append(bytes(val))
        elif field == 20:
            atype = val
    if floats:
        out = floats
    elif ints:
        out = ints
    elif strings:
        out = strings
    if out is None:
        # proto3 omits default-valued scalars on the wire: an attribute with
        # e.g. axis=0 or beta=0.0 arrives as name+type only. Reconstruct the
        # default from AttributeProto.type (1 FLOAT, 2 INT, 3 STRING,
        # 6 FLOATS, 7 INTS, 8 STRINGS).
        out = {1: 0.0, 2: 0, 3: b"", 6: [], 7: [], 8: []}.get(atype)
    return name, out


def decode_node(buf: bytes) -> Dict[str, Any]:
    node = {"input": [], "output": [], "name": "", "op_type": "", "attrs": {}}
    for field, _, val in _iter_fields(buf):
        if field == 1:
            node["input"].append(val.decode())
        elif field == 2:
            node["output"].append(val.decode())
        elif field == 3:
            node["name"] = val.decode()
        elif field == 4:
            node["op_type"] = val.decode()
        elif field == 5:
            k, v = decode_attribute(val)
            node["attrs"][k] = v
    return node


def _decode_value_info(buf: bytes) -> str:
    for field, _, val in _iter_fields(buf):
        if field == 1:
            return val.decode()
    return ""


def decode_graph(buf: bytes) -> Dict[str, Any]:
    graph: Dict[str, Any] = {
        "nodes": [],
        "initializers": {},
        "inputs": [],
        "outputs": [],
        "name": "",
    }
    for field, _, val in _iter_fields(buf):
        if field == 1:
            graph["nodes"].append(decode_node(val))
        elif field == 2:
            graph["name"] = val.decode()
        elif field == 5:
            name, arr = decode_tensor(val)
            graph["initializers"][name] = arr
        elif field == 11:
            graph["inputs"].append(_decode_value_info(val))
        elif field == 12:
            graph["outputs"].append(_decode_value_info(val))
    return graph


def decode_model(buf: bytes) -> Dict[str, Any]:
    """ModelProto → {'graph': ..., 'opset': int, 'ir_version': int}."""
    model: Dict[str, Any] = {"graph": None, "opset": 0, "ir_version": 0}
    for field, _, val in _iter_fields(buf):
        if field == 1:
            model["ir_version"] = _signed(val)
        elif field == 7:
            model["graph"] = decode_graph(val)
        elif field == 8:  # opset_import (OperatorSetIdProto)
            for f2, _, v2 in _iter_fields(val):
                if f2 == 2:
                    model["opset"] = max(model["opset"], _signed(v2))
    if model["graph"] is None:
        raise ValueError("no GraphProto found — not an ONNX model file?")
    return model


# -- minimal writer (tests author .onnx files in-process) --------------------


def _varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wtype: int) -> bytes:
    return _varint((field << 3) | wtype)


def _ld(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def encode_tensor(name: str, arr: np.ndarray) -> bytes:
    code = {v: k for k, v in TENSOR_DTYPES.items()}[arr.dtype.type]
    out = b""
    for d in arr.shape:
        out += _tag(1, 0) + _varint(d)
    out += _tag(2, 0) + _varint(code)
    out += _ld(8, name.encode())
    out += _ld(9, np.ascontiguousarray(arr).tobytes())
    return out


def encode_attribute(name: str, value: Any) -> bytes:
    out = _ld(1, name.encode())
    if isinstance(value, float):
        out += _tag(2, 5) + struct.pack("<f", value) + _tag(20, 0) + _varint(1)
    elif isinstance(value, bool):
        out += _tag(3, 0) + _varint(int(value)) + _tag(20, 0) + _varint(2)
    elif isinstance(value, int):
        out += _tag(3, 0) + _varint(value & ((1 << 64) - 1)) + _tag(20, 0) + _varint(2)
    elif isinstance(value, (bytes, str)):
        b = value.encode() if isinstance(value, str) else value
        out += _ld(4, b) + _tag(20, 0) + _varint(3)
    elif isinstance(value, np.ndarray):
        out += _ld(5, encode_tensor(name + "_t", value)) + _tag(20, 0) + _varint(4)
    elif isinstance(value, (list, tuple)) and all(isinstance(v, int) for v in value):
        for v in value:
            out += _tag(8, 0) + _varint(v & ((1 << 64) - 1))
        out += _tag(20, 0) + _varint(7)
    elif isinstance(value, (list, tuple)):
        for v in value:
            out += _tag(7, 5) + struct.pack("<f", float(v))
        out += _tag(20, 0) + _varint(6)
    else:
        raise TypeError(f"cannot encode attribute {name}={value!r}")
    return out


def encode_node(op_type: str, inputs, outputs, attrs=None, name="") -> bytes:
    out = b""
    for i in inputs:
        out += _ld(1, i.encode())
    for o in outputs:
        out += _ld(2, o.encode())
    out += _ld(3, (name or op_type).encode())
    out += _ld(4, op_type.encode())
    for k, v in (attrs or {}).items():
        out += _ld(5, encode_attribute(k, v))
    return out


def _encode_value_info(name: str) -> bytes:
    return _ld(1, name.encode())


def encode_model(nodes, initializers, inputs, outputs, opset: int = 13) -> bytes:
    graph = b"".join(_ld(1, n) for n in nodes)
    graph += _ld(2, b"g")
    for name, arr in initializers.items():
        graph += _ld(5, encode_tensor(name, arr))
    for i in inputs:
        graph += _ld(11, _encode_value_info(i))
    for o in outputs:
        graph += _ld(12, _encode_value_info(o))
    model = _tag(1, 0) + _varint(8)  # ir_version
    model += _ld(8, _tag(2, 0) + _varint(opset))
    model += _ld(7, graph)
    return model
