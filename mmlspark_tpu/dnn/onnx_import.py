"""ONNX → JAX import (gated — the ``onnx`` package is not in this image).

SURVEY.md §7 step 5 names ONNX import as the CNTK-evaluator replacement
path. The environment ships without the ``onnx`` protobuf bindings, so this
module degrades to a clear error; :func:`mmlspark_tpu.dnn.from_torch` is
the supported external-graph frontend meanwhile. The op lowering table in
:mod:`torch_import` (conv/pool/norm/activation/gemm) is exactly the set an
ONNX walker needs, so wiring a real parser here is mechanical once the
package exists.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import numpy as np


def onnx_available() -> bool:
    try:
        import onnx  # noqa: F401

        return True
    except ImportError:
        return False


def from_onnx(path: str) -> Tuple[Callable, Dict[str, Any]]:
    """Load an ONNX file into ``(apply_fn, params)`` for DNNModel."""
    if not onnx_available():
        raise ImportError(
            "the 'onnx' package is not installed in this environment; "
            "import external graphs with mmlspark_tpu.dnn.from_torch instead"
        )
    raise NotImplementedError(
        "ONNX parsing lands when the onnx package is present; "
        "use mmlspark_tpu.dnn.from_torch"
    )
