"""ONNX → JAX import: protobuf walk + op lowering, no ``onnx`` package.

SURVEY.md §7 step 5 names ONNX import as the CNTK-evaluator replacement
(the reference broadcasts serialized CNTK graphs and evaluates them over
JNI — ``com/microsoft/CNTK/SerializableFunction.scala:17-143``). The image
ships no ``onnx`` bindings, so the wire format is decoded by the vendored
reader in :mod:`onnx_proto`, and each NodeProto is lowered to a JAX op,
producing the same pure ``(apply_fn, params)`` contract as
:func:`mmlspark_tpu.dnn.from_torch`:

    fn, params = from_onnx("model.onnx")
    DNNModel(applyFn=fn, modelParams=params, inputCol=..., outputCol=...)

Static shapes only (the XLA contract): shape-producing ops (Reshape /
Squeeze / Flatten / Transpose) are evaluated with static attribute or
initializer operands. Unsupported ops raise with the op name.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple

import numpy as np

from mmlspark_tpu.dnn.onnx_proto import decode_model


def onnx_available() -> bool:
    """The vendored decoder is always available (kept for API compat)."""
    return True


def _pads_to_lax(pads: List[int], spatial: int):
    # ONNX pads = [b1..bn, e1..en]
    return [(pads[i], pads[i + spatial]) for i in range(spatial)]


def _auto_pad(attrs, spatial):
    ap = attrs.get("auto_pad", b"NOTSET")
    ap = ap.decode() if isinstance(ap, bytes) else ap
    if ap in ("NOTSET", ""):
        pads = attrs.get("pads", [0] * (2 * spatial))
        return _pads_to_lax(pads, spatial)
    if ap == "VALID":
        return "VALID"
    if ap == "SAME_UPPER":
        return "SAME"
    # SAME_LOWER puts the extra pad at the START; lax "SAME" pads at the end,
    # which would silently shift every window — refuse instead.
    raise ValueError(f"unsupported auto_pad {ap}; re-export with explicit pads")


def _conv(jnp, lax, x, w, b, attrs):
    spatial = x.ndim - 2
    strides = tuple(attrs.get("strides", [1] * spatial))
    dilations = tuple(attrs.get("dilations", [1] * spatial))
    groups = int(attrs.get("group", 1))
    pad = _auto_pad(attrs, spatial)
    dn = ("NCHW", "OIHW", "NCHW") if spatial == 2 else ("NCW", "OIW", "NCW")
    out = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pad,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=groups,
    )
    if b is not None:
        out = out + b.reshape((1, -1) + (1,) * spatial)
    return out


def _pool(jnp, lax, x, attrs, kind):
    spatial = x.ndim - 2
    ks = tuple(attrs["kernel_shape"])
    strides = tuple(attrs.get("strides", [1] * spatial))
    pad = _auto_pad(attrs, spatial)
    if pad == "VALID":
        pad = [(0, 0)] * spatial
    elif pad == "SAME":
        raise ValueError("SAME pooling unsupported; export with explicit pads")
    window = (1, 1) + ks
    strides_full = (1, 1) + strides
    pad_full = [(0, 0), (0, 0)] + list(pad)
    if kind == "max":
        return lax.reduce_window(
            x, -jnp.inf, lax.max, window, strides_full, pad_full
        )
    s = lax.reduce_window(x, 0.0, lax.add, window, strides_full, pad_full)
    if attrs.get("count_include_pad", 0) or all(p == (0, 0) for p in pad):
        return s / float(np.prod(ks))
    ones = jnp.ones_like(x)
    cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides_full, pad_full)
    return s / cnt


def _gemm(jnp, a, b, c, attrs):
    alpha = attrs.get("alpha", 1.0)
    beta = attrs.get("beta", 1.0)
    if attrs.get("transA", 0):
        a = a.T
    if attrs.get("transB", 0):
        b = b.T
    out = alpha * (a @ b)
    if c is not None:
        out = out + beta * c
    return out


def _softmax(jnp, x, attrs, opset, log=False):
    axis = int(attrs.get("axis", -1 if opset >= 13 else 1))
    if opset < 13 and x.ndim > 2:
        # Pre-13 ONNX Softmax is the flatten-to-2D variant: normalize
        # jointly over ALL dims from `axis` onward, not per-axis.
        axis = axis % x.ndim
        lead = int(np.prod(x.shape[:axis])) if axis else 1
        flat = x.reshape(lead, -1)
        return _softmax(jnp, flat, {"axis": 1}, 13, log=log).reshape(x.shape)
    m = x - x.max(axis=axis, keepdims=True)
    if log:
        return m - jnp.log(jnp.exp(m).sum(axis=axis, keepdims=True))
    e = jnp.exp(m)
    return e / e.sum(axis=axis, keepdims=True)


def _reshape(jnp, x, shape_arr, attrs):
    shape = [int(s) for s in np.asarray(shape_arr).tolist()]
    shape = [x.shape[i] if s == 0 and attrs.get("allowzero", 0) == 0 else s
             for i, s in enumerate(shape)]
    return x.reshape(shape)


def from_onnx(path_or_bytes) -> Tuple[Callable, Dict[str, Any]]:
    """Load an ONNX model into ``(apply_fn, params)`` for DNNModel.

    ``apply_fn(params, {input_name: array}) -> {output_name: array}``;
    ``params`` is the initializer dict (numpy arrays) so downstream code
    can treat the weights as a pytree.
    """
    if isinstance(path_or_bytes, (bytes, bytearray)):
        buf = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as fh:
            buf = fh.read()
    model = decode_model(buf)
    graph = model["graph"]
    opset = model["opset"] or 13
    inits: Dict[str, np.ndarray] = dict(graph["initializers"])
    # Constant nodes fold into the initializer set.
    nodes = []
    for node in graph["nodes"]:
        if node["op_type"] == "Constant":
            inits[node["output"][0]] = np.asarray(node["attrs"]["value"])
        else:
            nodes.append(node)
    graph_inputs = [i for i in graph["inputs"] if i not in inits]
    outputs = list(graph["outputs"])

    params = {k: np.asarray(v) for k, v in inits.items()}

    def apply_fn(params, inputs):
        import jax.numpy as jnp
        from jax import lax

        env: Dict[str, Any] = {}
        env.update({k: jnp.asarray(v) for k, v in params.items()})
        if isinstance(inputs, dict):
            env.update({k: jnp.asarray(v) for k, v in inputs.items()})
        else:
            env[graph_inputs[0]] = jnp.asarray(inputs)

        def get(name):
            if name == "":
                return None
            if name not in env:
                raise KeyError(
                    f"ONNX value {name!r} undefined (graph not topo-sorted?)"
                )
            return env[name]

        for node in nodes:
            op = node["op_type"]
            attrs = node["attrs"]
            ins = [get(n) for n in node["input"]]
            if op == "Conv":
                out = _conv(jnp, lax, ins[0], ins[1], ins[2] if len(ins) > 2 else None, attrs)
            elif op == "MatMul":
                out = ins[0] @ ins[1]
            elif op == "Gemm":
                out = _gemm(jnp, ins[0], ins[1], ins[2] if len(ins) > 2 else None, attrs)
            elif op == "Add":
                out = ins[0] + ins[1]
            elif op == "Sub":
                out = ins[0] - ins[1]
            elif op == "Mul":
                out = ins[0] * ins[1]
            elif op == "Div":
                out = ins[0] / ins[1]
            elif op == "Pow":
                out = ins[0] ** ins[1]
            elif op == "Sqrt":
                out = jnp.sqrt(ins[0])
            elif op == "Exp":
                out = jnp.exp(ins[0])
            elif op == "Neg":
                out = -ins[0]
            elif op == "Relu":
                out = jnp.maximum(ins[0], 0)
            elif op == "LeakyRelu":
                alpha = attrs.get("alpha", 0.01)
                out = jnp.where(ins[0] >= 0, ins[0], alpha * ins[0])
            elif op == "Sigmoid":
                out = 1.0 / (1.0 + jnp.exp(-ins[0]))
            elif op == "Tanh":
                out = jnp.tanh(ins[0])
            elif op == "Erf":
                from jax.scipy.special import erf

                out = erf(ins[0])
            elif op == "Softmax":
                out = _softmax(jnp, ins[0], attrs, opset)
            elif op == "LogSoftmax":
                out = _softmax(jnp, ins[0], attrs, opset, log=True)
            elif op == "MaxPool":
                out = _pool(jnp, lax, ins[0], attrs, "max")
            elif op == "AveragePool":
                out = _pool(jnp, lax, ins[0], attrs, "avg")
            elif op == "GlobalAveragePool":
                out = ins[0].mean(axis=tuple(range(2, ins[0].ndim)), keepdims=True)
            elif op == "BatchNormalization":
                x, scale, bias, mean, var = ins[:5]
                eps = attrs.get("epsilon", 1e-5)
                shape = (1, -1) + (1,) * (x.ndim - 2)
                out = (x - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + eps)
                out = out * scale.reshape(shape) + bias.reshape(shape)
            elif op == "Flatten":
                axis = int(attrs.get("axis", 1))
                lead = int(np.prod(ins[0].shape[:axis])) if axis else 1
                out = ins[0].reshape(lead, -1)
            elif op == "Reshape":
                out = _reshape(jnp, ins[0], np.asarray(ins[1]), attrs)
            elif op == "Transpose":
                perm = attrs.get("perm")
                out = jnp.transpose(ins[0], perm)
            elif op == "Concat":
                out = jnp.concatenate(ins, axis=int(attrs["axis"]))
            elif op == "Squeeze":
                axes = attrs.get("axes")
                if axes is None and len(ins) > 1:
                    axes = [int(v) for v in np.asarray(ins[1]).tolist()]
                out = jnp.squeeze(ins[0], axis=tuple(axes) if axes else None)
            elif op == "Unsqueeze":
                axes = attrs.get("axes")
                if axes is None and len(ins) > 1:
                    axes = [int(v) for v in np.asarray(ins[1]).tolist()]
                # ONNX axes refer to the OUTPUT rank: normalize negatives
                # against it before applying in ascending order (a raw sort
                # would apply negatives against the not-yet-expanded rank and
                # misplace dims for mixed lists like [-3, 1] on 1-D input).
                out_rank = jnp.ndim(ins[0]) + len(axes)
                out = ins[0]
                for ax in sorted(a % out_rank for a in axes):
                    out = jnp.expand_dims(out, ax)
            elif op == "Clip":
                lo = ins[1] if len(ins) > 1 and ins[1] is not None else attrs.get("min")
                hi = ins[2] if len(ins) > 2 and ins[2] is not None else attrs.get("max")
                out = jnp.clip(ins[0], lo, hi)
            elif op in ("Identity", "Dropout"):
                out = ins[0]
            elif op == "Gather":
                out = jnp.take(
                    ins[0], ins[1].astype(jnp.int32), axis=int(attrs.get("axis", 0))
                )
            elif op == "ReduceMean":
                axes = attrs.get("axes")
                kd = bool(attrs.get("keepdims", 1))
                out = ins[0].mean(axis=tuple(axes) if axes else None, keepdims=kd)
            else:
                raise NotImplementedError(
                    f"ONNX op {op!r} not in the lowering table "
                    f"(node {node['name']!r})"
                )
            outs = node["output"]
            if len(outs) > 1:
                if op in ("Dropout", "BatchNormalization"):
                    outs = outs[:1]  # extra outputs are training-mode only
                else:
                    raise NotImplementedError(
                        f"ONNX op {op!r} with {len(outs)} outputs unsupported "
                        f"(node {node['name']!r})"
                    )
            env[outs[0]] = out

        return {o: env[o] for o in outputs}

    return apply_fn, params
