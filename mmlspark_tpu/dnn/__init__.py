"""Deep-network inference on TPU (reference ``cntk/`` — SURVEY.md §2.4).

The reference evaluates serialized CNTK graphs per-partition over JNI
(``cntk/CNTKModel.scala``). Here the model is a jittable JAX function +
params pytree evaluated in fixed-shape device batches; external graphs
arrive via :mod:`torch_import` (torch.fx → JAX) or :mod:`onnx_import`
(vendored protobuf decoder — no ``onnx`` package required).
"""

from mmlspark_tpu.dnn.model import DNNModel
from mmlspark_tpu.dnn.onnx_import import from_onnx
from mmlspark_tpu.dnn.torch_import import from_torch

__all__ = ["DNNModel", "from_onnx", "from_torch"]
