"""torch.fx → JAX graph import.

The reference ships models as serialized CNTK graphs evaluated over JNI
(``com/microsoft/CNTK/SerializableFunction.scala:17-143``). The TPU-native
equivalent of "bring an external deep net" is graph import into XLA: a
``torch.nn.Module`` is symbolically traced with ``torch.fx`` and each node
is lowered to a JAX op, producing a pure ``apply(params, inputs)`` function
that jits onto the MXU. No torch code runs at inference time — torch is
only the import-time frontend (the same role ONNX plays in SURVEY.md §7
step 5; see :mod:`mmlspark_tpu.dnn.onnx_import` for the gated ONNX path).

Covered op set: Conv2d (incl. groups/dilation), Linear, BatchNorm1d/2d
(eval), LayerNorm, ReLU/GELU/SiLU/Sigmoid/Tanh/Softmax, MaxPool2d,
AvgPool2d, AdaptiveAvgPool2d, Flatten/Dropout/Identity, residual adds,
cat, mul, and the common tensor methods (view/reshape/flatten/mean/
permute/transpose). Layout stays NCHW end-to-end — XLA relayouts for the
TPU convolution units itself.
"""

from __future__ import annotations

import operator
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


def _pair(v: Any) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return int(v[0]), int(v[1])
    return int(v), int(v)


def _conv2d(x, w, b, stride, padding, dilation, groups):
    import jax.numpy as jnp
    from jax import lax

    sh, sw = _pair(stride)
    dh, dw = _pair(dilation)
    if isinstance(padding, str):
        pad = padding.upper()  # 'same'/'valid'
    else:
        ph, pw = _pair(padding)
        pad = [(ph, ph), (pw, pw)]
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=(sh, sw),
        padding=pad,
        rhs_dilation=(dh, dw),
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        feature_group_count=int(groups),
    )
    if b is not None:
        out = out + b.reshape(1, -1, 1, 1)
    return out


def _linear(x, w, b):
    out = x @ w.T
    if b is not None:
        out = out + b
    return out


def _batch_norm(x, gamma, beta, mean, var, eps):
    shape = (1, -1) + (1,) * (x.ndim - 2)
    inv = (var + eps) ** -0.5
    out = (x - mean.reshape(shape)) * inv.reshape(shape)
    if gamma is not None:
        out = out * gamma.reshape(shape)
    if beta is not None:
        out = out + beta.reshape(shape)
    return out


def _layer_norm(x, normalized_shape, gamma, beta, eps):
    import jax.numpy as jnp

    axes = tuple(range(x.ndim - len(normalized_shape), x.ndim))
    mean = x.mean(axis=axes, keepdims=True)
    var = ((x - mean) ** 2).mean(axis=axes, keepdims=True)
    out = (x - mean) / jnp.sqrt(var + eps)
    if gamma is not None:
        out = out * gamma
    if beta is not None:
        out = out + beta
    return out


def _pool2d(x, kernel, stride, padding, reduce_fn, init, average: bool):
    from jax import lax

    kh, kw = _pair(kernel)
    sh, sw = _pair(stride if stride is not None else kernel)
    ph, pw = _pair(padding)
    window = (1, 1, kh, kw)
    strides = (1, 1, sh, sw)
    pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
    out = lax.reduce_window(x, init, reduce_fn, window, strides, pads)
    if average:
        out = out / float(kh * kw)
    return out


def _max_pool2d(x, kernel, stride=None, padding=0):
    from jax import lax

    return _pool2d(x, kernel, stride, padding, lax.max, -np.inf, average=False)


def _avg_pool2d(x, kernel, stride=None, padding=0):
    from jax import lax

    return _pool2d(x, kernel, stride, padding, lax.add, 0.0, average=True)


def _adaptive_avg_pool2d(x, output_size):
    oh, ow = _pair(output_size)
    h, w = x.shape[2], x.shape[3]
    if (oh, ow) == (1, 1):
        return x.mean(axis=(2, 3), keepdims=True)
    if h % oh or w % ow:
        raise NotImplementedError(
            f"adaptive_avg_pool2d: input {h}x{w} not divisible by output {oh}x{ow}"
        )
    x = x.reshape(x.shape[0], x.shape[1], oh, h // oh, ow, w // ow)
    return x.mean(axis=(3, 5))


def _softmax(x, dim=-1):
    import jax

    return jax.nn.softmax(x, axis=dim)


class _TorchGraph:
    """A traced torch graph lowered node-by-node at call time."""

    def __init__(self, graph_module: Any):
        import torch

        self.nodes = list(graph_module.graph.nodes)
        self.modules = dict(graph_module.named_modules())
        # Pull every parameter/buffer out of torch into numpy once; the
        # resulting pytree is the DNNModel ``modelParams``.
        self.params: Dict[str, Dict[str, np.ndarray]] = {}
        for name, mod in self.modules.items():
            entry: Dict[str, np.ndarray] = {}
            for p_name, p in mod.named_parameters(recurse=False):
                entry[p_name] = p.detach().cpu().numpy()
            for b_name, b in mod.named_buffers(recurse=False):
                entry[b_name] = b.detach().cpu().numpy()
            if entry:
                self.params[name] = entry
        self.attr_consts: Dict[str, np.ndarray] = {}
        for node in self.nodes:
            if node.op == "get_attr":
                obj = graph_module
                for part in node.target.split("."):
                    obj = getattr(obj, part)
                self.attr_consts[node.target] = obj.detach().cpu().numpy()
        self.input_names = [n.name for n in self.nodes if n.op == "placeholder"]

    # -- node lowering -------------------------------------------------------

    def _lower_module(self, mod: Any, p: Dict[str, Any], args: list, kwargs: dict):
        import torch.nn as nn

        x = args[0]
        if isinstance(mod, nn.Conv2d):
            return _conv2d(
                x, p["weight"], p.get("bias"), mod.stride, mod.padding,
                mod.dilation, mod.groups,
            )
        if isinstance(mod, nn.Linear):
            return _linear(x, p["weight"], p.get("bias"))
        if isinstance(mod, (nn.BatchNorm1d, nn.BatchNorm2d, nn.BatchNorm3d)):
            return _batch_norm(
                x, p.get("weight"), p.get("bias"), p["running_mean"],
                p["running_var"], mod.eps,
            )
        if isinstance(mod, nn.LayerNorm):
            return _layer_norm(
                x, tuple(mod.normalized_shape), p.get("weight"), p.get("bias"),
                mod.eps,
            )
        if isinstance(mod, nn.ReLU):
            import jax

            return jax.nn.relu(x)
        if isinstance(mod, nn.GELU):
            import jax

            return jax.nn.gelu(x, approximate=mod.approximate != "none")
        if isinstance(mod, nn.SiLU):
            import jax

            return jax.nn.silu(x)
        if isinstance(mod, nn.Sigmoid):
            import jax

            return jax.nn.sigmoid(x)
        if isinstance(mod, nn.Tanh):
            import jax.numpy as jnp

            return jnp.tanh(x)
        if isinstance(mod, nn.Softmax):
            return _softmax(x, mod.dim if mod.dim is not None else -1)
        if isinstance(mod, nn.MaxPool2d):
            return _max_pool2d(x, mod.kernel_size, mod.stride, mod.padding)
        if isinstance(mod, nn.AvgPool2d):
            return _avg_pool2d(x, mod.kernel_size, mod.stride, mod.padding)
        if isinstance(mod, nn.AdaptiveAvgPool2d):
            return _adaptive_avg_pool2d(x, mod.output_size)
        if isinstance(mod, nn.Flatten):
            lo = mod.start_dim
            hi = mod.end_dim if mod.end_dim != -1 else x.ndim - 1
            shape = x.shape[:lo] + (-1,) + x.shape[hi + 1 :]
            return x.reshape(shape)
        if isinstance(mod, (nn.Dropout, nn.Identity)):
            return x
        raise NotImplementedError(
            f"torch module {type(mod).__name__} has no JAX lowering"
        )

    def _lower_function(self, target: Any, args: list, kwargs: dict):
        import jax
        import jax.numpy as jnp
        import torch
        import torch.nn.functional as F

        table: Dict[Any, Callable] = {
            operator.add: lambda a, b: a + b,
            operator.sub: lambda a, b: a - b,
            operator.mul: lambda a, b: a * b,
            operator.truediv: lambda a, b: a / b,
            operator.matmul: lambda a, b: a @ b,
            torch.add: lambda a, b: a + b,
            torch.mul: lambda a, b: a * b,
            torch.relu: jax.nn.relu,
            F.relu: lambda x, inplace=False: jax.nn.relu(x),
            F.gelu: lambda x, approximate="none": jax.nn.gelu(
                x, approximate=approximate != "none"
            ),
            F.silu: lambda x, inplace=False: jax.nn.silu(x),
            torch.sigmoid: jax.nn.sigmoid,
            F.sigmoid: jax.nn.sigmoid,
            torch.tanh: jnp.tanh,
            F.softmax: _softmax,
            F.max_pool2d: _max_pool2d,
            F.avg_pool2d: _avg_pool2d,
            F.adaptive_avg_pool2d: _adaptive_avg_pool2d,
            F.linear: _linear,
            torch.flatten: lambda x, start_dim=0, end_dim=-1: x.reshape(
                x.shape[:start_dim] + (-1,)
            )
            if end_dim in (-1, x.ndim - 1)
            else x,
            torch.cat: lambda ts, dim=0: jnp.concatenate(ts, axis=dim),
            torch.mean: lambda x, dim=None, keepdim=False: x.mean(
                axis=dim, keepdims=keepdim
            ),
        }
        if target in table:
            return table[target](*args, **kwargs)
        raise NotImplementedError(f"torch function {target} has no JAX lowering")

    def _lower_method(self, name: str, args: list, kwargs: dict):
        import jax.numpy as jnp

        x = args[0]
        rest = args[1:]
        if name in ("view", "reshape"):
            shape = rest[0] if len(rest) == 1 and isinstance(rest[0], (tuple, list)) else rest
            return x.reshape(tuple(int(s) for s in shape))
        if name == "flatten":
            start = rest[0] if rest else 0
            return x.reshape(x.shape[:start] + (-1,))
        if name == "mean":
            return x.mean(axis=rest[0] if rest else None, **kwargs)
        if name == "permute":
            return jnp.transpose(x, rest)
        if name == "transpose":
            perm = list(range(x.ndim))
            perm[rest[0]], perm[rest[1]] = perm[rest[1]], perm[rest[0]]
            return jnp.transpose(x, perm)
        if name == "contiguous":
            return x
        if name == "size":
            return x.shape[rest[0]] if rest else x.shape
        if name == "add":
            return x + rest[0]
        if name == "mul":
            return x * rest[0]
        raise NotImplementedError(f"tensor method .{name}() has no JAX lowering")

    # -- execution -----------------------------------------------------------

    def __call__(self, params: Dict[str, Dict[str, Any]], inputs: Dict[str, Any]):
        env: Dict[Any, Any] = {}

        def resolve(v: Any) -> Any:
            import torch.fx as fx

            if isinstance(v, fx.Node):
                return env[v]
            if isinstance(v, (list, tuple)):
                return type(v)(resolve(x) for x in v)
            return v

        out = None
        for node in self.nodes:
            if node.op == "placeholder":
                if node.name not in inputs:
                    raise KeyError(
                        f"missing model input {node.name!r}; have {sorted(inputs)}"
                    )
                env[node] = inputs[node.name]
            elif node.op == "get_attr":
                env[node] = self.attr_consts[node.target]
            elif node.op == "call_module":
                mod = self.modules[node.target]
                p = params.get(node.target, {})
                env[node] = self._lower_module(
                    mod, p, [resolve(a) for a in node.args],
                    {k: resolve(v) for k, v in node.kwargs.items()},
                )
            elif node.op == "call_function":
                env[node] = self._lower_function(
                    node.target, [resolve(a) for a in node.args],
                    {k: resolve(v) for k, v in node.kwargs.items()},
                )
            elif node.op == "call_method":
                env[node] = self._lower_method(
                    node.target, [resolve(a) for a in node.args],
                    {k: resolve(v) for k, v in node.kwargs.items()},
                )
            elif node.op == "output":
                out = resolve(node.args[0])
            else:  # pragma: no cover
                raise NotImplementedError(f"fx op {node.op}")
        return out


def from_torch(
    module: Any, single_input_name: str = "input", single_output_name: str = "output"
) -> Tuple[Callable, Dict[str, Dict[str, np.ndarray]]]:
    """Trace a ``torch.nn.Module`` and return ``(apply_fn, params)``.

    ``apply_fn(params, {input_name: array}) -> {output_name: array}`` is pure
    and jittable; ``params`` is a plain dict pytree of numpy arrays. Feed
    both straight into :class:`~mmlspark_tpu.dnn.model.DNNModel`:

        fn, params = from_torch(resnet.eval())
        DNNModel(applyFn=fn, modelParams=params,
                 feedDict={"input": "images"}, fetchDict={"scores": "output"})
    """
    import torch
    import torch.fx as fx

    module = module.eval()
    graph_module = fx.symbolic_trace(module)
    lowered = _TorchGraph(graph_module)

    names = lowered.input_names
    if len(names) == 1 and names[0] != single_input_name:
        rename = {single_input_name: names[0]}
    else:
        rename = {}

    def apply_fn(params, inputs):
        mapped = {rename.get(k, k): v for k, v in inputs.items()}
        result = lowered(params, mapped)
        if isinstance(result, dict):
            return result
        if isinstance(result, (list, tuple)):
            return {f"{single_output_name}_{i}": r for i, r in enumerate(result)}
        return {single_output_name: result}

    return apply_fn, lowered.params
