"""Scheduler/executor metrics — per-task timings, retry counts, queue depth.

Follows ``core/profiling.py`` conventions: an accumulating object with
``summary()`` returning a plain dict and ``log(logger, prefix)`` emitting
through :func:`~mmlspark_tpu.core.profiling.get_logger`, exactly like
:class:`~mmlspark_tpu.core.profiling.StopWatch` (aggregate queue-wait/run
phase times ride an embedded StopWatch, so existing log tooling applies).

Every ``note_*`` also feeds the process-global
:class:`~mmlspark_tpu.observability.registry.MetricsRegistry` (counters
named ``scheduler_*``, queue-wait/run latency histograms), so a serving
endpoint's ``GET /metrics`` scrape carries scheduler state without any
extra wiring; pass an explicit ``registry`` for an isolated one (tests
assert registry counters equal :meth:`summary` exactly).
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import Dict, Optional

from mmlspark_tpu.core.profiling import StopWatch, get_logger
from mmlspark_tpu.observability.registry import MetricsRegistry, get_registry


class RuntimeMetrics:
    """Thread-safe counters/timings for one scheduler (accumulates across
    jobs when the scheduler is reused, e.g. the serving dispatch loop)."""

    def __init__(self, registry: Optional[MetricsRegistry] = None) -> None:
        self._lock = threading.Lock()
        self.stopwatch = StopWatch()  # aggregate "queue_wait"/"run" phases
        #: task index -> {"queue_wait": s, "run": s, "attempts": n}
        self.task_timings: Dict[int, Dict[str, float]] = {}
        self.retries: "collections.Counter[int]" = collections.Counter()
        self.counters: "collections.Counter[str]" = collections.Counter()
        self.max_queue_depth = 0
        # registry bridge: the same counts, scrapeable (docs/observability.md)
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self._reg_tasks_done = reg.counter(
            "scheduler_tasks_done_total", "Tasks completed successfully"
        )
        self._reg_dispatches = reg.counter(
            "scheduler_dispatches_total", "Attempts handed to the executor pool"
        )
        self._reg_retries = reg.counter(
            "scheduler_retries_total", "Task re-dispatches after a failure"
        )
        self._reg_failures = reg.counter(
            "scheduler_failures_total",
            "Attempt failures by reason (error/executor_death/timeout/heartbeat)",
        )
        self._reg_recomputes = reg.counter(
            "scheduler_lineage_recomputes_total",
            "Lost partitions rebuilt from lineage",
        )
        self._reg_wasted = reg.counter(
            "scheduler_wasted_results_total",
            "Superseded attempts whose late result was discarded",
        )
        self._reg_queue_depth = reg.gauge(
            "scheduler_max_queue_depth", "High-water executor queue depth"
        )
        self._reg_spec_launched = reg.counter(
            "scheduler_speculative_launched_total",
            "Speculative duplicate attempts launched against stragglers",
        )
        self._reg_spec_wins = reg.counter(
            "scheduler_speculative_wins_total",
            "Tasks whose speculative copy finished first",
        )
        self._reg_recovered = reg.counter(
            "scheduler_tasks_recovered_total",
            "Tasks restored from journal checkpoints (zero re-execution)",
        )
        self._reg_quarantines = reg.counter(
            "scheduler_quarantines_total",
            "Workers quarantined by the health tracker",
        )
        self._reg_paroles = reg.counter(
            "scheduler_paroles_total",
            "Quarantined workers paroled back into the pool",
        )
        self._reg_quarantined_now = reg.gauge(
            "scheduler_quarantined_workers", "Workers currently quarantined"
        )
        self._reg_queue_wait = reg.histogram(
            "scheduler_task_queue_wait_seconds", "Dispatch-to-start wait per attempt"
        )
        self._reg_run = reg.histogram(
            "scheduler_task_run_seconds", "Run time of successful attempts"
        )

    # -- recording (called by the scheduler/executors) ----------------------

    def note_dispatch(self, index: int, queue_depth: int) -> None:
        with self._lock:
            self.counters["dispatches"] += 1
            self.max_queue_depth = max(self.max_queue_depth, queue_depth)
        self._reg_dispatches.inc()
        self._reg_queue_depth.set_max(queue_depth)

    def note_start(self, index: int, queue_wait: float) -> None:
        with self._lock:
            t = self.task_timings.setdefault(
                index, {"queue_wait": 0.0, "run": 0.0, "attempts": 0}
            )
            t["queue_wait"] += queue_wait
            t["attempts"] += 1
        self._accumulate_phase("queue_wait", queue_wait)
        self._reg_queue_wait.observe(queue_wait)

    def note_done(self, index: int, run_seconds: float) -> None:
        with self._lock:
            t = self.task_timings.setdefault(
                index, {"queue_wait": 0.0, "run": 0.0, "attempts": 1}
            )
            t["run"] += run_seconds
            self.counters["tasks_done"] += 1
        self._accumulate_phase("run", run_seconds)
        self._reg_tasks_done.inc()
        self._reg_run.observe(run_seconds)

    def _accumulate_phase(self, phase: str, seconds: float) -> None:
        # externally timed spans fold into the same phase table so
        # sw.log()/summary() work (StopWatch.add is the public form)
        self.stopwatch.add(phase, seconds)

    def note_retry(self, index: int) -> None:
        with self._lock:
            self.retries[index] += 1
            self.counters["retries_total"] += 1
        self._reg_retries.inc()

    def note_failure(self, index: int, reason: str) -> None:
        """reason: 'error' | 'executor_death' | 'timeout' | 'heartbeat' |
        'corrupt' (result failed the end-to-end CRC check)."""
        with self._lock:
            self.counters["failures_total"] += 1
            self.counters[f"failures_{reason}"] += 1
        self._reg_failures.labels(reason=reason).inc()

    def note_recompute(self, index: int) -> None:
        with self._lock:
            self.counters["lineage_recomputes"] += 1
        self._reg_recomputes.inc()

    def note_wasted_result(self) -> None:
        """A superseded attempt (timeout / heartbeat loss / lost race)
        reported late; its result was discarded."""
        with self._lock:
            self.counters["wasted_results"] += 1
        self._reg_wasted.inc()

    def note_speculative_launch(self, index: int) -> None:
        with self._lock:
            self.counters["speculative_launched"] += 1
        self._reg_spec_launched.inc()

    def note_speculative_win(self, index: int) -> None:
        """A speculative duplicate finished before the original attempt."""
        with self._lock:
            self.counters["speculative_wins"] += 1
        self._reg_spec_wins.inc()

    def note_recovered(self, index: int) -> None:
        """A task restored from a journal checkpoint without dispatch."""
        with self._lock:
            self.counters["tasks_recovered"] += 1
        self._reg_recovered.inc()

    def note_quarantine(self, worker_id: int) -> None:
        with self._lock:
            self.counters["quarantines"] += 1
            n = self.counters["quarantines"] - self.counters["paroles"]
        self._reg_quarantines.inc()
        self._reg_quarantined_now.set(max(0, n))

    def note_parole(self, worker_id: int) -> None:
        with self._lock:
            self.counters["paroles"] += 1
            n = self.counters["quarantines"] - self.counters["paroles"]
        self._reg_paroles.inc()
        self._reg_quarantined_now.set(max(0, n))

    # -- reporting (core/profiling conventions) -----------------------------

    @property
    def retries_total(self) -> int:
        return self.counters["retries_total"]

    def summary(self) -> dict:
        with self._lock:
            return {
                "tasks_done": self.counters["tasks_done"],
                "dispatches": self.counters["dispatches"],
                "retries_total": self.counters["retries_total"],
                "failures_total": self.counters["failures_total"],
                "failures_error": self.counters["failures_error"],
                "failures_heartbeat": self.counters["failures_heartbeat"],
                "failures_timeout": self.counters["failures_timeout"],
                "failures_executor_death": self.counters["failures_executor_death"],
                "failures_corrupt": self.counters["failures_corrupt"],
                "lineage_recomputes": self.counters["lineage_recomputes"],
                "wasted_results": self.counters["wasted_results"],
                "speculative_launched": self.counters["speculative_launched"],
                "speculative_wins": self.counters["speculative_wins"],
                "tasks_recovered": self.counters["tasks_recovered"],
                "quarantines": self.counters["quarantines"],
                "paroles": self.counters["paroles"],
                "max_queue_depth": self.max_queue_depth,
                "phases": self.stopwatch.summary(),
                "per_task": {i: dict(t) for i, t in self.task_timings.items()},
                "retries_per_task": dict(self.retries),
            }

    def log(self, logger: Optional[logging.Logger] = None, prefix: str = "") -> None:
        logger = logger or get_logger("mmlspark_tpu.runtime")
        s = self.summary()
        logger.info(
            "%stasks=%d dispatches=%d retries=%d failures=%d "
            "(heartbeat=%d timeout=%d death=%d) recomputes=%d "
            "speculative=%d/%d recovered=%d quarantines=%d "
            "max_queue_depth=%d",
            prefix, s["tasks_done"], s["dispatches"], s["retries_total"],
            s["failures_total"], s["failures_heartbeat"], s["failures_timeout"],
            s["failures_executor_death"], s["lineage_recomputes"],
            s["speculative_wins"], s["speculative_launched"],
            s["tasks_recovered"], s["quarantines"],
            s["max_queue_depth"],
        )
        self.stopwatch.log(logger, prefix=prefix)
