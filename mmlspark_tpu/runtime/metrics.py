"""Scheduler/executor metrics — per-task timings, retry counts, queue depth.

Follows ``core/profiling.py`` conventions: an accumulating object with
``summary()`` returning a plain dict and ``log(logger, prefix)`` emitting
through :func:`~mmlspark_tpu.core.profiling.get_logger`, exactly like
:class:`~mmlspark_tpu.core.profiling.StopWatch` (aggregate queue-wait/run
phase times ride an embedded StopWatch, so existing log tooling applies).
"""

from __future__ import annotations

import collections
import logging
import threading
from typing import Dict, Optional

from mmlspark_tpu.core.profiling import StopWatch, get_logger


class RuntimeMetrics:
    """Thread-safe counters/timings for one scheduler (accumulates across
    jobs when the scheduler is reused, e.g. the serving dispatch loop)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.stopwatch = StopWatch()  # aggregate "queue_wait"/"run" phases
        #: task index -> {"queue_wait": s, "run": s, "attempts": n}
        self.task_timings: Dict[int, Dict[str, float]] = {}
        self.retries: "collections.Counter[int]" = collections.Counter()
        self.counters: "collections.Counter[str]" = collections.Counter()
        self.max_queue_depth = 0

    # -- recording (called by the scheduler/executors) ----------------------

    def note_dispatch(self, index: int, queue_depth: int) -> None:
        with self._lock:
            self.counters["dispatches"] += 1
            self.max_queue_depth = max(self.max_queue_depth, queue_depth)

    def note_start(self, index: int, queue_wait: float) -> None:
        with self._lock:
            t = self.task_timings.setdefault(
                index, {"queue_wait": 0.0, "run": 0.0, "attempts": 0}
            )
            t["queue_wait"] += queue_wait
            t["attempts"] += 1
        self._accumulate_phase("queue_wait", queue_wait)

    def note_done(self, index: int, run_seconds: float) -> None:
        with self._lock:
            t = self.task_timings.setdefault(
                index, {"queue_wait": 0.0, "run": 0.0, "attempts": 1}
            )
            t["run"] += run_seconds
            self.counters["tasks_done"] += 1
        self._accumulate_phase("run", run_seconds)

    def _accumulate_phase(self, phase: str, seconds: float) -> None:
        # StopWatch only accumulates through measure(); fold externally
        # timed spans into the same phase table so sw.log()/summary() work
        totals = self.stopwatch._totals
        totals[phase] = totals.get(phase, 0.0) + seconds

    def note_retry(self, index: int) -> None:
        with self._lock:
            self.retries[index] += 1
            self.counters["retries_total"] += 1

    def note_failure(self, index: int, reason: str) -> None:
        """reason: 'error' | 'executor_death' | 'timeout' | 'heartbeat'."""
        with self._lock:
            self.counters["failures_total"] += 1
            self.counters[f"failures_{reason}"] += 1

    def note_recompute(self, index: int) -> None:
        with self._lock:
            self.counters["lineage_recomputes"] += 1

    def note_wasted_result(self) -> None:
        """A superseded attempt (timeout / heartbeat loss) reported late;
        its result was discarded."""
        with self._lock:
            self.counters["wasted_results"] += 1

    # -- reporting (core/profiling conventions) -----------------------------

    @property
    def retries_total(self) -> int:
        return self.counters["retries_total"]

    def summary(self) -> dict:
        with self._lock:
            return {
                "tasks_done": self.counters["tasks_done"],
                "dispatches": self.counters["dispatches"],
                "retries_total": self.counters["retries_total"],
                "failures_total": self.counters["failures_total"],
                "failures_error": self.counters["failures_error"],
                "failures_heartbeat": self.counters["failures_heartbeat"],
                "failures_timeout": self.counters["failures_timeout"],
                "failures_executor_death": self.counters["failures_executor_death"],
                "lineage_recomputes": self.counters["lineage_recomputes"],
                "wasted_results": self.counters["wasted_results"],
                "max_queue_depth": self.max_queue_depth,
                "phases": self.stopwatch.summary(),
                "per_task": {i: dict(t) for i, t in self.task_timings.items()},
                "retries_per_task": dict(self.retries),
            }

    def log(self, logger: Optional[logging.Logger] = None, prefix: str = "") -> None:
        logger = logger or get_logger("mmlspark_tpu.runtime")
        s = self.summary()
        logger.info(
            "%stasks=%d dispatches=%d retries=%d failures=%d "
            "(heartbeat=%d timeout=%d death=%d) recomputes=%d "
            "max_queue_depth=%d",
            prefix, s["tasks_done"], s["dispatches"], s["retries_total"],
            s["failures_total"], s["failures_heartbeat"], s["failures_timeout"],
            s["failures_executor_death"], s["lineage_recomputes"],
            s["max_queue_depth"],
        )
        self.stopwatch.log(logger, prefix=prefix)
