"""Executor health tracking and quarantine — the BlacklistTracker analogue.

Spark's ``BlacklistTracker`` (``spark.blacklist.*``, later
``spark.excludeOnFailure.*``) stops scheduling tasks on executors that
keep failing: failures are counted per executor over a rolling window,
an executor crossing the threshold is excluded from new task dispatch,
and a timeout paroles it back into the pool. This module is that policy
for the thread-based runtime:

- every attempt failure (error / timeout / heartbeat loss / corrupt
  result) books ``1.0`` against the worker that ran it; an OOM failure
  books ``oom_weight`` (default 2.0 — a worker that keeps exhausting
  memory poisons every task placed on it, the posture of Spark's
  OOM-aware ``excludeOnFailure``); being overtaken by a speculative
  copy books ``straggle_weight`` (chronic slowness is a health signal
  too, at a discount);
- scores are summed over a rolling ``window_s`` window; a worker at or
  above ``threshold`` is quarantined: the executor pool refuses to hand
  it new attempts (:meth:`ExecutorPool._admit`) until ``parole_s``
  elapses, when its history is wiped and it rejoins the fleet;
- if every alive worker is quarantined the scheduler fails fast with
  :class:`~mmlspark_tpu.runtime.scheduler.AllWorkersQuarantinedError`
  (Spark's "cannot run anywhere due to node and executor blacklist")
  unless the policy opts into waiting for parole.

The clock is injectable so quarantine/parole tests run on a fake clock
with zero real sleeps. Thread-safe: workers consult it from their pull
loops while the driver books failures from completion callbacks.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Set, Tuple


class HealthTracker:
    """Rolling-window per-worker failure scores with timed quarantine.

    ``metrics`` (a :class:`~mmlspark_tpu.runtime.metrics.RuntimeMetrics`)
    and ``on_quarantine`` / ``on_parole`` callbacks are optional — the
    scheduler wires them to the metrics registry and the event bus.
    """

    def __init__(
        self,
        threshold: float = 3.0,
        window_s: float = 60.0,
        parole_s: float = 30.0,
        straggle_weight: float = 0.5,
        oom_weight: float = 2.0,
        partition_weight: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        metrics=None,
        on_quarantine: Optional[Callable[[int, float], None]] = None,
        on_parole: Optional[Callable[[int], None]] = None,
    ):
        if threshold <= 0:
            raise ValueError(f"threshold must be > 0, got {threshold}")
        self.threshold = float(threshold)
        self.window_s = float(window_s)
        self.parole_s = float(parole_s)
        self.straggle_weight = float(straggle_weight)
        self.oom_weight = float(oom_weight)
        self.partition_weight = float(partition_weight)
        self.clock = clock
        self.metrics = metrics
        self.on_quarantine = on_quarantine
        self.on_parole = on_parole
        self._lock = threading.Lock()
        #: worker id -> deque[(t, weight)] within the rolling window
        self._events: Dict[int, Deque[Tuple[float, float]]] = {}
        #: worker id -> parole time (quarantine ends)
        self._quarantined: Dict[int, float] = {}
        #: total quarantines/paroles (monotonic, for summaries)
        self.quarantines = 0
        self.paroles = 0

    # -- scoring -------------------------------------------------------------

    def note_failure(self, worker_id: Optional[int], reason: str = "error") -> None:
        """Book one attempt failure against ``worker_id`` (None = the
        attempt never reached a worker; nothing to book). OOM failures
        score ``oom_weight`` — memory exhaustion on a worker predicts
        exhaustion for whatever lands there next — and partition blame
        scores ``partition_weight``: a member the gang voted off for
        stalling the collective will stall the re-formed gang too."""
        if worker_id is not None:
            weight = 1.0
            if reason == "oom":
                weight = self.oom_weight
            elif reason == "partition":
                weight = self.partition_weight
            self._book(int(worker_id), weight)

    def note_straggle(self, worker_id: Optional[int]) -> None:
        """The worker's attempt was overtaken by a speculative copy."""
        if worker_id is not None:
            self._book(int(worker_id), self.straggle_weight)

    def _book(self, wid: int, weight: float) -> None:
        fire: Optional[Tuple[int, float]] = None
        with self._lock:
            now = self.clock()
            if wid in self._quarantined:
                return  # already out of the pool; don't extend the sentence
            q = self._events.setdefault(wid, collections.deque())
            q.append((now, weight))
            self._trim(q, now)
            score = sum(w for _, w in q)
            if score >= self.threshold:
                self._quarantined[wid] = now + self.parole_s
                q.clear()
                self.quarantines += 1
                fire = (wid, score)
        if fire is not None:
            if self.metrics is not None:
                self.metrics.note_quarantine(fire[0])
            if self.on_quarantine is not None:
                self.on_quarantine(fire[0], fire[1])

    def _trim(self, q: Deque[Tuple[float, float]], now: float) -> None:
        while q and now - q[0][0] > self.window_s:
            q.popleft()

    def score(self, worker_id: int) -> float:
        with self._lock:
            q = self._events.get(int(worker_id))
            if not q:
                return 0.0
            self._trim(q, self.clock())
            return sum(w for _, w in q)

    # -- quarantine state ----------------------------------------------------

    def is_quarantined(self, worker_id: int) -> bool:
        """True while the worker is serving its quarantine; checking after
        the parole time paroles it (history wiped, callbacks fired)."""
        wid = int(worker_id)
        paroled = False
        with self._lock:
            until = self._quarantined.get(wid)
            if until is None:
                return False
            if self.clock() < until:
                return True
            del self._quarantined[wid]
            self._events.pop(wid, None)
            self.paroles += 1
            paroled = True
        if paroled:
            if self.metrics is not None:
                self.metrics.note_parole(wid)
            if self.on_parole is not None:
                self.on_parole(wid)
        return False

    def quarantined_workers(self) -> Set[int]:
        """Worker ids currently quarantined (parole checks applied)."""
        with self._lock:
            wids = list(self._quarantined)
        return {w for w in wids if self.is_quarantined(w)}

    def all_quarantined(self, worker_ids: List[int]) -> bool:
        """True when ``worker_ids`` is non-empty and every one of them is
        quarantined — the fail-fast condition."""
        if not worker_ids:
            return False
        return all(self.is_quarantined(w) for w in worker_ids)

    def next_parole_in(self) -> Optional[float]:
        """Seconds until the earliest quarantined worker paroles (None
        when nobody is quarantined) — the driver's wait bound."""
        with self._lock:
            if not self._quarantined:
                return None
            return max(0.0, min(self._quarantined.values()) - self.clock())
