"""Worker-side enactment of gang network-degradation directives.

The driver serializes :meth:`FaultPlan.net_partition` / ``net_delay`` /
``net_drop`` / ``net_corrupt`` directives into the epoch spec
(``net_faults`` + ``net_seed``); each gang member builds one
:class:`NetChaos` from them and hands it to its
:class:`~mmlspark_tpu.runtime.procgroup.AllreduceGroup`, which consults
:meth:`NetChaos.on_send` for every outgoing frame. The degradation is
therefore enacted at the real socket boundary of the collective — the
same frames, the same rounds — with no live ``FaultPlan`` object in the
worker (mirroring ``FaultPlan.should_die``).

Determinism: the drop RNG is seeded from ``(net_seed, member, epoch)``,
so a pinned ``MMLSPARK_TPU_FAULT_SEED`` replays the exact same frame
losses run after run. Corruption happens *after* the sender checksums
the frame, so the receiver's CRC check sees a genuine wire flip.

A partition swallows frames in *both* directions (each side filters its
own sends), which is what makes the failure gray: neither peer errors,
both just stop hearing from each other, and only the collective's io
deadline — never a blocked ``recv`` — ends the round.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np


def corrupt_bytes(data: bytes) -> bytes:
    """A length-preserving wire flip of ``data`` (first byte XOR 0xFF) —
    shared by the gang frame path and the HTTP response-corruption path
    so both chaos modes garble payloads the same way."""
    if not data:
        return data
    bad = bytearray(data)
    bad[0] ^= 0xFF
    return bytes(bad)


class NetChaos:
    """Per-member network degradation for one gang epoch.

    ``directives`` is the ``net_faults`` list from the epoch spec;
    entries for other members/epochs are ignored, so every member can be
    handed the same list. ``enacted`` records what actually fired as
    ``(kind, round)`` pairs — the worker ships it back in its revoked /
    done report so the driver can mark the plan's directives fired.
    """

    def __init__(
        self,
        directives: List[Dict[str, Any]],
        member: int,
        epoch: int,
        seed: int = 0,
    ):
        self.member = int(member)
        self.epoch = int(epoch)
        self._rng = np.random.default_rng(
            (int(seed) * 1_000_003 + self.member * 8191 + self.epoch)
            & 0xFFFFFFFF
        )
        #: (peer, after_round) pairs this member stops talking to
        self._partitions: List[tuple] = []
        self._delay_ms = 0.0
        self._drops: List[float] = []
        self._corrupt_left = 0
        self.enacted: List[tuple] = []
        for d in directives or []:
            if int(d.get("epoch", 0)) != self.epoch:
                continue
            kind = d.get("kind")
            if kind == "partition":
                a, b = int(d.get("a", -1)), int(d.get("b", -1))
                if self.member == a:
                    self._partitions.append((b, int(d.get("after_round", 0))))
                elif self.member == b:
                    self._partitions.append((a, int(d.get("after_round", 0))))
            elif int(d.get("member", -1)) != self.member:
                continue
            elif kind == "delay":
                self._delay_ms += float(d.get("ms", 0.0))
            elif kind == "drop":
                self._drops.append(float(d.get("p", 0.0)))
            elif kind == "corrupt":
                self._corrupt_left += int(d.get("n", 1))

    @property
    def active(self) -> bool:
        return bool(
            self._partitions or self._delay_ms
            or self._drops or self._corrupt_left
        )

    def partitioned(self, peer: int, round_no: int) -> bool:
        return any(
            int(peer) == p and int(round_no) >= after
            for p, after in self._partitions
        )

    def on_send(
        self, peer: int, round_no: int, payload: bytes
    ) -> Optional[bytes]:
        """The wire between this member and ``peer`` for one outgoing
        frame: returns the bytes to actually send (possibly delayed or
        corrupted), or None when the frame is swallowed (partition /
        drop) — the sender then simply doesn't send, and the peer's io
        deadline is what notices."""
        if self.partitioned(peer, round_no):
            self.enacted.append(("partition", int(round_no)))
            return None
        if any(float(self._rng.random()) < p for p in self._drops):
            self.enacted.append(("drop", int(round_no)))
            return None
        if self._delay_ms > 0.0:
            self.enacted.append(("delay", int(round_no)))
            time.sleep(self._delay_ms / 1000.0)
        if self._corrupt_left > 0:
            self._corrupt_left -= 1
            self.enacted.append(("corrupt", int(round_no)))
            return corrupt_bytes(payload)
        return payload
