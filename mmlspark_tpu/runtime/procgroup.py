"""Process-group supervisor: real OS processes, rendezvous, gang recovery.

Everything below ``runtime/`` so far exercised fault tolerance against
worker *threads*; the reference framework's failure domain is the worker
*process* (a lost JVM executor). This module closes that gap: the driver
supervises N genuine child processes that rendezvous through
``jax.distributed`` (the executor-keyed convention of
``parallel/mesh.py``), exchange histograms over a LightGBM-style socket
allreduce, heartbeat through the group workdir, and — the point — survive
one of their number being SIGKILL'd mid-collective.

Roles:

- :func:`pick_port` — seeded, bind-probed port picker (deterministic
  chaos runs need reproducible rendezvous addresses; TOCTOU losers are
  healed by the epoch retry loop);
- :class:`AllreduceGroup` — star-topology sum-allreduce over TCP
  (rank 0 accumulates and broadcasts; LightGBM's socket collective
  reduced to the one op GBDT fit needs). Round counters in the frame
  header catch desynchronized members; any socket failure raises
  :class:`GroupRevokedError`;
- :func:`worker_main` — the child-process entry loop: wait for an epoch
  spec naming this member -> rendezvous -> form the socket group ->
  *release the jax.distributed client while everyone is alive* -> run the
  payload -> commit barrier -> report. On revocation, clear XLA backends
  and wait for the next epoch spec;
- :class:`ProcessGroup` — the driver: spawn/respawn members, watch
  heartbeats and exit statuses, translate deaths into
  ``ProcessLost``/``GroupReformed`` events and
  :class:`~mmlspark_tpu.runtime.health.HealthTracker` bookings, and
  re-form the gang with a respawned (or, when quarantined, dropped)
  membership.

Why the client release (step between group formation and payload): the
CPU coordination service fatally aborts any process whose peer dies while
the distributed client is live — gang recovery is impossible with the
client up. On this backend the client's only job is rendezvous, so each
epoch uses it for exactly that and then shuts it down cleanly; peer death
afterwards surfaces as a catchable socket error in the allreduce.

Protocol files in the group workdir (all JSON, atomically renamed in):

====================  =======================================================
``epoch-<k>.json``    driver -> workers: membership, ports, entry, payload
``hb-<m>``            worker heartbeat (driver checks mtime staleness)
``ready-<k>-<m>``     member m formed epoch k (rendezvous + group + release)
``done-<k>-<m>.json`` member m's payload finished; carries the result
``revoked-<k>-<m>``   member m observed epoch k revoked (peer loss/timeout)
``failed-<k>-<m>``    member m's payload raised (a bug, not a fault)
``log-<m>-<g>.txt``   stdout/stderr of member m, generation g
``stop``              driver -> workers: exit cleanly
====================  =======================================================
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
import traceback
import zlib
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.runtime.faults import FaultPlan, current_faults
from mmlspark_tpu.runtime.health import HealthTracker
from mmlspark_tpu.runtime.journal import _atomic_write

logger = get_logger("mmlspark_tpu.runtime.procgroup")

#: env vars that must not leak into CPU worker processes (accelerator
#: runtime hooks wedge the child before it reaches the rendezvous)
_SCRUB_PREFIXES = ("PALLAS_AXON", "AXON", "TPU_")
_SCRUB_EXACT = ("XLA_FLAGS",)


class GroupRevokedError(RuntimeError):
    """The current gang epoch is dead: a peer was lost mid-collective (or
    the rendezvous timed out). Not a payload bug — the worker reports the
    revocation and waits for the re-formed epoch.

    ``suspect`` (when the collective could attribute the failure) is the
    stable member id of the peer this process blames — a non-root always
    blames the star center, rank 0 blames the member on the failed
    connection. ``stats`` is the collective's retransmit/CRC/slow-peer
    tally at death. Both ride the worker's revoked report so the driver
    can pick the victim by vote."""

    suspect: Optional[int] = None
    stats: Optional[Dict[str, Any]] = None


class GangFailedError(RuntimeError):
    """The supervisor ran out of recovery options: no live membership
    left, or the epoch budget was exhausted without a successful fit."""


def scrub_env(env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """A child-process environment with accelerator hooks stripped and the
    backend pinned to CPU (the posture every multi-process CPU test and
    smoke tool needs; override ``JAX_PLATFORMS`` after the call to target
    real hardware)."""
    base = dict(os.environ if env is None else env)
    out = {
        k: v for k, v in base.items()
        if k not in _SCRUB_EXACT and not k.startswith(_SCRUB_PREFIXES)
    }
    out["JAX_PLATFORMS"] = "cpu"
    # children run with cwd=workdir; make this package importable even
    # when it is used from a source checkout rather than installed
    pkg_root = str(Path(__file__).resolve().parents[2])
    parts = [pkg_root] + [
        p for p in out.get("PYTHONPATH", "").split(os.pathsep) if p
    ]
    out["PYTHONPATH"] = os.pathsep.join(dict.fromkeys(parts))
    return out


def pick_port(
    seed: Optional[int] = None,
    attempts: int = 64,
    low: int = 20001,
    high: int = 59999,
    exclude: Optional[Sequence[int]] = None,
) -> int:
    """Seeded, bind-probed free-port picker.

    ``random.randint`` port pickers make chaos runs unreproducible and
    bare ``bind(0)`` pickers hand back ports that another picker grabs in
    the gap — this draws candidates from a seeded RNG and *proves* each by
    binding it before returning. The TOCTOU window between probe and the
    worker's real bind still exists; callers heal a lost race by retrying
    with the next epoch/attempt (which advances the seed).
    """
    rng = np.random.default_rng(seed)
    skip = set(int(p) for p in (exclude or ()))
    last_err: Optional[OSError] = None
    for _ in range(attempts):
        port = int(rng.integers(low, high))
        if port in skip:
            continue
        probe = socket.socket()
        try:
            probe.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            probe.bind(("127.0.0.1", port))
        except OSError as e:
            last_err = e
            continue
        finally:
            probe.close()
        return port
    raise OSError(
        f"no free port in [{low}, {high}] after {attempts} seeded probes"
    ) from last_err


# -- socket allreduce ---------------------------------------------------------


class AllreduceGroup:
    """Star-topology float32 sum-allreduce over localhost TCP.

    Rank 0 binds ``port``, accepts ``world - 1`` connections, sums the
    incoming buffers and broadcasts the total; other ranks send and
    receive. Every frame is ``(round, nbytes, crc32)`` + payload and is
    acknowledged: the receiver verifies the payload CRC and answers ACK,
    or NAK for a wire-corrupted frame, which the sender answers with a
    bounded retransmit (``max_retransmits``) of the clean bytes — a
    flipped bit degrades to one extra round trip instead of a corrupt
    histogram. A round-counter mismatch means the members desynchronized
    (one resumed a different iteration) and revokes the group rather
    than silently mixing histograms from different trees.

    Deadlines, not hangs: formation runs under ``timeout`` and every
    per-round socket op under ``io_timeout``, so a partitioned or
    alive-but-silent peer surfaces as ``socket.timeout`` within one io
    window — including a dead star center, which every non-root notices
    the same way (the coordinator-stall watchdog is nothing more than
    this deadline plus blame: a non-root's ``suspect`` is always the
    coordinator). Any socket error — peer SIGKILL'd, accept/connect
    timeout, short read, retransmit exhaustion — raises
    :class:`GroupRevokedError` carrying the suspected member and the
    collective's stats, and marks the group ``revoked``.

    ``member``/``members`` carry the *stable* supervisor ids (rank order)
    so blame and chaos directives survive re-formation renumbering; a
    hello frame after connect tells rank 0 which member each accepted
    connection belongs to. ``chaos`` (a
    :class:`~mmlspark_tpu.runtime.netchaos.NetChaos`) filters every
    outgoing frame; ``slow_peer_s`` is the soft detection threshold — a
    successful round that made rank 0 wait at least this long books the
    peer into ``stats["slow_peers"]`` (the driver turns that into health
    straggle bookings and ``PeerSlow`` events).
    """

    _HDR = struct.Struct(">QQI")
    _HELLO = struct.Struct(">Q")
    _ACK, _NAK = b"\x06", b"\x15"

    def __init__(
        self,
        rank: int,
        world: int,
        port: int,
        timeout: float = 30.0,
        io_timeout: Optional[float] = None,
        member: Optional[int] = None,
        members: Optional[Sequence[int]] = None,
        chaos=None,
        slow_peer_s: Optional[float] = None,
        max_retransmits: int = 2,
    ):
        self.rank, self.world, self.port = int(rank), int(world), int(port)
        self.timeout = float(timeout)
        self.io_timeout = float(io_timeout if io_timeout is not None
                                else timeout)
        self.member = int(member if member is not None else rank)
        self.members = [int(m) for m in (
            members if members is not None else range(world)
        )]
        self.chaos = chaos
        self.slow_peer_s = float(
            slow_peer_s if slow_peer_s is not None else self.io_timeout / 2.0
        )
        self.max_retransmits = int(max_retransmits)
        self.revoked = False
        self.rounds = 0
        #: member id this process blames for the revocation, when known
        self.suspect: Optional[int] = None
        self.stats: Dict[str, Any] = {
            "retransmits": 0, "crc_drops": 0, "slow_peers": {},
        }
        self._conns: List[socket.socket] = []
        #: stable member id behind each entry of ``_conns`` (rank 0 learns
        #: them from the hello frames; a non-root's single peer is the
        #: coordinator)
        self._peers: List[int] = []
        if self.world <= 1:
            return
        coordinator = self.members[0]
        try:
            if self.rank == 0:
                srv = socket.socket()
                srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
                srv.bind(("127.0.0.1", self.port))
                srv.listen(self.world - 1)
                srv.settimeout(self.timeout)
                try:
                    for _ in range(self.world - 1):
                        conn, _ = srv.accept()
                        conn.settimeout(self.timeout)
                        hello, = self._HELLO.unpack(
                            self._recv_exact(conn, self._HELLO.size)
                        )
                        conn.settimeout(self.io_timeout)
                        self._conns.append(conn)
                        self._peers.append(int(hello))
                finally:
                    srv.close()
            else:
                deadline = time.monotonic() + self.timeout
                while True:
                    try:
                        conn = socket.create_connection(
                            ("127.0.0.1", self.port), timeout=1.0
                        )
                        break
                    except OSError:
                        if time.monotonic() >= deadline:
                            raise
                        time.sleep(0.05)
                conn.settimeout(self.timeout)
                conn.sendall(self._HELLO.pack(self.member))
                conn.settimeout(self.io_timeout)
                self._conns.append(conn)
                self._peers.append(coordinator)
        except (OSError, ConnectionError, struct.error) as e:
            if self.suspect is None and self.rank != 0:
                self.suspect = coordinator
            self._die(f"group formation failed (rank {self.rank}): {e}")

    def _die(self, why: str) -> None:
        self.revoked = True
        self.close()
        err = GroupRevokedError(why)
        err.suspect = self.suspect
        err.stats = dict(self.stats)
        raise err

    def _send(self, conn: socket.socket, peer: int, buf: bytes) -> None:
        """One acknowledged frame to ``peer``: CRC over the clean bytes,
        chaos applied after (so injected corruption is a genuine wire
        flip), retransmit the clean copy on NAK up to
        ``max_retransmits`` times."""
        hdr = self._HDR.pack(
            self.rounds, len(buf), zlib.crc32(buf) & 0xFFFFFFFF
        )
        for _ in range(self.max_retransmits + 1):
            wire = buf
            if self.chaos is not None:
                wire = self.chaos.on_send(peer, self.rounds, buf)
                if wire is None:
                    # swallowed (partition/drop): nothing on the wire,
                    # nothing to wait for — the peer's io deadline and
                    # ours end this round
                    return
            conn.sendall(hdr + wire)
            ack = self._recv_exact(conn, 1)
            if ack == self._ACK:
                return
            self.stats["retransmits"] += 1
        raise ConnectionError(
            f"peer {peer} rejected frame {self.rounds} "
            f"{self.max_retransmits + 1} times (CRC)"
        )

    def _recv(self, conn: socket.socket, peer: int) -> bytes:
        """One verified frame from ``peer``: NAK + re-read on CRC
        mismatch, bounded like the send side."""
        for _ in range(self.max_retransmits + 1):
            hdr = self._recv_exact(conn, self._HDR.size)
            rnd, nbytes, want = self._HDR.unpack(hdr)
            if rnd != self.rounds:
                raise ConnectionError(
                    f"round mismatch: peer {peer} at {rnd}, "
                    f"local at {self.rounds}"
                )
            payload = self._recv_exact(conn, nbytes)
            if zlib.crc32(payload) & 0xFFFFFFFF == want:
                conn.sendall(self._ACK)
                return payload
            self.stats["crc_drops"] += 1
            conn.sendall(self._NAK)
        raise ConnectionError(
            f"frame from peer {peer} failed CRC "
            f"{self.max_retransmits + 1} times"
        )

    def _timed_recv(self, conn: socket.socket, peer: int) -> bytes:
        """A receive that also feeds the soft slow-peer detector: waits
        that clear ``slow_peer_s`` (but still succeed) are remembered as
        the peer's worst observed lag."""
        t0 = time.monotonic()
        data = self._recv(conn, peer)
        wait = time.monotonic() - t0
        if self.slow_peer_s > 0 and wait >= self.slow_peer_s:
            slow = self.stats["slow_peers"]
            slow[str(peer)] = max(float(slow.get(str(peer), 0.0)), wait)
        return data

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes:
        out = bytearray()
        while len(out) < n:
            chunk = conn.recv(min(1 << 20, n - len(out)))
            if not chunk:
                raise ConnectionError("peer closed")
            out += chunk
        return bytes(out)

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        """Element-wise float32 sum across all members (identity when
        ``world == 1``). Raises :class:`GroupRevokedError` on any wire
        failure — the caller's signal to start gang recovery."""
        if self.world <= 1:
            return np.ascontiguousarray(arr, dtype=np.float32)
        if self.revoked:
            raise GroupRevokedError("allreduce on a revoked group")
        a = np.ascontiguousarray(arr, dtype=np.float32)
        peer = -1
        try:
            if self.rank == 0:
                total = a.copy()
                for conn, peer in zip(self._conns, self._peers):
                    total += np.frombuffer(
                        self._timed_recv(conn, peer), np.float32
                    ).reshape(a.shape)
                buf = total.tobytes()
                for conn, peer in zip(self._conns, self._peers):
                    self._send(conn, peer, buf)
                out = total
            else:
                peer = self._peers[0]
                self._send(self._conns[0], peer, a.tobytes())
                out = np.frombuffer(
                    self._timed_recv(self._conns[0], peer), np.float32
                ).reshape(a.shape)
        except (OSError, ConnectionError, struct.error) as e:
            self.suspect = peer if peer >= 0 else None
            kind = "deadline" if isinstance(e, socket.timeout) else "error"
            self._die(
                f"allreduce round {self.rounds} failed "
                f"({kind}, suspect member {self.suspect}): {e}"
            )
        self.rounds += 1
        return out

    def barrier(self) -> None:
        """All members reached this point (sum-allreduce of one scalar)."""
        self.allreduce(np.ones((1,), np.float32))

    def close(self) -> None:
        for conn in self._conns:
            try:
                conn.close()
            except OSError:  # pragma: no cover - close is best-effort
                pass
        self._conns = []
        self._peers = []


# -- worker side --------------------------------------------------------------


@dataclasses.dataclass
class WorkerContext:
    """Everything a payload entry point gets: identity, the epoch spec's
    payload, and the collective. ``rank``/``world`` describe the *current*
    epoch's membership (a survivor of a two-member gang re-forms with
    ``world == 2`` and possibly a different rank); ``member`` is the
    stable supervisor-assigned id."""

    member: int
    rank: int
    world: int
    epoch: int
    workdir: Path
    payload: Dict[str, Any]
    group: Optional[AllreduceGroup]
    fault_directives: List[dict] = dataclasses.field(default_factory=list)

    def allreduce(self, arr: np.ndarray) -> np.ndarray:
        if self.group is None:
            return np.ascontiguousarray(arr, dtype=np.float32)
        return self.group.allreduce(arr)

    def maybe_die(self, iteration: int) -> None:
        """Enact a ``FaultPlan.kill_process`` directive: a real SIGKILL,
        no Python teardown — the failure mode the supervisor exists for."""
        if FaultPlan.should_die(
            self.fault_directives, self.member, iteration, self.epoch
        ):
            logger.warning(
                "member %d enacting kill_process at iteration %d (epoch %d)",
                self.member, iteration, self.epoch,
            )
            sys.stdout.flush()
            sys.stderr.flush()
            os.kill(os.getpid(), signal.SIGKILL)


class _Heartbeat(threading.Thread):
    """Daemon thread bumping ``hb-<member>`` every ``interval`` seconds;
    the driver reads staleness off the file's mtime."""

    def __init__(self, path: Path, interval: float = 0.5):
        super().__init__(name=f"procgroup-hb-{path.name}", daemon=True)
        self.path = path
        self.interval = float(interval)
        self._stop = threading.Event()
        self._seq = 0

    def run(self) -> None:
        while not self._stop.is_set():
            self.beat()
            self._stop.wait(self.interval)

    def beat(self) -> None:
        self._seq += 1
        try:
            self.path.write_text(f"{self._seq} {time.time()}\n")
        except OSError:  # pragma: no cover - workdir vanished mid-shutdown
            pass

    def stop(self) -> None:
        self._stop.set()


def _write_json(path: Path, payload: Dict[str, Any]) -> None:
    _atomic_write(str(path), json.dumps(payload).encode("utf-8"))


def _resolve_entry(entry: str) -> Callable[[WorkerContext], Any]:
    mod_name, _, fn_name = entry.partition(":")
    if not fn_name:
        raise ValueError(f"entry must be 'module:function', got {entry!r}")
    return getattr(importlib.import_module(mod_name), fn_name)


def _clear_backends() -> None:
    """Drop initialized XLA backends + compiled caches so the next epoch's
    rendezvous builds a topology against the new membership."""
    try:
        import jax
        from jax._src import xla_bridge
    except Exception:  # noqa: BLE001 - pragma: no cover - jax-free unit-test workers
        return
    if getattr(xla_bridge, "_backends", None) and hasattr(
        xla_bridge, "_clear_backends"
    ):
        xla_bridge._clear_backends()
        if hasattr(xla_bridge.get_backend, "cache_clear"):
            xla_bridge.get_backend.cache_clear()
        jax.clear_caches()


def _wait_for_spec(
    workdir: Path, member: int, next_epoch: int, poll: float = 0.05
) -> Optional[Dict[str, Any]]:
    """Block until an epoch spec with ``epoch >= next_epoch`` appears (the
    highest wins — stale specs from revoked epochs are skipped), or the
    stop file does. Returns the spec, or None on stop."""
    while True:
        if (workdir / "stop").exists():
            return None
        best: Optional[Tuple[int, Path]] = None
        for path in workdir.glob("epoch-*.json"):
            try:
                k = int(path.stem.split("-", 1)[1])
            except (IndexError, ValueError):
                continue
            if k >= next_epoch and (best is None or k > best[0]):
                best = (k, path)
        if best is not None:
            try:
                return json.loads(best[1].read_text())
            except (OSError, json.JSONDecodeError):
                pass  # mid-rename blip; re-read next tick
        time.sleep(poll)


def _form_epoch(
    spec: Dict[str, Any], member: int, rank: int, world: int
) -> Optional[AllreduceGroup]:
    """The epoch formation sequence: jax.distributed rendezvous (when the
    spec asks for it and the gang spans processes), socket group, then
    *release the distributed client while every member is alive* — after
    this point peer death is a catchable socket error, not a fatal
    coordination-service abort. Any failure revokes the epoch."""
    use_jax = spec.get("rendezvous", "jax") == "jax" and world > 1
    if use_jax:
        from mmlspark_tpu.parallel.mesh import (
            distributed_init,
            distributed_shutdown,
        )

        _clear_backends()
        try:
            distributed_init(
                coordinator_address=f"127.0.0.1:{spec['coordinator_port']}",
                num_processes=world,
                process_id=rank,
                initialization_timeout=spec.get("rendezvous_timeout_s", 60.0),
            )
        except Exception as e:  # noqa: BLE001 - straggler/timeout = revoked
            raise GroupRevokedError(f"rendezvous failed: {e}") from e
        import jax

        if jax.process_count() != world:
            distributed_shutdown(clear_backends=True)
            raise GroupRevokedError(
                f"rendezvous formed {jax.process_count()} processes, "
                f"expected {world}"
            )
    group = None
    if world > 1:
        chaos = None
        net = spec.get("net_faults") or []
        if net:
            from mmlspark_tpu.runtime.netchaos import NetChaos

            chaos = NetChaos(
                net, member, int(spec.get("epoch", 0)),
                seed=int(spec.get("net_seed", 0)),
            )
            if not chaos.active:
                chaos = None
        group = AllreduceGroup(
            rank, world, int(spec["reduce_port"]),
            timeout=float(spec.get("group_timeout_s", 30.0)),
            io_timeout=float(spec.get("io_timeout_s",
                                      spec.get("group_timeout_s", 30.0))),
            member=member,
            members=[int(m) for m in spec["members"]],
            chaos=chaos,
            slow_peer_s=spec.get("slow_peer_s"),
        )
    if use_jax:
        from mmlspark_tpu.parallel.mesh import distributed_shutdown

        distributed_shutdown()
    return group


def worker_main(workdir: str, member: int, start_epoch: int = 0) -> int:
    """Child-process entry loop (spawned as
    ``python -m mmlspark_tpu.runtime.procgroup --worker ...``).

    Runs epochs until dropped from the membership or told to stop. A
    revoked epoch (peer loss) is reported and survived; a payload
    exception is reported and fatal — the supervisor must be able to tell
    "my peer died" from "my code is broken".
    """
    wd = Path(workdir)
    member = int(member)
    hb = _Heartbeat(wd / f"hb-{member}")
    hb.start()
    next_epoch = int(start_epoch)
    try:
        while True:
            spec = _wait_for_spec(wd, member, next_epoch)
            if spec is None:
                return 0
            epoch = int(spec["epoch"])
            members: List[int] = [int(m) for m in spec["members"]]
            if member not in members:
                logger.info("member %d dropped from epoch %d; exiting",
                            member, epoch)
                return 0
            rank, world = members.index(member), len(members)
            group: Optional[AllreduceGroup] = None
            try:
                group = _form_epoch(spec, member, rank, world)
                _write_json(wd / f"ready-{epoch}-{member}.json",
                            {"rank": rank, "world": world, "pid": os.getpid()})
                ctx = WorkerContext(
                    member=member, rank=rank, world=world, epoch=epoch,
                    workdir=wd, payload=dict(spec.get("payload") or {}),
                    group=group,
                    fault_directives=list(spec.get("faults") or []),
                )
                # the epoch spec ships the driver's TraceContext: the
                # gang.worker span (and the payload's children — allreduce,
                # histogram build) land in the driver's trace, tagged with
                # this process's label in the federated event log
                from mmlspark_tpu.observability.tracing import (
                    TraceContext,
                    get_tracer,
                )

                trace_ctx = TraceContext.from_dict(spec.get("trace"))
                with get_tracer().span(
                    "gang.worker", context=trace_ctx,
                    member=member, rank=rank, epoch=epoch,
                ):
                    result = _resolve_entry(spec["entry"])(ctx)
                if group is not None:
                    group.barrier()  # commit: the whole gang finished
                _write_json(wd / f"done-{epoch}-{member}.json",
                            {"ok": True, "result": result,
                             "collective": dict(group.stats)
                             if group is not None else {}})
            except GroupRevokedError as e:
                logger.warning("member %d: epoch %d revoked: %s",
                               member, epoch, e)
                _write_json(wd / f"revoked-{epoch}-{member}.json",
                            {"reason": str(e),
                             "suspect": getattr(e, "suspect", None),
                             "stats": getattr(e, "stats", None) or {}})
            except Exception as e:  # noqa: BLE001 - payload bug: report + die
                _write_json(wd / f"failed-{epoch}-{member}.json",
                            {"error": f"{type(e).__name__}: {e}",
                             "traceback": traceback.format_exc()})
                traceback.print_exc()
                return 1
            finally:
                if group is not None:
                    group.close()
                _clear_backends()
            next_epoch = epoch + 1
    finally:
        hb.stop()


def demo_entry(ctx: WorkerContext) -> Dict[str, Any]:
    """The dryrun/smoke payload: every member contributes ``member + 1``
    over a small grid and checks the allreduced total against the
    closed-form sum — proof the rendezvous numbered the right processes
    and the collective crossed all of them."""
    iters = int(ctx.payload.get("iterations", 3))
    total = 0.0
    for it in range(iters):
        ctx.maybe_die(it)
        local = np.full((4, 8), float(ctx.member + 1), np.float32)
        total = float(ctx.allreduce(local).sum())
    expected = 32.0 * sum(
        float(m + 1) for m in ctx.payload.get("expect_members", [ctx.member])
    )
    if ctx.payload.get("expect_members") and abs(total - expected) > 1e-5:
        raise AssertionError(f"allreduce total {total} != expected {expected}")
    return {"member": ctx.member, "rank": ctx.rank, "world": ctx.world,
            "total": total}


# -- driver side --------------------------------------------------------------


@dataclasses.dataclass
class ExitStatus:
    """Structured record of one member process's demise (or survival)."""

    member: int
    pid: int
    returncode: Optional[int]
    reason: str
    epoch: int

    @property
    def signal(self) -> Optional[int]:
        if self.returncode is not None and self.returncode < 0:
            return -self.returncode
        return None


class _Member:
    """Driver-side handle for one supervised child process."""

    def __init__(self, member: int, proc: subprocess.Popen, log_path: Path,
                 generation: int):
        self.member = member
        self.proc = proc
        self.log_path = log_path
        self.generation = generation

    @property
    def pid(self) -> int:
        return self.proc.pid


class ProcessGroup:
    """Supervised gang of worker processes with heartbeat liveness,
    structured exit-status collection, and epoch-based gang recovery.

    One :meth:`run` call drives the full protocol: write the epoch spec,
    watch for the gang to finish (done files) or fracture (child death,
    heartbeat silence, epoch timeout), and on fracture book the loss with
    the :class:`HealthTracker`, respawn or drop the member, and re-form on
    fresh ports. The payload sees revocation as
    :class:`GroupRevokedError` and is responsible for resuming from its
    own journal — the supervisor guarantees only membership and liveness.
    """

    def __init__(
        self,
        num_members: int,
        entry: str,
        payload: Optional[Dict[str, Any]] = None,
        workdir: Optional[str] = None,
        env: Optional[Dict[str, str]] = None,
        seed: int = 0,
        rendezvous: str = "jax",
        heartbeat_timeout_s: float = 10.0,
        epoch_timeout_s: float = 300.0,
        rendezvous_timeout_s: float = 60.0,
        group_timeout_s: float = 15.0,
        io_timeout_s: Optional[float] = None,
        slow_peer_s: Optional[float] = None,
        revoke_grace_s: float = 2.0,
        respawn: bool = True,
        max_epochs: int = 8,
        health: Optional[HealthTracker] = None,
        faults: Optional[FaultPlan] = None,
    ):
        if num_members < 1:
            raise ValueError(f"num_members must be >= 1, got {num_members}")
        self.num_members = int(num_members)
        self.entry = entry
        self.payload = dict(payload or {})
        if workdir is None:
            import tempfile

            workdir = tempfile.mkdtemp(prefix="mmlspark-tpu-procgroup-")
        self.workdir = Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)
        self.env = scrub_env(env)
        self.seed = int(seed)
        self.rendezvous = rendezvous
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.epoch_timeout_s = float(epoch_timeout_s)
        self.rendezvous_timeout_s = float(rendezvous_timeout_s)
        self.group_timeout_s = float(group_timeout_s)
        #: per-round collective deadline — the bound on how long a
        #: partitioned or silent peer can stall the gang before the
        #: epoch revokes (defaults to the formation timeout)
        self.io_timeout_s = float(
            io_timeout_s if io_timeout_s is not None else group_timeout_s
        )
        self.slow_peer_s = slow_peer_s
        #: how long to wait after the first revoked report for the rest
        #: of the gang to file theirs, so victim selection sees every vote
        self.revoke_grace_s = float(revoke_grace_s)
        self.respawn = bool(respawn)
        self.max_epochs = int(max_epochs)
        self.faults = faults if faults is not None else current_faults()
        self.health = health or HealthTracker(
            threshold=2.0, window_s=600.0, parole_s=600.0
        )
        self._wire_health_events()
        self.epoch = 0
        self.members: List[int] = list(range(self.num_members))
        self._procs: Dict[int, _Member] = {}
        self._generations: Dict[int, int] = {}
        self.exit_statuses: List[ExitStatus] = []
        self._metrics = self._make_metrics()

    # -- observability wiring ------------------------------------------------

    def _wire_health_events(self) -> None:
        from mmlspark_tpu.observability import WorkerQuarantined, get_bus

        def announce(member: int, score: float) -> None:
            bus = get_bus()
            if bus.active:
                bus.publish(WorkerQuarantined(
                    worker=member, score=score,
                    parole_s=self.health.parole_s,
                ))

        if self.health.on_quarantine is None:
            self.health.on_quarantine = announce

    @staticmethod
    def _make_metrics():
        from mmlspark_tpu.observability import get_registry

        reg = get_registry()
        return {
            "members": reg.gauge(
                "procgroup_members", "Live members in the process group"),
            "epoch": reg.gauge(
                "procgroup_epoch", "Current gang epoch"),
            "started": reg.counter(
                "procgroup_processes_started_total",
                "Member processes spawned (including respawns)"),
            "lost": reg.counter(
                "procgroup_processes_lost_total",
                "Member processes lost (exit, signal, or heartbeat silence)"),
            "reforms": reg.counter(
                "procgroup_reforms_total", "Gang recovery re-formations"),
            "partitions": reg.counter(
                "netchaos_partitions_total",
                "Partition-triggered epoch revocations resolved"),
            "retransmits": reg.counter(
                "collective_retransmits_total",
                "Allreduce frames retransmitted after a CRC rejection"),
            "slow_peers": reg.counter(
                "netchaos_slow_peers_total",
                "Slow-peer detections booked from collective stats"),
        }

    def _publish(self, event) -> None:
        from mmlspark_tpu.observability import get_bus

        bus = get_bus()
        if bus.active:
            bus.publish(event)

    # -- spawn/monitor -------------------------------------------------------

    def start(self) -> "ProcessGroup":
        for member in self.members:
            self._spawn(member, start_epoch=0)
        return self

    def _spawn(self, member: int, start_epoch: int) -> None:
        from mmlspark_tpu.observability import ProcessStarted

        gen = self._generations.get(member, -1) + 1
        self._generations[member] = gen
        log_path = self.workdir / f"log-{member}-{gen}.txt"
        log_fh = open(log_path, "wb")
        # per-process event-log federation: the gang member writes its own
        # ``<base>@member-<m>`` segment instead of clobbering the driver's
        # live file (observability.events.collect folds them back)
        env = dict(self.env)
        env["MMLSPARK_TPU_EVENT_LOG_PROCESS"] = f"member-{member}"
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "mmlspark_tpu.runtime.procgroup",
                 "--worker", str(self.workdir), str(member),
                 "--start-epoch", str(start_epoch)],
                env=env, stdout=log_fh, stderr=subprocess.STDOUT,
                cwd=str(self.workdir),
            )
        finally:
            log_fh.close()  # child holds its own descriptor
        self._procs[member] = _Member(member, proc, log_path, gen)
        self._metrics["started"].inc()
        logger.info("spawned member %d pid %d (epoch %d, gen %d)",
                    member, proc.pid, start_epoch, gen)
        self._publish(ProcessStarted(member=member, pid=proc.pid,
                                     epoch=start_epoch))

    def tail_log(self, member: int, max_bytes: int = 4096) -> str:
        """The last ``max_bytes`` of a member's current log — appended to
        failure messages so a worker's stderr reaches the driver's
        exception instead of dying with the temp dir."""
        handle = self._procs.get(member)
        if handle is None or not handle.log_path.exists():
            return ""
        data = handle.log_path.read_bytes()
        return data[-max_bytes:].decode("utf-8", errors="replace")

    def _hb_age(self, member: int) -> Optional[float]:
        path = self.workdir / f"hb-{member}"
        try:
            return time.time() - path.stat().st_mtime
        except OSError:
            return None  # no beat yet — covered by the epoch deadline

    def _check_losses(self, epoch: int, done: Dict[int, Any]) -> List[ExitStatus]:
        """Sweep live members for deaths and heartbeat silence. A member
        that already reported done for this epoch is not a loss regardless
        of its process state (it may be exiting after the stop file)."""
        losses: List[ExitStatus] = []
        for member in self.members:
            if member in done:
                continue
            handle = self._procs.get(member)
            if handle is None:
                continue
            rc = handle.proc.poll()
            if rc is not None:
                reason = f"signal:{-rc}" if rc < 0 else f"exit:{rc}"
                losses.append(ExitStatus(member, handle.pid, rc, reason, epoch))
                continue
            age = self._hb_age(member)
            if age is not None and age > self.heartbeat_timeout_s:
                handle.proc.kill()
                handle.proc.wait(timeout=10)
                losses.append(ExitStatus(
                    member, handle.pid, handle.proc.returncode,
                    "heartbeat", epoch,
                ))
        return losses

    def _read_done(self, epoch: int) -> Dict[int, Any]:
        done: Dict[int, Any] = {}
        for member in self.members:
            path = self.workdir / f"done-{epoch}-{member}.json"
            if path.exists():
                try:
                    done[member] = json.loads(path.read_text())
                except (OSError, json.JSONDecodeError):
                    pass
        return done

    def _read_revoked(self, epoch: int) -> Dict[int, Any]:
        """Members that reported epoch ``epoch`` revoked, with their
        blame (``suspect``) and collective stats."""
        revoked: Dict[int, Any] = {}
        for member in self.members:
            path = self.workdir / f"revoked-{epoch}-{member}.json"
            if path.exists():
                try:
                    revoked[member] = json.loads(path.read_text())
                except (OSError, json.JSONDecodeError):
                    pass
        return revoked

    def _write_spec(self, epoch: int) -> None:
        directives: List[dict] = []
        if self.faults is not None:
            directives = self.faults.process_kill_directives()
        spec = {
            "epoch": epoch,
            "members": list(self.members),
            "coordinator_port": pick_port(seed=self.seed * 1000 + epoch * 2),
            "reduce_port": pick_port(seed=self.seed * 1000 + epoch * 2 + 1),
            "entry": self.entry,
            "payload": self.payload,
            "faults": directives,
            "rendezvous": self.rendezvous,
            "rendezvous_timeout_s": self.rendezvous_timeout_s,
            "group_timeout_s": self.group_timeout_s,
            "io_timeout_s": self.io_timeout_s,
            "slow_peer_s": self.slow_peer_s,
        }
        if self.faults is not None:
            net = self.faults.net_directives(epoch)
            if net:
                spec["net_faults"] = net
                spec["net_seed"] = self.faults.seed
        if spec["reduce_port"] == spec["coordinator_port"]:
            spec["reduce_port"] = pick_port(
                seed=self.seed * 1000 + epoch * 2 + 7,
                exclude=[spec["coordinator_port"]],
            )
        # ship the driver's ambient trace so worker spans (allreduce,
        # histogram build) parent under it in the merged fleet trace
        from mmlspark_tpu.observability.tracing import TraceContext, get_tracer

        span = get_tracer().current()
        if span is not None:
            spec["trace"] = TraceContext.from_span(span).to_dict()
        _write_json(self.workdir / f"epoch-{epoch}.json", spec)

    # -- the gang loop -------------------------------------------------------

    def run(self, poll: float = 0.1) -> Dict[int, Any]:
        """Drive epochs until one completes on every live member. Returns
        ``{member: payload result}`` for the successful epoch. Raises
        :class:`GangFailedError` when recovery options run out and
        ``RuntimeError`` when a payload itself fails (a bug, surfaced with
        the worker's log tail).

        The whole gang runs under one ``procgroup.run`` span whose
        context ships in every epoch spec, so worker-side spans join the
        driver's trace; a :class:`GangFailedError` trips the incident
        flight recorder before it propagates."""
        from mmlspark_tpu.observability.tracing import get_tracer

        with get_tracer().span("procgroup.run", entry=self.entry):
            return self._run_epochs(poll)

    def _gang_failed(self, message: str) -> GangFailedError:
        """Book the incident (when a recorder is installed) and build the
        terminal error — gang death is exactly what the flight recorder
        exists to capture."""
        from mmlspark_tpu.observability.incidents import maybe_record
        from mmlspark_tpu.observability.tracing import get_tracer

        span = get_tracer().current()
        maybe_record(
            "gang_failed",
            trace_id=span.trace_id if span is not None else "",
            detail=message,
        )
        return GangFailedError(message)

    def _harvest_collective(
        self, epoch: int, stats_by_member: Dict[int, dict]
    ) -> None:
        """Fold the gang's per-member collective stats into metrics,
        events, health bookings, and fault-plan acknowledgements:
        retransmits count toward ``collective_retransmits_total`` (and
        consume a ``net_corrupt`` directive — the corruption fired and
        was absorbed); slow peers become ``PeerSlow`` events plus health
        straggle bookings (and consume a ``net_delay`` directive)."""
        from mmlspark_tpu.observability import PeerSlow

        for member in sorted(stats_by_member):
            stats = stats_by_member[member] or {}
            retrans = int(stats.get("retransmits", 0))
            if retrans > 0:
                self._metrics["retransmits"].inc(retrans)
                if self.faults is not None:
                    self.faults.mark_net_fired("corrupt", member, epoch)
                logger.warning(
                    "member %d absorbed %d retransmit(s) in epoch %d",
                    member, retrans, epoch,
                )
            for peer, wait in sorted(
                (stats.get("slow_peers") or {}).items()
            ):
                peer = int(peer)
                self._metrics["slow_peers"].inc()
                self.health.note_straggle(peer)
                if self.faults is not None:
                    self.faults.mark_net_fired("delay", peer, epoch)
                self._publish(PeerSlow(
                    member=peer, epoch=epoch, wait_s=float(wait),
                ))
                logger.warning(
                    "member %d observed peer %d slow (%.3fs) in epoch %d",
                    member, peer, float(wait), epoch,
                )

    def _pick_victim(self, epoch: int, revoked: Dict[int, Any]) -> int:
        """Deterministic blame resolution for a no-corpse revocation:
        every reporter names the peer its collective suspected (non-roots
        always blame the star center, rank 0 blames the member on the
        failed link); members that filed nothing within the grace window
        are suspects by silence. Most votes loses; ties go to the
        highest member id, so the coordinator survives a symmetric
        two-member partition and the journal-holding rank 0 is kept."""
        votes: Dict[int, int] = {}
        for reporter, info in revoked.items():
            suspect = info.get("suspect")
            if suspect is None or int(suspect) == int(reporter):
                continue
            if int(suspect) in self.members:
                votes[int(suspect)] = votes.get(int(suspect), 0) + 1
        silent = [
            m for m in self.members
            if m not in revoked and m not in self._read_done(epoch)
        ]
        for m in silent:  # said nothing while the gang revoked around it
            votes[m] = votes.get(m, 0) + 1
        if not votes:
            return max(self.members)
        top = max(votes.values())
        return max(m for m, n in votes.items() if n == top)

    def _resolve_revocation(
        self, epoch: int, revoked: Dict[int, Any]
    ) -> List[ExitStatus]:
        """Turn a partition-style revocation (every process alive, the
        collective dead) into the loss the existing recovery path knows
        how to handle: pick the blamed member, kill it, and book the
        death with reason ``"partition"``. When a real corpse already
        exists (the revocation was a peer noticing a SIGKILL) the corpse
        is the loss and no extra member is killed."""
        from mmlspark_tpu.observability import NetworkPartitioned
        from mmlspark_tpu.observability.incidents import maybe_record
        from mmlspark_tpu.observability.tracing import get_tracer

        self._harvest_collective(epoch, {
            m: info.get("stats") or {} for m, info in revoked.items()
        })
        losses = self._check_losses(epoch, self._read_done(epoch))
        if losses:
            return losses
        victim = self._pick_victim(epoch, revoked)
        handle = self._procs.get(victim)
        pid, rc = -1, None
        if handle is not None:
            if handle.proc.poll() is None:
                handle.proc.kill()
                handle.proc.wait(timeout=10)
            pid, rc = handle.pid, handle.proc.returncode
        reasons = "; ".join(
            f"m{m}: {info.get('reason', '?')}"
            for m, info in sorted(revoked.items())
        )
        self._metrics["partitions"].inc()
        if self.faults is not None:
            for kind in ("partition", "drop"):
                if self.faults.mark_net_fired(kind, victim, epoch):
                    break
        self._publish(NetworkPartitioned(
            member=victim, epoch=epoch, reason=reasons,
        ))
        span = get_tracer().current()
        maybe_record(
            "network_partitioned",
            trace_id=span.trace_id if span is not None else "",
            detail=f"epoch {epoch} victim {victim}: {reasons}",
        )
        logger.warning(
            "epoch %d revoked without a corpse; victim member %d "
            "(votes from %s)", epoch, victim, sorted(revoked),
        )
        return [ExitStatus(victim, pid, rc, "partition", epoch)]

    def _run_epochs(self, poll: float) -> Dict[int, Any]:
        from mmlspark_tpu.observability import GroupReformed, ProcessLost

        if not self._procs:
            self.start()
        while True:
            if self.epoch >= self.max_epochs:
                raise self._gang_failed(
                    f"no successful epoch within {self.max_epochs} attempts"
                )
            epoch = self.epoch
            self._metrics["epoch"].set(epoch)
            self._metrics["members"].set(len(self.members))
            self._write_spec(epoch)
            outcome, detail = self._monitor_epoch(epoch, poll)
            if outcome == "ok":
                return detail
            if outcome == "failed":
                raise RuntimeError(detail)
            if outcome == "revoked":
                # partition/slow-peer: resolve blame into a loss, then
                # recover exactly as for a corpse
                detail = self._resolve_revocation(epoch, detail)
            # book the dead, decide membership, re-form
            losses: List[ExitStatus] = detail
            survivors = list(self.members)
            for loss in losses:
                self.exit_statuses.append(loss)
                self._metrics["lost"].inc()
                self._publish(ProcessLost(
                    member=loss.member, pid=loss.pid,
                    reason=loss.reason, epoch=epoch,
                ))
                if self.faults is not None:
                    self.faults.mark_process_killed(loss.member)
                self.health.note_failure(loss.member, reason=loss.reason)
                survivors.remove(loss.member)
            next_members = list(survivors)
            for loss in losses:
                # drop the dead handle now: its demise is booked above, and
                # shutdown() must not book the same corpse a second time
                self._procs.pop(loss.member, None)
                if self.respawn and not self.health.is_quarantined(loss.member):
                    self._spawn(loss.member, start_epoch=epoch + 1)
                    next_members.append(loss.member)
                else:
                    logger.warning(
                        "member %d not respawned (quarantined=%s respawn=%s)",
                        loss.member,
                        self.health.is_quarantined(loss.member), self.respawn,
                    )
            if not next_members:
                raise self._gang_failed(
                    "all members lost and none eligible for respawn"
                )
            self.members = sorted(next_members)
            self.epoch = epoch + 1
            self._metrics["reforms"].inc()
            self._publish(GroupReformed(
                epoch=self.epoch, members=len(self.members), lost=len(losses),
            ))
            logger.info("gang re-formed for epoch %d with members %s "
                        "(lost %s)", self.epoch, self.members,
                        [l.member for l in losses])

    def _monitor_epoch(self, epoch: int, poll: float) -> Tuple[str, Any]:
        deadline = time.monotonic() + self.epoch_timeout_s
        while True:
            done = self._read_done(epoch)
            if all(m in done for m in self.members):
                bad = {m: d for m, d in done.items() if not d.get("ok")}
                if bad:
                    return "failed", f"payload reported failure: {bad}"
                self._harvest_collective(epoch, {
                    m: d.get("collective") or {} for m, d in done.items()
                })
                return "ok", {m: d.get("result") for m, d in done.items()}
            for member in self.members:
                path = self.workdir / f"failed-{epoch}-{member}.json"
                if path.exists():
                    try:
                        info = json.loads(path.read_text())
                    except (OSError, json.JSONDecodeError):
                        info = {}
                    return "failed", (
                        f"member {member} payload failed in epoch {epoch}: "
                        f"{info.get('error', '?')}\n"
                        f"{info.get('traceback', '')}\n"
                        f"--- log tail ---\n{self.tail_log(member)}"
                    )
            losses = self._check_losses(epoch, done)
            if losses:
                time.sleep(min(0.5, poll * 2))  # catch simultaneous deaths
                losses = self._check_losses(epoch, self._read_done(epoch))
                if losses:
                    return "lost", losses
            revoked = self._read_revoked(epoch)
            if revoked:
                # a partition/slow-peer revocation with every process
                # still alive: give the rest of the gang a grace window
                # to file their reports so victim selection sees all votes
                grace = min(deadline, time.monotonic() + self.revoke_grace_s)
                while time.monotonic() < grace:
                    done = self._read_done(epoch)
                    revoked = self._read_revoked(epoch)
                    if all(
                        m in done or m in revoked
                        or (self._procs.get(m) is not None
                            and self._procs[m].proc.poll() is not None)
                        for m in self.members
                    ):
                        break
                    time.sleep(poll)
                return "revoked", self._read_revoked(epoch)
            if time.monotonic() >= deadline:
                stuck = [m for m in self.members if m not in done]
                losses = []
                for member in stuck:
                    handle = self._procs.get(member)
                    if handle is None:
                        continue
                    handle.proc.kill()
                    handle.proc.wait(timeout=10)
                    losses.append(ExitStatus(
                        member, handle.pid, handle.proc.returncode,
                        "timeout", epoch,
                    ))
                if losses:
                    return "lost", losses
                return "failed", f"epoch {epoch} timed out with no live member"
            time.sleep(poll)

    # -- teardown ------------------------------------------------------------

    def shutdown(self, grace_s: float = 5.0) -> List[ExitStatus]:
        """Stop the gang: write the stop file, give workers ``grace_s`` to
        exit on their own, then escalate to terminate/kill. Returns the
        final exit status of every member ever spawned."""
        try:
            (self.workdir / "stop").write_text("stop\n")
        except OSError:  # pragma: no cover - workdir already gone
            pass
        deadline = time.monotonic() + grace_s
        for handle in self._procs.values():
            remaining = max(0.0, deadline - time.monotonic())
            try:
                handle.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                handle.proc.terminate()
                try:
                    handle.proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    handle.proc.kill()
                    handle.proc.wait(timeout=10)
        final: List[ExitStatus] = []
        for member, handle in sorted(self._procs.items()):
            rc = handle.proc.returncode
            reason = "running" if rc is None else (
                f"signal:{-rc}" if rc < 0 else f"exit:{rc}"
            )
            final.append(ExitStatus(member, handle.pid, rc, reason, self.epoch))
        self._metrics["members"].set(0)
        return final

    def __enter__(self) -> "ProcessGroup":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()


# -- CLI (the spawned worker) -------------------------------------------------


def _main(argv: List[str]) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="mmlspark_tpu.runtime.procgroup")
    parser.add_argument("--worker", required=True, metavar="WORKDIR",
                        help="group workdir (driver-managed)")
    parser.add_argument("member", type=int)
    parser.add_argument("--start-epoch", type=int, default=0)
    args = parser.parse_args(argv)
    return worker_main(args.worker, args.member, args.start_epoch)


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    # Re-dispatch through the canonically-imported module: under
    # ``python -m`` this file runs as ``__main__``, and exception classes
    # defined here would differ from the ones payload entries import from
    # ``mmlspark_tpu.runtime.procgroup`` — ``except GroupRevokedError``
    # in worker_main must see the SAME class the payload raises.
    from mmlspark_tpu.runtime import procgroup as _canonical

    sys.exit(_canonical._main(sys.argv[1:]))
