"""Partitioned-job scheduler — the driver half of the runtime.

Reproduces the slice of Spark's driver that MMLSpark actually leaned on:
a partitioned job is N independent tasks, each walking
``PENDING -> RUNNING -> DONE | FAILED`` with bounded retries, exponential
backoff with *seeded* jitter (two runs with the same policy seed back off
identically — fault tests stay deterministic), per-task timeouts,
heartbeat-loss re-dispatch, and lineage-based recompute of lost
partitions. Results always come back in task-index order regardless of
completion order, so a partitioned computation is a drop-in replacement
for its inline loop — bit-identical output, which is what the
fault-injected ``fit`` parity tests assert.

The driver loop runs in the caller's thread: it dispatches due tasks,
then waits on the job condition with a heartbeat-interval timeout, and on
every wake scans RUNNING attempts for per-task timeout and stale
heartbeats. A lost attempt is *superseded* (its late result, if any, is
discarded), its worker is declared lost, and the task is re-queued.
"""

from __future__ import annotations

import contextlib
import dataclasses
import enum
import itertools
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.observability.events import (
    TaskDispatched,
    TaskFailed,
    TaskRetried,
    get_bus,
)
from mmlspark_tpu.observability.tracing import get_tracer
from mmlspark_tpu.runtime.executor import ExecutorPool
from mmlspark_tpu.runtime.faults import FaultPlan, current_faults
from mmlspark_tpu.runtime.lineage import Lineage, PartitionLostError, ShardLineage
from mmlspark_tpu.runtime.metrics import RuntimeMetrics

logger = get_logger("mmlspark_tpu.runtime")

# job ids are process-global so event-log records from concurrent fits
# never collide (the SparkListenerJobStart jobId analogue)
_JOB_IDS = itertools.count()
_JOB_ID_LOCK = threading.Lock()


def _next_job_id() -> int:
    with _JOB_ID_LOCK:
        return next(_JOB_IDS)


class TaskState(enum.Enum):
    PENDING = "pending"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"


class TaskLostError(RuntimeError):
    """Driver-side verdict on a running attempt: per-task timeout exceeded
    or the executor's heartbeat went stale. Counts against the retry
    budget like any task failure."""


class JobFailedError(RuntimeError):
    """A task exhausted its retry budget; the whole job fails (Spark
    semantics: ``spark.task.maxFailures`` exceeded aborts the stage)."""


@dataclasses.dataclass
class SchedulerPolicy:
    """Retry/timeout/backoff knobs for one partitioned job (the analog of
    ``spark.task.maxFailures`` / ``spark.network.timeout`` et al.)."""

    max_workers: int = 4
    #: re-dispatches allowed per task beyond the first attempt
    max_retries: int = 2
    backoff_base: float = 0.05
    backoff_factor: float = 2.0
    #: jitter fraction; the jitter draw is seeded per (seed, task, failure)
    backoff_jitter: float = 0.25
    backoff_max: float = 5.0
    #: wall-clock limit per attempt; None disables
    task_timeout: Optional[float] = None
    heartbeat_interval: float = 0.05
    #: a worker whose last beat is older than this is declared lost
    heartbeat_timeout: float = 1.0
    seed: int = 0
    #: explicit fault plan; falls back to faults.current_faults()
    faults: Optional[FaultPlan] = None

    def backoff(self, index: int, failures: int) -> float:
        """Delay before re-dispatching ``index`` after its ``failures``-th
        failure. Deterministic: jitter comes from an RNG seeded with
        ``(policy.seed, index, failures)``."""
        base = min(
            self.backoff_max,
            self.backoff_base * self.backoff_factor ** max(0, failures - 1),
        )
        jitter = np.random.default_rng((self.seed, index, failures)).random()
        return base * (1.0 + self.backoff_jitter * jitter)


@dataclasses.dataclass
class TaskRecord:
    index: int
    payload: Any
    state: TaskState = TaskState.PENDING
    attempt: int = -1  # id of the latest attempt
    failures: int = 0
    result: Any = None
    error: Optional[BaseException] = None
    not_before: float = 0.0  # monotonic time before which we won't re-dispatch
    needs_recompute: bool = False


class _Attempt:
    """One dispatch of one task; the unit the executor pool runs."""

    def __init__(self, job: "_Job", task: TaskRecord, attempt_id: int):
        self.job = job
        self.task = task
        self.id = attempt_id
        #: 0-based per-task attempt number (what FaultPlan keys on)
        self.task_attempt = task.failures
        self.superseded = threading.Event()
        self.worker = None
        self.dispatched_at = time.monotonic()
        self.started_at: Optional[float] = None
        #: tracing span opened at dispatch; finished by whichever side
        #: settles the attempt (success, failure, or driver supersede)
        self.span = None

    # -- executor-side hooks -------------------------------------------------

    def mark_started(self, worker) -> None:
        self.worker = worker
        self.started_at = time.monotonic()
        self.job.metrics.note_start(
            self.task.index, self.started_at - self.dispatched_at
        )

    def execute(self, worker) -> Any:
        plan = self.job.policy.faults or current_faults()
        if plan is not None:
            plan.apply_on_start(
                self.task.index,
                self.task_attempt,
                worker=worker,
                superseded=self.superseded,
            )
        payload = self.task.payload
        if isinstance(payload, ShardLineage):
            payload = payload.materialize()
        return self.job.fn(payload)

    def report_success(self, result: Any) -> None:
        self.job._on_success(self, result)

    def report_failure(self, err: BaseException, executor_died: bool = False) -> None:
        self.job._on_failure(self, err, executor_died)


class _Job:
    """Driver-side state of one partitioned job."""

    def __init__(
        self,
        fn: Callable[[Any], Any],
        shards: Sequence[Any],
        policy: SchedulerPolicy,
        metrics: RuntimeMetrics,
        lineage: Optional[Lineage],
    ):
        self.fn = fn
        self.policy = policy
        self.metrics = metrics
        self.lineage = lineage
        self.id = _next_job_id()
        self.bus = get_bus()
        self.tasks = [TaskRecord(i, payload) for i, payload in enumerate(shards)]
        self.cond = threading.Condition()
        self.pending = set(range(len(self.tasks)))
        self.running: Dict[int, _Attempt] = {}
        self.done_count = 0
        self.failed: List[TaskRecord] = []
        self._attempt_ids = 0

    def finished(self) -> bool:
        return self.done_count + len(self.failed) == len(self.tasks)

    def next_attempt_id(self) -> int:
        aid = self._attempt_ids
        self._attempt_ids += 1
        return aid

    # -- completion callbacks (worker threads) -------------------------------

    def _is_current(self, att: _Attempt) -> bool:
        return (
            not att.superseded.is_set()
            and self.running.get(att.task.index) is att
        )

    def _on_success(self, att: _Attempt, result: Any) -> None:
        with self.cond:
            if not self._is_current(att):
                self.metrics.note_wasted_result()
                return
            t = att.task
            del self.running[t.index]
            t.state = TaskState.DONE
            t.result = result
            self.done_count += 1
            self.metrics.note_done(t.index, time.monotonic() - (att.started_at or att.dispatched_at))
            if att.span is not None:
                get_tracer().finish(att.span)
            self.cond.notify_all()

    def _on_failure(self, att: _Attempt, err: BaseException, executor_died: bool) -> None:
        with self.cond:
            if not self._is_current(att):
                self.metrics.note_wasted_result()
                return
            t = att.task
            del self.running[t.index]
            reason = "executor_death" if executor_died else "error"
            if att.span is not None:
                get_tracer().finish(att.span, status=reason, error=str(err)[:200])
            self._register_failure(t, err, reason)
            self.cond.notify_all()

    def _register_failure(self, t: TaskRecord, err: BaseException, reason: str) -> None:
        """Book a failure against ``t`` and either re-queue or fail it.
        Caller holds ``self.cond``."""
        t.failures += 1
        self.metrics.note_failure(t.index, reason)
        permanent = t.failures > self.policy.max_retries
        if self.bus.active:
            self.bus.publish(TaskFailed(
                job_id=self.id, task_id=t.index, reason=reason,
                permanent=permanent,
            ))
        if (
            isinstance(err, PartitionLostError)
            and self.lineage is not None
            and self.lineage.has(t.index)
        ):
            t.needs_recompute = True
        if permanent:
            t.state = TaskState.FAILED
            t.error = err
            self.failed.append(t)
            logger.warning(
                "task %d failed permanently after %d attempts (%s): %s",
                t.index, t.failures, reason, err,
            )
        else:
            self.metrics.note_retry(t.index)
            if self.bus.active:
                self.bus.publish(TaskRetried(
                    job_id=self.id, task_id=t.index, failures=t.failures,
                    reason=reason,
                ))
            t.state = TaskState.PENDING
            t.not_before = time.monotonic() + self.policy.backoff(t.index, t.failures)
            self.pending.add(t.index)
            logger.info(
                "task %d attempt failed (%s); retry %d/%d after backoff",
                t.index, reason, t.failures, self.policy.max_retries,
            )


class Scheduler:
    """Driver for partitioned jobs over an :class:`ExecutorPool`.

    Reusable across jobs (the serving dispatch loop keeps one alive);
    metrics accumulate across runs. If no pool is supplied the scheduler
    owns one sized by the policy and :meth:`close` shuts it down.
    """

    def __init__(
        self,
        pool: Optional[ExecutorPool] = None,
        policy: Optional[SchedulerPolicy] = None,
        metrics: Optional[RuntimeMetrics] = None,
    ):
        self.policy = policy or current_policy() or SchedulerPolicy()
        self.metrics = metrics or RuntimeMetrics()
        self._owns_pool = pool is None
        self.pool = pool or ExecutorPool(
            self.policy.max_workers,
            heartbeat_interval=self.policy.heartbeat_interval,
        )

    # -- driver loop ---------------------------------------------------------

    def run(
        self,
        fn: Callable[[Any], Any],
        shards: Sequence[Any],
        *,
        lineage: Optional[Lineage] = None,
    ) -> List[Any]:
        """Run ``fn`` over every shard; return results in shard order.

        Raises :class:`JobFailedError` if any task exhausts its retry
        budget (partial results are discarded, Spark stage-abort style).
        """
        shards = list(shards)
        if not shards:
            return []
        job = _Job(fn, shards, self.policy, self.metrics, lineage)
        # the job span parents every attempt span (attempts are children,
        # retries siblings); under a pipeline-stage or serving-apply span
        # the whole tree hangs off one trace id
        with get_tracer().span(
            "scheduler.job", job_id=job.id, tasks=len(job.tasks)
        ):
            while True:
                with job.cond:
                    if job.finished():
                        break
                    now = time.monotonic()
                    self._dispatch_due(job, now)
                    self._monitor(job, now)
                    timeout = self._wait_timeout(job, now)
                    job.cond.wait(timeout)
                # Replace any executor that died (ExecutorDeathError exit) or
                # was declared lost (stale heartbeat) — outside the job lock,
                # since spawning threads under it serves nothing.
                if self.pool.alive_count < self.pool.target_workers:
                    self.pool.ensure_capacity()
            if job.failed:
                first = job.failed[0]
                raise JobFailedError(
                    f"{len(job.failed)}/{len(job.tasks)} tasks failed permanently; "
                    f"first: task {first.index} after {first.failures} attempts"
                ) from first.error
        return [t.result for t in job.tasks]

    def _dispatch_due(self, job: _Job, now: float) -> None:
        """Submit every pending task whose backoff has elapsed. Caller
        holds ``job.cond``."""
        for index in sorted(job.pending):
            t = job.tasks[index]
            if t.not_before > now:
                continue
            if t.needs_recompute and job.lineage is not None:
                t.payload = job.lineage.recompute(index)
                t.needs_recompute = False
                self.metrics.note_recompute(index)
                logger.info("task %d: recomputed lost partition from lineage", index)
            job.pending.discard(index)
            att = _Attempt(job, t, job.next_attempt_id())
            t.attempt = att.id
            t.state = TaskState.RUNNING
            job.running[index] = att
            depth = self.pool.queue_depth() + 1
            self.metrics.note_dispatch(index, depth)
            # attempt spans: children of scheduler.job; a retry opens a
            # NEW span, so failed attempts read as siblings tagged with
            # their failure reason
            att.span = get_tracer().start_span(
                f"task-{index}", job_id=job.id, attempt=t.failures
            )
            if job.bus.active:
                job.bus.publish(TaskDispatched(
                    job_id=job.id, task_id=index, attempt=t.failures,
                    queue_depth=depth,
                ))
            self.pool.submit(att)

    def _monitor(self, job: _Job, now: float) -> bool:
        """Scan RUNNING attempts for per-task timeout and heartbeat loss;
        supersede and re-queue offenders. Caller holds ``job.cond``.
        Returns True if a worker was declared lost."""
        lost = False
        timeout = self.policy.task_timeout
        for index, att in list(job.running.items()):
            t = att.task
            if (
                timeout is not None
                and att.started_at is not None
                and now - att.started_at > timeout
            ):
                att.superseded.set()
                del job.running[index]
                if att.span is not None:
                    get_tracer().finish(att.span, status="timeout")
                job._register_failure(
                    t,
                    TaskLostError(
                        f"task {index} attempt {att.id} exceeded "
                        f"task_timeout={timeout:g}s"
                    ),
                    "timeout",
                )
            elif (
                att.worker is not None
                and now - att.worker.last_beat > self.policy.heartbeat_timeout
            ):
                att.superseded.set()
                del job.running[index]
                if att.span is not None:
                    get_tracer().finish(att.span, status="heartbeat")
                self.pool.declare_lost(att.worker)
                lost = True
                job._register_failure(
                    t,
                    TaskLostError(
                        f"executor running task {index} attempt {att.id} missed "
                        f"heartbeats for > {self.policy.heartbeat_timeout:g}s"
                    ),
                    "heartbeat",
                )
        return lost

    def _wait_timeout(self, job: _Job, now: float) -> float:
        """How long the driver may sleep: until the next backoff expiry,
        capped at a heartbeat interval so monitoring stays responsive."""
        timeout = self.policy.heartbeat_interval
        for index in job.pending:
            delta = job.tasks[index].not_before - now
            if 0 < delta < timeout:
                timeout = delta
        return max(timeout, 0.001)

    def close(self) -> None:
        if self._owns_pool:
            self.pool.shutdown()

    def __enter__(self) -> "Scheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def run_partitioned(
    fn: Callable[[Any], Any],
    shards: Sequence[Any],
    policy: Optional[SchedulerPolicy] = None,
    *,
    lineage: Optional[Lineage] = None,
    pool: Optional[ExecutorPool] = None,
    metrics: Optional[RuntimeMetrics] = None,
) -> List[Any]:
    """Run ``fn`` over ``shards`` on a fault-tolerant scheduler; results
    come back in shard order. The one-call public entry point."""
    with Scheduler(pool=pool, policy=policy, metrics=metrics) as sched:
        return sched.run(fn, shards, lineage=lineage)


# -- ambient policy (reaches schedulers created inside fit/serve calls) ------

_POLICY_STACK: List[SchedulerPolicy] = []


@contextlib.contextmanager
def policy(
    policy_or_none: Optional[SchedulerPolicy] = None, **kwargs: Any
) -> Iterator[SchedulerPolicy]:
    """Make a :class:`SchedulerPolicy` ambient: estimators/servers that
    build their own scheduler pick it up without API threading.

    ``with runtime.policy(max_workers=8, max_retries=3): est.fit(...)``
    """
    p = policy_or_none if policy_or_none is not None else SchedulerPolicy(**kwargs)
    _POLICY_STACK.append(p)
    try:
        yield p
    finally:
        _POLICY_STACK.remove(p)


def current_policy() -> Optional[SchedulerPolicy]:
    return _POLICY_STACK[-1] if _POLICY_STACK else None
