"""Partitioned-job scheduler — the driver half of the runtime.

Reproduces the slice of Spark's driver that MMLSpark actually leaned on:
a partitioned job is N independent tasks, each walking
``PENDING -> RUNNING -> DONE | FAILED`` with bounded retries, exponential
backoff with *seeded* jitter (two runs with the same policy seed back off
identically — fault tests stay deterministic), per-task timeouts,
heartbeat-loss re-dispatch, and lineage-based recompute of lost
partitions. Results always come back in task-index order regardless of
completion order, so a partitioned computation is a drop-in replacement
for its inline loop — bit-identical output, which is what the
fault-injected ``fit`` parity tests assert.

The driver loop runs in the caller's thread: it dispatches due tasks,
then waits on the job condition with a heartbeat-interval timeout, and on
every wake scans RUNNING attempts for per-task timeout and stale
heartbeats. A lost attempt is *superseded* (its late result, if any, is
discarded), its worker is declared lost, and the task is re-queued.

Three further Spark behaviors ride the same loop (docs/runtime.md):

- **speculative execution** (``spark.speculation``) — once
  ``speculation_quantile`` of tasks have finished, a running attempt
  older than ``speculation_multiplier`` x the median run time gets a
  duplicate attempt on a *different* worker; first result wins, the
  loser is superseded (its straggle is booked against its worker's
  health score);
- **executor quarantine** (BlacklistTracker) — a
  :class:`~mmlspark_tpu.runtime.health.HealthTracker` scores failures
  and straggles per worker over a rolling window; workers over the
  threshold get no new dispatches until parole, and when *every* alive
  worker is quarantined the job fails fast with
  :class:`AllWorkersQuarantinedError` (opt out via
  ``quarantine_fail_fast=False`` to wait for parole);
- **durable checkpoint/recovery** — pass a
  :class:`~mmlspark_tpu.runtime.journal.FitJournal` and completed task
  results are checkpointed (checksummed, atomic) as they land; a re-run
  after a crash restores them at startup with zero re-execution.
"""

from __future__ import annotations

import contextlib
import dataclasses
import enum
import itertools
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Union

import numpy as np

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.observability.events import (
    TaskDispatched,
    TaskFailed,
    TaskRecovered,
    TaskRetried,
    TaskSpeculated,
    WorkerParoled,
    WorkerQuarantined,
    get_bus,
)
from mmlspark_tpu.observability.tracing import get_tracer
from mmlspark_tpu.runtime.executor import ExecutorPool
from mmlspark_tpu.runtime.faults import FaultPlan, current_faults, is_oom_error
from mmlspark_tpu.runtime.health import HealthTracker
from mmlspark_tpu.runtime.journal import FitJournal, result_crc as _result_crc
from mmlspark_tpu.runtime.lineage import Lineage, PartitionLostError, ShardLineage
from mmlspark_tpu.runtime.metrics import RuntimeMetrics

logger = get_logger("mmlspark_tpu.runtime")

# job ids are process-global so event-log records from concurrent fits
# never collide (the SparkListenerJobStart jobId analogue)
_JOB_IDS = itertools.count()
_JOB_ID_LOCK = threading.Lock()


def _next_job_id() -> int:
    with _JOB_ID_LOCK:
        return next(_JOB_IDS)


class TaskState(enum.Enum):
    PENDING = "pending"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"


class TaskLostError(RuntimeError):
    """Driver-side verdict on a running attempt: per-task timeout exceeded
    or the executor's heartbeat went stale. Counts against the retry
    budget like any task failure."""


class ResultCorruptedError(RuntimeError):
    """The driver's end-to-end integrity check rejected a reported result:
    the CRC the executor took after computing it no longer matches the
    value that arrived. Retryable — the re-run computes a clean copy."""


@dataclasses.dataclass
class AttemptInfo:
    """One line of a task's attempt history — what :class:`JobFailedError`
    carries per task and ``format_timeline`` renders."""

    attempt: int
    worker: int  # executor worker id; -1 = never reached a worker
    reason: str  # ok|error|oom|timeout|heartbeat|executor_death|corrupt|superseded
    duration: float
    speculative: bool = False


class JobFailedError(RuntimeError):
    """A task exhausted its retry budget; the whole job fails (Spark
    semantics: ``spark.task.maxFailures`` exceeded aborts the stage).

    ``history`` maps task index -> ordered :class:`AttemptInfo` list for
    every task that recorded at least one attempt, so the post-mortem
    (which worker, which failure mode, how long, speculative or not) is
    on the exception itself — no event-log round trip needed.
    """

    def __init__(self, message: str, history: Optional[Dict[int, List[AttemptInfo]]] = None):
        super().__init__(message)
        self.history: Dict[int, List[AttemptInfo]] = history or {}

    def describe(self) -> str:
        """The message plus per-task attempt lines, newest task last."""
        lines = [str(self)]
        for index in sorted(self.history):
            for a in self.history[index]:
                spec = " (spec)" if a.speculative else ""
                lines.append(
                    f"  task {index}: attempt {a.attempt}{spec} on "
                    f"w{a.worker} {a.reason} {a.duration:.3f}s"
                )
        return "\n".join(lines)


class AllWorkersQuarantinedError(JobFailedError):
    """Every alive worker is quarantined and ``quarantine_fail_fast`` is
    on — the job cannot make progress anywhere (Spark's "task cannot run
    anywhere due to node and executor blacklist" abort)."""


@dataclasses.dataclass
class SchedulerPolicy:
    """Retry/timeout/backoff knobs for one partitioned job (the analog of
    ``spark.task.maxFailures`` / ``spark.network.timeout`` et al.)."""

    max_workers: int = 4
    #: re-dispatches allowed per task beyond the first attempt
    max_retries: int = 2
    backoff_base: float = 0.05
    backoff_factor: float = 2.0
    #: jitter fraction; the jitter draw is seeded per (seed, task, failure)
    backoff_jitter: float = 0.25
    backoff_max: float = 5.0
    #: wall-clock limit per attempt; None disables
    task_timeout: Optional[float] = None
    heartbeat_interval: float = 0.05
    #: a worker whose last beat is older than this is declared lost
    heartbeat_timeout: float = 1.0
    seed: int = 0
    #: explicit fault plan; falls back to faults.current_faults()
    faults: Optional[FaultPlan] = None
    # -- speculative execution (spark.speculation[.multiplier|.quantile]) ----
    speculation: bool = False
    #: a running attempt older than multiplier x median run time straggles
    speculation_multiplier: float = 1.5
    #: fraction of tasks that must be DONE before speculation engages
    speculation_quantile: float = 0.75
    # -- executor quarantine (spark.excludeOnFailure.*) ----------------------
    #: rolling failure score at which a worker is quarantined; 0 disables
    quarantine_threshold: float = 0.0
    quarantine_window: float = 60.0
    parole_s: float = 30.0
    #: raise AllWorkersQuarantinedError instead of waiting for parole
    quarantine_fail_fast: bool = True
    # -- end-to-end result integrity -----------------------------------------
    #: checksum every result executor-side and verify driver-side
    result_integrity: bool = False

    def backoff(self, index: int, failures: int) -> float:
        """Delay before re-dispatching ``index`` after its ``failures``-th
        failure. Deterministic: jitter comes from an RNG seeded with
        ``(policy.seed, index, failures)``."""
        base = min(
            self.backoff_max,
            self.backoff_base * self.backoff_factor ** max(0, failures - 1),
        )
        jitter = np.random.default_rng((self.seed, index, failures)).random()
        return base * (1.0 + self.backoff_jitter * jitter)


@dataclasses.dataclass
class TaskRecord:
    index: int
    payload: Any
    state: TaskState = TaskState.PENDING
    attempt: int = -1  # id of the latest attempt
    failures: int = 0
    result: Any = None
    error: Optional[BaseException] = None
    not_before: float = 0.0  # monotonic time before which we won't re-dispatch
    needs_recompute: bool = False
    #: OOM failures so far — the retry's reduced-footprint hint
    oom_failures: int = 0
    #: ordered AttemptInfo per settled attempt (success, failure, supersede)
    history: List[AttemptInfo] = dataclasses.field(default_factory=list)


class _Attempt:
    """One dispatch of one task; the unit the executor pool runs."""

    def __init__(
        self,
        job: "_Job",
        task: TaskRecord,
        attempt_id: int,
        speculative: bool = False,
        excluded_workers: Sequence[int] = (),
    ):
        self.job = job
        self.task = task
        self.id = attempt_id
        #: 0-based per-task attempt number (what FaultPlan keys on)
        self.task_attempt = task.failures
        self.speculative = speculative
        #: worker ids that must NOT run this attempt (a speculative copy
        #: has to land on a different executor than the original)
        self.excluded_workers = tuple(excluded_workers)
        self.superseded = threading.Event()
        self.worker = None
        self.dispatched_at = time.monotonic()
        self.started_at: Optional[float] = None
        #: CRC32 the executor took over the pickled result, pre-transport
        self.result_crc: Optional[int] = None
        #: tracing span opened at dispatch; finished by whichever side
        #: settles the attempt (success, failure, or driver supersede)
        self.span = None

    # -- executor-side hooks -------------------------------------------------

    def mark_started(self, worker) -> None:
        self.worker = worker
        self.started_at = time.monotonic()
        self.job.metrics.note_start(
            self.task.index, self.started_at - self.dispatched_at
        )

    def execute(self, worker) -> Any:
        plan = self.job.policy.faults or current_faults()
        if plan is not None:
            plan.apply_on_start(
                self.task.index,
                self.task_attempt,
                worker=worker,
                superseded=self.superseded,
            )
        payload = self.task.payload
        if isinstance(payload, ShardLineage):
            payload = payload.materialize()
        # an OOM relaunch runs under a reduced-footprint hint (how many
        # times this task has OOMed); footprint-aware task bodies consult
        # pressure.reduced_footprint() to shrink their working set
        from mmlspark_tpu.runtime.pressure import _footprint_hint

        with _footprint_hint(self.task.oom_failures):
            result = self.job.fn(payload)
        if self.job.policy.result_integrity or (
            plan is not None
            and plan.will_corrupt(self.task.index, self.task_attempt)
        ):
            self.result_crc = _result_crc(result)
        if plan is not None:
            result = plan.apply_on_result(
                self.task.index, self.task_attempt, result
            )
        return result

    def report_success(self, result: Any) -> None:
        self.job._on_success(self, result)

    def report_failure(self, err: BaseException, executor_died: bool = False) -> None:
        self.job._on_failure(self, err, executor_died)

    def age(self, now: float) -> Optional[float]:
        return None if self.started_at is None else now - self.started_at


class _Job:
    """Driver-side state of one partitioned job."""

    def __init__(
        self,
        fn: Callable[[Any], Any],
        shards: Sequence[Any],
        policy: SchedulerPolicy,
        metrics: RuntimeMetrics,
        lineage: Optional[Lineage],
        journal: Optional[FitJournal] = None,
        health: Optional[HealthTracker] = None,
    ):
        self.fn = fn
        self.policy = policy
        self.metrics = metrics
        self.lineage = lineage
        self.journal = journal
        self.health = health
        self.id = _next_job_id()
        self.bus = get_bus()
        self.tasks = [TaskRecord(i, payload) for i, payload in enumerate(shards)]
        self.cond = threading.Condition()
        self.pending = set(range(len(self.tasks)))
        #: task index -> live attempts (>1 while a speculative copy races)
        self.running: Dict[int, List[_Attempt]] = {}
        self.done_count = 0
        self.failed: List[TaskRecord] = []
        #: run durations of successful attempts — the speculation median
        self.run_durations: List[float] = []
        self._attempt_ids = 0

    def finished(self) -> bool:
        return self.done_count + len(self.failed) == len(self.tasks)

    def next_attempt_id(self) -> int:
        aid = self._attempt_ids
        self._attempt_ids += 1
        return aid

    # -- completion callbacks (worker threads) -------------------------------

    def _is_current(self, att: _Attempt) -> bool:
        return (
            not att.superseded.is_set()
            and att in self.running.get(att.task.index, ())
        )

    def _on_success(self, att: _Attempt, result: Any) -> None:
        # end-to-end integrity: the executor checksummed the result before
        # it crossed the (simulated) wire; verify before taking the lock
        corrupt = (
            att.result_crc is not None and _result_crc(result) != att.result_crc
        )
        accepted = False
        t = att.task
        with self.cond:
            if not self._is_current(att):
                self.metrics.note_wasted_result()
                return
            now = time.monotonic()
            duration = now - (att.started_at or att.dispatched_at)
            siblings = self.running.get(t.index, [])
            siblings.remove(att)
            if corrupt:
                if not siblings:
                    self.running.pop(t.index, None)
                if att.span is not None:
                    get_tracer().finish(att.span, status="corrupt")
                self._register_failure(
                    t,
                    ResultCorruptedError(
                        f"task {t.index} attempt {att.id} result failed the "
                        f"end-to-end CRC check "
                        f"(expected {att.result_crc:#010x})"
                    ),
                    "corrupt",
                    att=att,
                )
                self.cond.notify_all()
                return
            # first result wins: supersede any racing sibling attempts
            self.running.pop(t.index, None)
            for other in siblings:
                other.superseded.set()
                if other.span is not None:
                    get_tracer().finish(other.span, status="superseded")
                t.history.append(AttemptInfo(
                    attempt=other.task_attempt,
                    worker=other.worker.wid if other.worker is not None else -1,
                    reason="superseded",
                    duration=(now - other.started_at) if other.started_at else 0.0,
                    speculative=other.speculative,
                ))
                if self.health is not None and other.worker is not None:
                    # being overtaken is a (discounted) health signal
                    self.health.note_straggle(other.worker.wid)
            t.state = TaskState.DONE
            t.result = result
            t.history.append(AttemptInfo(
                attempt=att.task_attempt,
                worker=att.worker.wid if att.worker is not None else -1,
                reason="ok",
                duration=duration,
                speculative=att.speculative,
            ))
            self.done_count += 1
            self.run_durations.append(duration)
            self.metrics.note_done(t.index, duration)
            if att.speculative:
                self.metrics.note_speculative_win(t.index)
                logger.info(
                    "task %d: speculative copy won in %.3fs", t.index, duration
                )
            if att.span is not None:
                get_tracer().finish(att.span)
            accepted = True
            self.cond.notify_all()
        if accepted and self.journal is not None:
            # durable record outside the job lock: checkpoint + journal
            # line on the worker's time, never blocking the driver. A
            # full checkpoint volume degrades durability, not the job —
            # the task's success already stands
            try:
                self.journal.record(t.index, result)
            except OSError as e:
                logger.warning(
                    "journal record for task %d failed (%s); result kept "
                    "in memory, recovery will recompute it", t.index, e,
                )

    def _on_failure(self, att: _Attempt, err: BaseException, executor_died: bool) -> None:
        with self.cond:
            if not self._is_current(att):
                self.metrics.note_wasted_result()
                return
            t = att.task
            siblings = self.running.get(t.index, [])
            if att in siblings:
                siblings.remove(att)
            if not siblings:
                self.running.pop(t.index, None)
            if executor_died:
                reason = "executor_death"
            elif is_oom_error(err):
                # memory exhaustion is its own retryable class: the
                # relaunch carries a reduced-footprint hint, and the
                # health tracker scores it heavier than a plain error
                reason = "oom"
                t.oom_failures += 1
            else:
                reason = "error"
            if att.span is not None:
                get_tracer().finish(att.span, status=reason, error=str(err)[:200])
            self._register_failure(t, err, reason, att=att)
            self.cond.notify_all()

    def _register_failure(
        self,
        t: TaskRecord,
        err: BaseException,
        reason: str,
        att: Optional[_Attempt] = None,
    ) -> None:
        """Book a failure against ``t`` and either re-queue or fail it.
        Caller holds ``self.cond``; ``att`` (when the failure settled a
        specific attempt) supplies worker/timing/speculative detail."""
        worker_id = -1
        duration = 0.0
        speculative = False
        attempt_no = t.failures
        if att is not None:
            attempt_no = att.task_attempt
            speculative = att.speculative
            if att.worker is not None:
                worker_id = att.worker.wid
            if att.started_at is not None:
                duration = time.monotonic() - att.started_at
        t.failures += 1
        self.metrics.note_failure(t.index, reason)
        if self.health is not None and worker_id >= 0:
            self.health.note_failure(worker_id, reason)
        t.history.append(AttemptInfo(
            attempt=attempt_no, worker=worker_id, reason=reason,
            duration=duration, speculative=speculative,
        ))
        others_running = bool(self.running.get(t.index))
        permanent = t.failures > self.policy.max_retries and not others_running
        if self.bus.active:
            self.bus.publish(TaskFailed(
                job_id=self.id, task_id=t.index, reason=reason,
                permanent=permanent, worker=worker_id, duration=duration,
                speculative=speculative, attempt=attempt_no,
            ))
        if (
            isinstance(err, PartitionLostError)
            and self.lineage is not None
            and self.lineage.has(t.index)
        ):
            t.needs_recompute = True
        if others_running:
            # a sibling attempt (the original, or a speculative copy) is
            # still live — it remains the task's hope; no re-queue, no
            # permanent verdict from this failure alone
            logger.info(
                "task %d attempt failed (%s); sibling attempt still running",
                t.index, reason,
            )
            return
        if permanent:
            t.state = TaskState.FAILED
            t.error = err
            self.failed.append(t)
            logger.warning(
                "task %d failed permanently after %d attempts (%s): %s",
                t.index, t.failures, reason, err,
            )
        else:
            self.metrics.note_retry(t.index)
            if self.bus.active:
                self.bus.publish(TaskRetried(
                    job_id=self.id, task_id=t.index, failures=t.failures,
                    reason=reason,
                ))
            t.state = TaskState.PENDING
            t.not_before = time.monotonic() + self.policy.backoff(t.index, t.failures)
            self.pending.add(t.index)
            logger.info(
                "task %d attempt failed (%s); retry %d/%d after backoff",
                t.index, reason, t.failures, self.policy.max_retries,
            )


class Scheduler:
    """Driver for partitioned jobs over an :class:`ExecutorPool`.

    Reusable across jobs (the serving dispatch loop keeps one alive);
    metrics accumulate across runs. If no pool is supplied the scheduler
    owns one sized by the policy and :meth:`close` shuts it down.

    ``health`` (a :class:`~mmlspark_tpu.runtime.health.HealthTracker`)
    is built automatically when ``policy.quarantine_threshold > 0``;
    pass one explicitly to control its clock (fake-clock tests) or share
    it across schedulers. Either way it is wired to the pool's admission
    check, this scheduler's metrics, and the event bus.
    """

    def __init__(
        self,
        pool: Optional[ExecutorPool] = None,
        policy: Optional[SchedulerPolicy] = None,
        metrics: Optional[RuntimeMetrics] = None,
        health: Optional[HealthTracker] = None,
    ):
        self.policy = policy or current_policy() or SchedulerPolicy()
        self.metrics = metrics or RuntimeMetrics()
        self._owns_pool = pool is None
        self.pool = pool or ExecutorPool(
            self.policy.max_workers,
            heartbeat_interval=self.policy.heartbeat_interval,
        )
        if health is None and self.policy.quarantine_threshold > 0:
            health = HealthTracker(
                threshold=self.policy.quarantine_threshold,
                window_s=self.policy.quarantine_window,
                parole_s=self.policy.parole_s,
            )
        self.health = health
        if health is not None:
            if health.metrics is None:
                health.metrics = self.metrics
            if health.on_quarantine is None:
                health.on_quarantine = self._announce_quarantine
            if health.on_parole is None:
                health.on_parole = self._announce_parole
        self.pool.health = health

    # -- quarantine announcements (HealthTracker callbacks) ------------------

    def _announce_quarantine(self, worker_id: int, score: float) -> None:
        logger.warning(
            "worker %d quarantined (score %.2f >= %.2f); parole in %.1fs",
            worker_id, score, self.health.threshold, self.health.parole_s,
        )
        bus = get_bus()
        if bus.active:
            bus.publish(WorkerQuarantined(
                worker=worker_id, score=score, parole_s=self.health.parole_s,
            ))

    def _announce_parole(self, worker_id: int) -> None:
        logger.info("worker %d paroled; rejoining the pool", worker_id)
        bus = get_bus()
        if bus.active:
            bus.publish(WorkerParoled(worker=worker_id))

    # -- driver loop ---------------------------------------------------------

    def run(
        self,
        fn: Callable[[Any], Any],
        shards: Sequence[Any],
        *,
        lineage: Optional[Lineage] = None,
        journal: Optional[FitJournal] = None,
        revalidate: Optional[Callable[[int, Any], bool]] = None,
    ) -> List[Any]:
        """Run ``fn`` over every shard; return results in shard order.

        ``journal`` makes the job durable: previously completed tasks are
        restored from its checkpoints at startup (zero re-execution) and
        every new completion is recorded before the job can finish.
        ``revalidate(index, result) -> bool`` vets each restored result
        (e.g. re-checksum side-effect files); a False sends the task back
        through normal execution.

        Raises :class:`JobFailedError` if any task exhausts its retry
        budget (partial results are discarded, Spark stage-abort style),
        carrying the per-task :class:`AttemptInfo` history.
        """
        shards = list(shards)
        if not shards:
            return []
        job = _Job(
            fn, shards, self.policy, self.metrics, lineage,
            journal=journal, health=self.health,
        )
        if journal is not None:
            self._restore_from_journal(job, journal, revalidate)
            if job.finished() and not job.failed:
                return [t.result for t in job.tasks]
        # the job span parents every attempt span (attempts are children,
        # retries siblings); under a pipeline-stage or serving-apply span
        # the whole tree hangs off one trace id
        with get_tracer().span(
            "scheduler.job", job_id=job.id, tasks=len(job.tasks)
        ):
            while True:
                with job.cond:
                    if job.finished():
                        break
                    now = time.monotonic()
                    self._check_all_quarantined(job)
                    self._dispatch_due(job, now)
                    self._monitor(job, now)
                    self._maybe_speculate(job, now)
                    timeout = self._wait_timeout(job, now)
                    job.cond.wait(timeout)
                # Replace any executor that died (ExecutorDeathError exit) or
                # was declared lost (stale heartbeat) — outside the job lock,
                # since spawning threads under it serves nothing.
                if self.pool.alive_count < self.pool.target_workers:
                    self.pool.ensure_capacity()
            if job.failed:
                first = job.failed[0]
                raise JobFailedError(
                    f"{len(job.failed)}/{len(job.tasks)} tasks failed permanently; "
                    f"first: task {first.index} after {first.failures} attempts",
                    history={
                        t.index: list(t.history) for t in job.tasks if t.history
                    },
                ) from first.error
        return [t.result for t in job.tasks]

    def _restore_from_journal(
        self,
        job: _Job,
        journal: FitJournal,
        revalidate: Optional[Callable[[int, Any], bool]],
    ) -> None:
        """Mark journaled tasks DONE before any dispatch happens (the
        checkpoint-recovery scan). Runs before the driver loop, so no
        locking is needed."""
        restored = journal.restore()
        recovered = 0
        for index in sorted(restored):
            if not 0 <= index < len(job.tasks):
                continue  # stale journal from a differently-sized run
            result = restored[index]
            if revalidate is not None and not revalidate(index, result):
                logger.warning(
                    "task %d: journal checkpoint failed revalidation; "
                    "recomputing", index,
                )
                continue
            t = job.tasks[index]
            t.state = TaskState.DONE
            t.result = result
            job.pending.discard(index)
            job.done_count += 1
            recovered += 1
            self.metrics.note_recovered(index)
            if job.bus.active:
                job.bus.publish(TaskRecovered(job_id=job.id, task_id=index))
        if recovered:
            logger.info(
                "restored %d/%d tasks from journal %s (zero re-execution)",
                recovered, len(job.tasks), journal.dir,
            )

    def _check_all_quarantined(self, job: _Job) -> None:
        """Fail fast when no alive worker may accept work. Caller holds
        ``job.cond``; raising releases it."""
        if self.health is None or not self.policy.quarantine_fail_fast:
            return
        if not (job.pending or job.running):
            return
        alive = [w.wid for w in self.pool.workers if not w.dead]
        if not alive or not self.health.all_quarantined(alive):
            return
        # abandon in-flight/queued attempts so workers skip them instead
        # of bouncing them through the inbox forever
        for atts in job.running.values():
            for att in atts:
                att.superseded.set()
        wait = self.health.next_parole_in()
        detail = f" (next parole in {wait:.1f}s)" if wait is not None else ""
        raise AllWorkersQuarantinedError(
            f"all {len(alive)} workers are quarantined; job {job.id} cannot "
            f"run anywhere{detail}",
            history={t.index: list(t.history) for t in job.tasks if t.history},
        )

    def _dispatch_due(self, job: _Job, now: float) -> None:
        """Submit every pending task whose backoff has elapsed. Caller
        holds ``job.cond``."""
        for index in sorted(job.pending):
            t = job.tasks[index]
            if t.not_before > now:
                continue
            if t.needs_recompute and job.lineage is not None:
                t.payload = job.lineage.recompute(index)
                t.needs_recompute = False
                self.metrics.note_recompute(index)
                logger.info("task %d: recomputed lost partition from lineage", index)
            job.pending.discard(index)
            att = _Attempt(job, t, job.next_attempt_id())
            t.attempt = att.id
            t.state = TaskState.RUNNING
            job.running[index] = [att]
            depth = self.pool.queue_depth() + 1
            self.metrics.note_dispatch(index, depth)
            # attempt spans: children of scheduler.job; a retry opens a
            # NEW span, so failed attempts read as siblings tagged with
            # their failure reason
            att.span = get_tracer().start_span(
                f"task-{index}", job_id=job.id, attempt=t.failures
            )
            if job.bus.active:
                job.bus.publish(TaskDispatched(
                    job_id=job.id, task_id=index, attempt=t.failures,
                    queue_depth=depth,
                ))
            self.pool.submit(att)

    def _monitor(self, job: _Job, now: float) -> bool:
        """Scan RUNNING attempts for per-task timeout and heartbeat loss;
        supersede and re-queue offenders. Caller holds ``job.cond``.
        Returns True if a worker was declared lost."""
        lost = False
        timeout = self.policy.task_timeout
        for index, atts in list(job.running.items()):
            for att in list(atts):
                t = att.task
                if (
                    timeout is not None
                    and att.started_at is not None
                    and now - att.started_at > timeout
                ):
                    att.superseded.set()
                    atts.remove(att)
                    if not atts:
                        job.running.pop(index, None)
                    if att.span is not None:
                        get_tracer().finish(att.span, status="timeout")
                    job._register_failure(
                        t,
                        TaskLostError(
                            f"task {index} attempt {att.id} exceeded "
                            f"task_timeout={timeout:g}s"
                        ),
                        "timeout",
                        att=att,
                    )
                elif (
                    att.worker is not None
                    and now - att.worker.last_beat > self.policy.heartbeat_timeout
                ):
                    att.superseded.set()
                    atts.remove(att)
                    if not atts:
                        job.running.pop(index, None)
                    if att.span is not None:
                        get_tracer().finish(att.span, status="heartbeat")
                    self.pool.declare_lost(att.worker)
                    lost = True
                    job._register_failure(
                        t,
                        TaskLostError(
                            f"executor running task {index} attempt {att.id} missed "
                            f"heartbeats for > {self.policy.heartbeat_timeout:g}s"
                        ),
                        "heartbeat",
                        att=att,
                    )
        return lost

    def _maybe_speculate(self, job: _Job, now: float) -> None:
        """Launch duplicate attempts against stragglers (the
        ``spark.speculation`` re-launch). Caller holds ``job.cond``.

        Engages only once ``speculation_quantile`` of the job's tasks are
        DONE and at least one run duration is known; a running attempt
        whose age exceeds ``speculation_multiplier`` x the median run
        time gets one speculative copy, pinned off its current worker."""
        pol = self.policy
        if not pol.speculation or not job.run_durations:
            return
        if job.done_count < pol.speculation_quantile * len(job.tasks):
            return
        workers = [w for w in self.pool.workers if not w.dead]
        if self.health is not None:
            workers = [w for w in workers if not self.health.is_quarantined(w.wid)]
        if len(workers) < 2:
            return  # nowhere different to run a copy
        median = float(np.median(job.run_durations))
        threshold = max(pol.speculation_multiplier * median, 1e-6)
        for index, atts in list(job.running.items()):
            if len(atts) != 1:
                continue  # a copy is already racing (or the list is settling)
            orig = atts[0]
            age = orig.age(now)
            if age is None or age <= threshold or orig.worker is None:
                continue
            spec = _Attempt(
                job, orig.task, job.next_attempt_id(),
                speculative=True, excluded_workers=(orig.worker.wid,),
            )
            atts.append(spec)
            depth = self.pool.queue_depth() + 1
            self.metrics.note_dispatch(index, depth)
            self.metrics.note_speculative_launch(index)
            spec.span = get_tracer().start_span(
                f"task-{index}", job_id=job.id, attempt=orig.task.failures,
                speculative=True,
            )
            if job.bus.active:
                job.bus.publish(TaskSpeculated(
                    job_id=job.id, task_id=index,
                    original_worker=orig.worker.wid, age=age, median=median,
                ))
                job.bus.publish(TaskDispatched(
                    job_id=job.id, task_id=index, attempt=orig.task.failures,
                    queue_depth=depth,
                ))
            logger.info(
                "task %d: speculative copy launched (attempt age %.3fs > "
                "%.2fx median %.3fs)",
                index, age, pol.speculation_multiplier, median,
            )
            self.pool.submit(spec)

    def _wait_timeout(self, job: _Job, now: float) -> float:
        """How long the driver may sleep: until the next backoff expiry,
        capped at a heartbeat interval so monitoring stays responsive."""
        timeout = self.policy.heartbeat_interval
        for index in job.pending:
            delta = job.tasks[index].not_before - now
            if 0 < delta < timeout:
                timeout = delta
        return max(timeout, 0.001)

    def close(self) -> None:
        if self._owns_pool:
            self.pool.shutdown()

    def __enter__(self) -> "Scheduler":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def run_partitioned(
    fn: Callable[[Any], Any],
    shards: Sequence[Any],
    policy: Optional[SchedulerPolicy] = None,
    *,
    lineage: Optional[Lineage] = None,
    pool: Optional[ExecutorPool] = None,
    metrics: Optional[RuntimeMetrics] = None,
    journal: Optional[FitJournal] = None,
    revalidate: Optional[Callable[[int, Any], bool]] = None,
) -> List[Any]:
    """Run ``fn`` over ``shards`` on a fault-tolerant scheduler; results
    come back in shard order. The one-call public entry point."""
    with Scheduler(pool=pool, policy=policy, metrics=metrics) as sched:
        return sched.run(
            fn, shards, lineage=lineage, journal=journal, revalidate=revalidate
        )


# -- ambient policy (reaches schedulers created inside fit/serve calls) ------

_POLICY_STACK: List[SchedulerPolicy] = []


@contextlib.contextmanager
def policy(
    policy_or_none: Optional[SchedulerPolicy] = None, **kwargs: Any
) -> Iterator[SchedulerPolicy]:
    """Make a :class:`SchedulerPolicy` ambient: estimators/servers that
    build their own scheduler pick it up without API threading.

    ``with runtime.policy(max_workers=8, max_retries=3): est.fit(...)``
    """
    p = policy_or_none if policy_or_none is not None else SchedulerPolicy(**kwargs)
    _POLICY_STACK.append(p)
    try:
        yield p
    finally:
        _POLICY_STACK.remove(p)


def current_policy() -> Optional[SchedulerPolicy]:
    return _POLICY_STACK[-1] if _POLICY_STACK else None
