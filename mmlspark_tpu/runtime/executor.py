"""Executor pool — thread workers with heartbeats, drain, and replacement.

The Spark analog is the executor fleet: each worker pulls task attempts
from a shared inbox, runs them, and reports back to the driver
(:mod:`~mmlspark_tpu.runtime.scheduler`). Two Spark behaviors are
reproduced faithfully:

- **heartbeats** — every worker runs a pulse thread stamping
  ``last_beat``; the scheduler's driver loop declares a worker lost when
  its beat goes stale (the injected ``drop_heartbeat`` fault suppresses
  the pulse to trigger exactly this path);
- **executor death** — a task raising :class:`ExecutorDeathError` takes
  its whole worker down (the thread exits, like a crashed JVM executor);
  the scheduler re-dispatches the attempt and calls
  :meth:`ExecutorPool.ensure_capacity` to spawn a replacement.

The pool also enforces *admission*: before executing an attempt a worker
consults :meth:`ExecutorPool._admit` — a quarantined worker (see
:class:`~mmlspark_tpu.runtime.health.HealthTracker`) gets no new work,
and an attempt that excludes this worker (a speculative copy must land
on a different executor than the original) is handed back to the inbox
for someone else. Attempts already superseded while queued are skipped
without burning a worker.

Workers are daemon threads so a held worker (fault-injected hang) never
blocks interpreter exit.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

from mmlspark_tpu.runtime.faults import ExecutorDeathError

#: Sentinel that tells a worker to exit its pull loop.
POISON = object()


class _Worker(threading.Thread):
    _ids = 0

    def __init__(self, pool: "ExecutorPool", heartbeat_interval: float):
        _Worker._ids += 1
        self.wid = _Worker._ids
        super().__init__(name=f"{pool.name}-worker-{self.wid}", daemon=True)
        self.pool = pool
        self.heartbeat_interval = heartbeat_interval
        self.last_beat = time.monotonic()
        #: set by the drop_heartbeat fault; the pulse thread stops stamping
        self.beat_suppressed = False
        self.current = None  # the _Attempt being executed, if any
        self.dead = False
        self._halt = threading.Event()

    # -- heartbeat ----------------------------------------------------------

    def _pulse(self) -> None:
        while not self._halt.is_set():
            if not self.beat_suppressed:
                self.last_beat = time.monotonic()
            self._halt.wait(self.heartbeat_interval)

    # -- pull loop ----------------------------------------------------------

    def run(self) -> None:
        pulse = threading.Thread(
            target=self._pulse, name=f"{self.name}-pulse", daemon=True
        )
        pulse.start()
        try:
            while True:
                att = self.pool._inbox.get()
                if att is POISON:
                    return
                sup = getattr(att, "superseded", None)
                if sup is not None and sup.is_set():
                    continue  # driver gave up on this attempt while queued
                if not self.pool._admit(self, att):
                    # quarantined, or this attempt must run elsewhere:
                    # hand it back and pause so the bounce doesn't spin hot
                    self.pool._inbox.put(att)
                    time.sleep(self.pool.heartbeat_interval / 4)
                    continue
                self.current = att
                att.mark_started(self)
                try:
                    result = att.execute(self)
                except ExecutorDeathError as e:
                    att.report_failure(e, executor_died=True)
                    self.dead = True
                    return  # the executor dies with its task
                except BaseException as e:  # noqa: BLE001 — task errors retry
                    att.report_failure(e)
                else:
                    att.report_success(result)
                finally:
                    self.current = None
                    self.beat_suppressed = False
        finally:
            self._halt.set()
            self.pool._note_exit(self)


class ExecutorPool:
    """Fixed-size pool of pull-loop workers sharing one task inbox."""

    def __init__(
        self,
        num_workers: int,
        heartbeat_interval: float = 0.05,
        name: str = "runtime",
    ):
        if num_workers < 1:
            raise ValueError(f"num_workers must be >= 1, got {num_workers}")
        self.name = name
        #: fleet size the pool keeps replacing dead workers up to
        self.target_workers = num_workers
        self.heartbeat_interval = heartbeat_interval
        #: optional HealthTracker; quarantined workers are refused work
        self.health = None
        self._inbox: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._workers: List[_Worker] = []
        self._draining = False
        self._shutdown = False
        for _ in range(num_workers):
            self._spawn()

    def _spawn(self) -> None:
        w = _Worker(self, self.heartbeat_interval)
        self._workers.append(w)
        w.start()

    def _note_exit(self, worker: _Worker) -> None:
        with self._lock:
            worker.dead = True

    # -- submission ---------------------------------------------------------

    def submit(self, attempt) -> None:
        if self._draining or self._shutdown:
            raise RuntimeError(f"pool {self.name!r} is shut down")
        self._inbox.put(attempt)

    def _admit(self, worker: "_Worker", attempt) -> bool:
        """May ``worker`` execute ``attempt``? False when the attempt
        excludes this worker (speculative copies must land on a different
        executor than the original) or the health tracker has the worker
        quarantined — the worker re-queues the attempt for someone else."""
        if worker.wid in getattr(attempt, "excluded_workers", ()):
            return False
        health = self.health
        if health is not None and health.is_quarantined(worker.wid):
            return False
        return True

    def queue_depth(self) -> int:
        return self._inbox.qsize()

    # -- membership ---------------------------------------------------------

    @property
    def workers(self) -> List[_Worker]:
        with self._lock:
            return list(self._workers)

    @property
    def alive_count(self) -> int:
        with self._lock:
            return sum(1 for w in self._workers if not w.dead)

    def declare_lost(self, worker: _Worker) -> None:
        """Driver-side verdict: this executor is gone (stale heartbeat).
        Its thread may still be blocked; being a daemon it can't hurt."""
        with self._lock:
            worker.dead = True
            if worker in self._workers:
                self._workers.remove(worker)

    def ensure_capacity(self, target: Optional[int] = None) -> int:
        """Replace dead workers until ``target`` (default: the pool's own
        size) are alive; returns the number spawned."""
        spawned = 0
        if target is None:
            target = self.target_workers
        with self._lock:
            if self._draining or self._shutdown:
                return 0
            self._workers = [w for w in self._workers if not w.dead]
            while len(self._workers) < target:
                self._spawn()
                spawned += 1
        return spawned

    # -- teardown -----------------------------------------------------------

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop accepting work and wait for in-flight tasks to finish.
        Returns True if the pool went quiet within ``timeout``."""
        self._draining = True
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            busy = any(w.current is not None for w in self.workers)
            if self._inbox.empty() and not busy:
                return True
            time.sleep(0.01)
        return False

    def shutdown(self, timeout: float = 5.0) -> None:
        with self._lock:
            if self._shutdown:
                return
            self._shutdown = True
            self._draining = True
            workers = list(self._workers)
        for _ in workers:
            self._inbox.put(POISON)
        deadline = time.monotonic() + timeout
        for w in workers:
            w.join(timeout=max(0.0, deadline - time.monotonic()))
