"""mmlspark_tpu.runtime — a fault-tolerant partition scheduler.

MMLSpark's "runtime" was Spark's driver/executor model: partition
dispatch, bounded retries, heartbeat-based executor loss detection,
straggler re-dispatch, and lineage recompute all came for free. This
subsystem is our self-owned replacement — small, thread-based, and
deterministic enough to test fault recovery bit-for-bit:

- :mod:`~mmlspark_tpu.runtime.scheduler` — the driver: per-task state
  machine, seeded exponential backoff, deterministic result ordering,
  speculative execution (straggler duplicates, first result wins);
- :mod:`~mmlspark_tpu.runtime.executor`  — the fleet: heartbeating
  worker pool with graceful drain, dead-worker replacement, and
  health-aware admission (quarantined workers get no new attempts);
- :mod:`~mmlspark_tpu.runtime.health`    — the BlacklistTracker
  analogue: rolling per-worker failure/straggle scores, timed
  quarantine with parole;
- :mod:`~mmlspark_tpu.runtime.journal`   — durable fit state: an
  append-only completion journal with checksummed atomic partition
  checkpoints (kill the process, rerun, zero re-execution) and
  atomic-rename model commits with a recovery scan;
- :mod:`~mmlspark_tpu.runtime.lineage`   — recompute a lost partition
  from its recorded source instead of failing the job;
- :mod:`~mmlspark_tpu.runtime.faults`    — seeded fault injection for
  chaos tests: task-plane (kill-task, delay-task, slow-task stragglers,
  corrupt-result, drop-heartbeat), HTTP-plane (503 storms, latency
  spikes, connection resets — consumed by the ``mmlspark_tpu.resilience``
  layer's clients), and exhaustion-plane (``oom_task`` host/device OOM,
  ``disk_full`` ENOSPC on guarded writes);
- :mod:`~mmlspark_tpu.runtime.pressure`  — the resource watchdog: HBM /
  host-RSS / disk gauges, a process-wide :class:`PressureLevel`, and
  ``MemoryPressure``/``DiskPressure`` events on threshold transitions;
- :mod:`~mmlspark_tpu.runtime.metrics`   — per-task timings, retry
  counts, queue depth via ``core/profiling.py`` conventions.

Quick start::

    from mmlspark_tpu import runtime

    results = runtime.run_partitioned(process, shards,
                                      runtime.SchedulerPolicy(max_workers=4))

    # chaos: kill the executor running a random task, assert recovery
    plan = runtime.FaultPlan(seed=7).kill_random_task(len(shards))
    with runtime.inject_faults(plan):
        same = runtime.run_partitioned(process, shards)
    assert same == results and plan.fired

    # durable fit: a rerun after a crash restores finished partitions
    journal = runtime.FitJournal("/durable/ckpt", key="my-job", num_tasks=8)
    results = runtime.run_partitioned(process, shards, journal=journal)
"""

from mmlspark_tpu.runtime.executor import ExecutorPool
from mmlspark_tpu.runtime.faults import (
    DeviceOomError,
    ExecutorDeathError,
    FaultPlan,
    check_write,
    current_faults,
    inject_faults,
    is_oom_error,
)
from mmlspark_tpu.runtime.health import HealthTracker
from mmlspark_tpu.runtime.journal import (
    CHECKPOINT_DIR_ENV,
    FitJournal,
    ModelStore,
    default_checkpoint_dir,
    result_crc,
)
from mmlspark_tpu.runtime.lineage import Lineage, PartitionLostError, ShardLineage
from mmlspark_tpu.runtime.metrics import RuntimeMetrics
from mmlspark_tpu.runtime.pressure import (
    PressureLevel,
    ResourceWatchdog,
    current_pressure_level,
    get_watchdog,
    reduced_footprint,
    set_pressure_level,
)
from mmlspark_tpu.runtime.procgroup import (
    AllreduceGroup,
    ExitStatus,
    GangFailedError,
    GroupRevokedError,
    ProcessGroup,
    WorkerContext,
    pick_port,
    scrub_env,
    worker_main,
)
from mmlspark_tpu.runtime.scheduler import (
    AllWorkersQuarantinedError,
    AttemptInfo,
    JobFailedError,
    ResultCorruptedError,
    Scheduler,
    SchedulerPolicy,
    TaskLostError,
    TaskState,
    current_policy,
    policy,
    run_partitioned,
)

__all__ = [
    "AllWorkersQuarantinedError",
    "AllreduceGroup",
    "AttemptInfo",
    "CHECKPOINT_DIR_ENV",
    "DeviceOomError",
    "ExecutorDeathError",
    "ExecutorPool",
    "ExitStatus",
    "FaultPlan",
    "FitJournal",
    "GangFailedError",
    "GroupRevokedError",
    "HealthTracker",
    "JobFailedError",
    "Lineage",
    "ModelStore",
    "PartitionLostError",
    "PressureLevel",
    "ProcessGroup",
    "ResourceWatchdog",
    "ResultCorruptedError",
    "RuntimeMetrics",
    "Scheduler",
    "SchedulerPolicy",
    "ShardLineage",
    "TaskLostError",
    "TaskState",
    "WorkerContext",
    "check_write",
    "current_faults",
    "current_policy",
    "current_pressure_level",
    "default_checkpoint_dir",
    "get_watchdog",
    "inject_faults",
    "is_oom_error",
    "pick_port",
    "policy",
    "reduced_footprint",
    "result_crc",
    "run_partitioned",
    "set_pressure_level",
    "scrub_env",
    "worker_main",
]
