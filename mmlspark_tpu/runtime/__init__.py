"""mmlspark_tpu.runtime — a fault-tolerant partition scheduler.

MMLSpark's "runtime" was Spark's driver/executor model: partition
dispatch, bounded retries, heartbeat-based executor loss detection,
straggler re-dispatch, and lineage recompute all came for free. This
subsystem is our self-owned replacement — small, thread-based, and
deterministic enough to test fault recovery bit-for-bit:

- :mod:`~mmlspark_tpu.runtime.scheduler` — the driver: per-task state
  machine, seeded exponential backoff, deterministic result ordering;
- :mod:`~mmlspark_tpu.runtime.executor`  — the fleet: heartbeating
  worker pool with graceful drain and dead-worker replacement;
- :mod:`~mmlspark_tpu.runtime.lineage`   — recompute a lost partition
  from its recorded source instead of failing the job;
- :mod:`~mmlspark_tpu.runtime.faults`    — seeded fault injection for
  chaos tests: task-plane (kill-task, delay-task, drop-heartbeat) and
  HTTP-plane (503 storms, latency spikes, connection resets — consumed
  by the ``mmlspark_tpu.resilience`` layer's clients);
- :mod:`~mmlspark_tpu.runtime.metrics`   — per-task timings, retry
  counts, queue depth via ``core/profiling.py`` conventions.

Quick start::

    from mmlspark_tpu import runtime

    results = runtime.run_partitioned(process, shards,
                                      runtime.SchedulerPolicy(max_workers=4))

    # chaos: kill the executor running a random task, assert recovery
    plan = runtime.FaultPlan(seed=7).kill_random_task(len(shards))
    with runtime.inject_faults(plan):
        same = runtime.run_partitioned(process, shards)
    assert same == results and plan.fired
"""

from mmlspark_tpu.runtime.executor import ExecutorPool
from mmlspark_tpu.runtime.faults import (
    ExecutorDeathError,
    FaultPlan,
    current_faults,
    inject_faults,
)
from mmlspark_tpu.runtime.lineage import Lineage, PartitionLostError, ShardLineage
from mmlspark_tpu.runtime.metrics import RuntimeMetrics
from mmlspark_tpu.runtime.scheduler import (
    JobFailedError,
    Scheduler,
    SchedulerPolicy,
    TaskLostError,
    TaskState,
    current_policy,
    policy,
    run_partitioned,
)

__all__ = [
    "ExecutorDeathError",
    "ExecutorPool",
    "FaultPlan",
    "JobFailedError",
    "Lineage",
    "PartitionLostError",
    "RuntimeMetrics",
    "Scheduler",
    "SchedulerPolicy",
    "ShardLineage",
    "TaskLostError",
    "TaskState",
    "current_faults",
    "current_policy",
    "inject_faults",
    "policy",
    "run_partitioned",
]
