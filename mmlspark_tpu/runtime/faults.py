"""Deterministic fault injection for the partition scheduler.

Spark's fault-tolerance tests kill executors from the outside; here the
failure modes are injected as *seeded, deterministic* hooks that fire at
exact (task, attempt) points, so a fault-path test asserts on one specific
recovery sequence instead of racing a process killer:

- ``kill_task(n)``      — the executor running task ``n`` dies mid-task
  (raises :class:`ExecutorDeathError`; the worker thread exits and the
  pool replaces it, like a lost JVM executor);
- ``delay_task(n, s)``  — task ``n`` stalls ``s`` seconds before running
  (straggler / per-task-timeout scenarios);
- ``slow_task(n, s)``   — task ``n`` straggles for up to ``s`` seconds
  but wakes the moment the scheduler supersedes it: the *cancellable*
  straggler that speculative execution overtakes (``delay_task`` sleeps
  unconditionally; ``slow_task`` loses a first-result-wins race);
- ``corrupt_result(n)`` — the executor computes task ``n``'s result
  correctly, checksums it, then flips bytes before reporting — the
  driver's end-to-end CRC check must catch the mismatch and retry;
- ``drop_heartbeat(n)`` — the executor running task ``n`` stops
  heartbeating and hangs until the scheduler declares it lost and
  re-dispatches (the classic network-partitioned worker).

The process plane (``mmlspark_tpu.runtime.procgroup``) injects at OS
granularity — these faults kill *real* processes, not worker threads:

- ``kill_process(m)`` — member ``m`` of a supervised process group
  SIGKILLs itself at a designated fit iteration. The directive is
  serialized into the group's epoch spec and enacted worker-side, so the
  death is a genuine ``SIGKILL`` with no Python cleanup; the driver marks
  the fault fired when it observes the corpse.

The streaming plane (``mmlspark_tpu.streaming``) injects at epoch
boundaries — the query consults the ambient plan at its two designated
crash windows and SIGKILLs its own process:

- ``kill_stream(epoch, point)`` — the query dies at ``point`` of epoch
  ``epoch``: ``"post_wal"`` (offsets logged, nothing processed) or
  ``"pre_commit"`` (sink done, commit log missing — the window where only
  idempotent epoch-keyed sinks keep delivery exactly-once).

The request plane (``mmlspark_tpu.resilience``) injects at the HTTP
boundary instead of the task boundary — the outbound clients consult the
ambient plan before every wire call:

- ``http_storm(n)``   — the next ``n`` matching requests answer a
  synthetic 503 (or any status) without touching the network — the
  down-dependency storm that must trip a circuit breaker;
- ``http_delay(n,s)`` — the next ``n`` matching requests stall ``s``
  seconds first (tail-latency spike; pairs with deadline propagation);
- ``http_reset(n)``   — the next ``n`` matching requests raise
  ``ConnectionResetError`` (the mid-flight TCP reset).

The network plane injects *gray* failures — the link is degraded but
nobody is dead, the class of fault every other mode here cannot express:

- ``net_partition(a, b)`` — the link between gang members ``a`` and
  ``b`` goes dark: frames between them are silently swallowed in both
  directions (a half-open TCP connection), so each side stalls until
  its collective deadline revokes the epoch. With a string target
  (``net_partition("registry")``) the next matching outbound HTTP
  connection raises an unreachable ``OSError`` instead;
- ``net_delay(member, ms)`` — member ``member`` lags every outgoing
  frame by ``ms`` milliseconds (the slow link / slow peer); string
  targets stall the matching HTTP request;
- ``net_drop(member, p)`` — each of the member's outgoing frames is
  dropped with seeded probability ``p`` (lossy link); string targets
  time the matching HTTP request out;
- ``net_corrupt(member, n)`` — the member's next ``n`` outgoing frames
  are bit-flipped on the wire *after* checksumming, so the receiver's
  CRC check must catch them and the retransmit path must absorb them;
  string targets garble the matching HTTP response body.

Gang directives (int members) are serialized into the epoch spec and
enacted worker-side by :class:`~mmlspark_tpu.runtime.netchaos.NetChaos`
(seeded per member, so the chaos replays exactly); the supervisor marks
them fired when it observes the partition-triggered revocation
(:meth:`mark_net_fired`). String directives enact on the outbound HTTP
path via :func:`check_net`, *below* ``http_storm`` — storms fake status
codes without a socket, net chaos degrades the socket itself.

The exhaustion plane injects *resource* failures instead of crashes —
the class of fault the pressure watchdog and degradation ladders
(docs/resilience.md "Resource pressure") exist to absorb:

- ``oom_task(n, kind)`` — attempt 0 of task ``n`` raises an
  out-of-memory error at the task boundary: ``kind="host"`` raises
  ``MemoryError`` (a gang worker blowing host RSS), ``kind="device"``
  raises :class:`DeviceOomError` whose message carries
  ``RESOURCE_EXHAUSTED`` exactly like an XLA allocator failure. Device
  OOMs registered against a fit are consumed by the histogram dispatch
  (:func:`FaultPlan.apply_on_histogram`) keyed by iteration, so the
  GBDT degradation ladder is exercised at the real catch site;
- ``disk_full(substr, n)`` — the next ``n`` guarded writes whose path
  contains ``substr`` raise ``OSError(ENOSPC)``. Every durable writer
  (FitJournal, ModelStore, streaming WAL/commit, EventLogSink,
  FlightRecorder) consults :func:`check_write` first, so the injection
  lands at the exact byte-never-written point of each plane.

The data plane (``mmlspark_tpu.dataguard``) injects *poison* instead of
failures — the bytes arrive, but they are wrong, the class of fault the
corrupt-record read modes and dead-letter store exist to absorb:

- ``truncate_shard(substr, n)`` — the next ``n`` guarded shard reads
  whose path contains ``substr`` see a torn file: the reader's gate
  (:func:`check_record`) raises :class:`CorruptShardError` at the exact
  point a truncated npz/CRC-mismatched sidecar would surface, so under
  ``mode=permissive`` the whole shard quarantines and under
  ``failfast`` the read dies like before;
- ``corrupt_record(substr, index, n)`` — record ``index`` of a matching
  jsonl/json source is garbled in flight (:func:`corrupt_record_bytes`
  flips its bytes after the file read), exercising the *per-record*
  quarantine path rather than the whole-file one;
- ``malformed_request(n, kind)`` — loadgen's ``--malformed`` phase pops
  these (:meth:`FaultPlan.take_malformed`) to emit seeded poison
  payloads (``"json"`` garbage bytes, ``"schema"`` wrong-width vectors,
  ``"nan"`` non-finite features) against a serving endpoint, proving
  the edge 400s them and the poison breaker sheds the flood.

Each registered fault fires at most once; ``plan.fired`` records what
actually triggered, so tests assert the fault happened AND was survived.
``kill_random_task`` draws its victim from the plan's seeded RNG — the
"kill one executor at random" chaos test, reproducible run to run.
"""

from __future__ import annotations

import contextlib
import errno
import os
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np


class ExecutorDeathError(RuntimeError):
    """Simulated executor death: the worker thread running the task exits
    (the scheduler retries the task on a surviving/replacement worker)."""


class DeviceOomError(RuntimeError):
    """Simulated accelerator out-of-memory. The message carries the
    ``RESOURCE_EXHAUSTED`` marker XLA's allocator uses, so every catch
    site that classifies by :func:`is_oom_error` treats an injected
    device OOM exactly like the real ``XlaRuntimeError``."""


class CorruptShardError(RuntimeError):
    """Simulated shard corruption: the guarded read gate
    (:func:`check_record`) raises this where a torn npz / stale CRC
    sidecar would surface, so read-mode handling is exercised at the
    real catch site (``PartitionLostError`` and decode errors take the
    same permissive/dropmalformed/failfast paths)."""


class FaultPlan:
    """Seeded registry of (task, attempt)-keyed faults, consulted by
    executor workers as each attempt starts. Thread-safe; each fault pops
    when it fires so retries run clean."""

    def __init__(self, seed: Optional[int] = None):
        if seed is None:
            # CI's runtime-faults step pins this so every run replays the
            # exact same chaos (kill_random_task victims included)
            seed = int(os.environ.get("MMLSPARK_TPU_FAULT_SEED", "0"))
        self.seed = int(seed)
        self._rng = np.random.default_rng(seed)
        self._kill = {}
        self._delay = {}
        self._slow = {}
        self._corrupt = {}
        self._drop_beat = {}
        #: [{member, iteration, epoch}] process-kill directives, serialized
        #: into the process group's epoch spec and enacted worker-side
        self._kill_process: List[dict] = []
        #: [{epoch, point}] streaming-query kill points, enacted in-process
        #: by StreamingQuery._maybe_die as a real SIGKILL
        self._kill_stream: List[dict] = []
        #: ordered HTTP fault directives, consumed first-match per request
        self._http: List[dict] = []
        self._http_seq = 0
        #: ordered network-degradation directives: gang-targeted entries
        #: (int members) ship in the epoch spec; HTTP-targeted entries
        #: (str url parts) are consumed by :meth:`apply_on_socket`
        self._net: List[dict] = []
        self._net_seq = 0
        #: (index, attempt) -> "host"|"device" out-of-memory directives
        self._oom: Dict[Tuple[int, int], str] = {}
        #: ordered disk-full directives, consumed first-match per write
        self._disk_full: List[dict] = []
        self._write_seq = 0
        #: ordered torn-shard directives, consumed first-match per read
        self._truncate: List[dict] = []
        #: ordered per-record corruption directives (jsonl/json sources)
        self._corrupt_record: List[dict] = []
        self._record_seq = 0
        #: ordered malformed-request directives, popped by loadgen
        self._malformed: List[dict] = []
        self._malformed_seq = 0
        self._lock = threading.Lock()
        #: [(kind, task_index, attempt)] in fire order
        self.fired: List[Tuple[str, int, int]] = []

    # -- registration (chainable) -------------------------------------------

    def kill_task(self, index: int, attempt: int = 0) -> "FaultPlan":
        self._kill[(int(index), int(attempt))] = True
        return self

    def delay_task(self, index: int, seconds: float, attempt: int = 0) -> "FaultPlan":
        self._delay[(int(index), int(attempt))] = float(seconds)
        return self

    def slow_task(self, index: int, seconds: float, attempt: int = 0) -> "FaultPlan":
        """Attempt ``attempt`` of task ``index`` straggles: it blocks up
        to ``seconds`` but wakes immediately when superseded (a speculative
        copy won, or the driver re-dispatched it), then runs the task body
        normally. The deterministic straggler speculation must overtake."""
        self._slow[(int(index), int(attempt))] = float(seconds)
        return self

    def corrupt_result(self, index: int, attempt: int = 0) -> "FaultPlan":
        """Attempt ``attempt`` of task ``index`` computes its result
        correctly and checksums it, then the reported value is corrupted
        in flight (bit flip / tainted object). The scheduler's result
        integrity check sees the CRC mismatch, books a retryable
        ``corrupt`` failure, and the retry runs clean."""
        self._corrupt[(int(index), int(attempt))] = True
        return self

    def drop_heartbeat(
        self, index: int, attempt: int = 0, hold: float = 30.0
    ) -> "FaultPlan":
        """The executor running attempt ``attempt`` of task ``index`` stops
        heartbeating and blocks (up to ``hold`` seconds, or until the
        scheduler supersedes the attempt), then dies."""
        self._drop_beat[(int(index), int(attempt))] = float(hold)
        return self

    def kill_random_task(self, num_tasks: int, attempt: int = 0) -> "FaultPlan":
        """Seeded kill-one-executor: the victim index is drawn from the
        plan's RNG, so the chaos is reproducible."""
        return self.kill_task(int(self._rng.integers(num_tasks)), attempt)

    def kill_process(
        self, member: int, iteration: int = 0, epoch: int = 0
    ) -> "FaultPlan":
        """Member ``member`` of a supervised process group SIGKILLs itself
        at the start of fit ``iteration`` during gang ``epoch`` — a real
        OS-level death (no atexit, no socket shutdown handshake), the kind
        quarantine/speculation/gang-recovery exist for. Enacted worker-side
        via the serialized directive (:meth:`process_kill_directives`);
        the supervisor marks it fired when it observes the death
        (:meth:`mark_process_killed`)."""
        self._kill_process.append({
            "member": int(member), "iteration": int(iteration),
            "epoch": int(epoch),
        })
        return self

    def kill_random_process(
        self, num_members: int, iteration: int = 0, epoch: int = 0
    ) -> "FaultPlan":
        """Seeded kill-one-process chaos, reproducible run to run."""
        return self.kill_process(
            int(self._rng.integers(num_members)), iteration, epoch
        )

    def process_kill_directives(self) -> List[dict]:
        """JSON-serializable process-kill directives for the supervisor to
        embed in the epoch spec it hands each worker."""
        with self._lock:
            return [dict(d) for d in self._kill_process]

    def mark_process_killed(self, member: int) -> bool:
        """Driver-side acknowledgement: the supervisor observed member
        ``member`` die for a registered directive. Pops the first
        directive for that member and books it in ``fired`` (kind
        ``kill_process``, third field = the directive's epoch)."""
        with self._lock:
            for i, d in enumerate(self._kill_process):
                if d["member"] == int(member):
                    popped = self._kill_process.pop(i)
                    break
            else:
                return False
        self.fired.append(("kill_process", int(member), int(popped["epoch"])))
        return True

    def kill_stream(self, epoch: int, point: str = "pre_commit") -> "FaultPlan":
        """The streaming query SIGKILLs its own process at ``point`` of
        epoch ``epoch`` — ``"post_wal"`` (plan durably logged, nothing
        processed yet) or ``"pre_commit"`` (sink ran, commit log not yet
        written: the nastiest window, where restart re-delivers the epoch
        and only sink idempotence keeps it exactly-once)."""
        if point not in ("post_wal", "pre_commit"):
            raise ValueError(
                f"unknown stream kill point {point!r} "
                "(expected 'post_wal' or 'pre_commit')"
            )
        self._kill_stream.append({"epoch": int(epoch), "point": str(point)})
        return self

    def should_kill_stream(self, epoch: int, point: str) -> bool:
        """Consulted by the streaming query at each designated crash
        window. Pops the first matching directive and books it in
        ``fired`` (kind ``kill_stream``); the caller then SIGKILLs
        itself — this return value is its death warrant."""
        with self._lock:
            for i, d in enumerate(self._kill_stream):
                if d["epoch"] == int(epoch) and d["point"] == str(point):
                    self._kill_stream.pop(i)
                    break
            else:
                return False
        self.fired.append(("kill_stream", int(epoch), 0))
        return True

    @staticmethod
    def should_die(
        directives: List[dict], member: int, iteration: int, epoch: int
    ) -> bool:
        """Worker-side check against the directives shipped in the epoch
        spec: True when this (member, iteration, epoch) is a designated
        death point. Static so workers need no live FaultPlan object."""
        for d in directives or []:
            if (
                int(d.get("member", -1)) == int(member)
                and int(d.get("iteration", 0)) == int(iteration)
                and int(d.get("epoch", 0)) == int(epoch)
            ):
                return True
        return False

    def http_storm(
        self,
        count: int = 1,
        status: int = 503,
        url_part: str = "",
        retry_after: Optional[float] = None,
    ) -> "FaultPlan":
        """The next ``count`` requests whose URL contains ``url_part``
        answer a synthetic ``status`` (default 503) without a wire call;
        ``retry_after`` adds a Retry-After header to the fake response."""
        self._http.append({
            "kind": "status", "n": int(count), "status": int(status),
            "url_part": url_part, "retry_after": retry_after,
        })
        return self

    def http_delay(
        self, count: int = 1, seconds: float = 0.05, url_part: str = ""
    ) -> "FaultPlan":
        """The next ``count`` matching requests stall ``seconds`` before
        going to the wire (injected tail-latency spike)."""
        self._http.append({
            "kind": "delay", "n": int(count), "seconds": float(seconds),
            "url_part": url_part,
        })
        return self

    def http_reset(self, count: int = 1, url_part: str = "") -> "FaultPlan":
        """The next ``count`` matching requests die with
        ``ConnectionResetError`` (mid-flight TCP reset)."""
        self._http.append({
            "kind": "reset", "n": int(count), "url_part": url_part,
        })
        return self

    def net_partition(
        self, a, b: int = 0, epoch: int = 0,
        after_round: int = 0, count: int = 1,
    ) -> "FaultPlan":
        """Partition the link between gang members ``a`` and ``b`` during
        gang ``epoch``: from allreduce round ``after_round`` on, frames
        between them are swallowed in both directions and each side's
        collective deadline — not a hang — ends the epoch. With a string
        ``a`` (URL substring) the next ``count`` matching outbound HTTP
        connections raise an unreachable ``OSError`` instead."""
        if isinstance(a, str):
            self._net.append({
                "target": "http", "kind": "partition",
                "url_part": str(a), "n": int(count),
            })
        else:
            self._net.append({
                "target": "gang", "kind": "partition", "a": int(a),
                "b": int(b), "epoch": int(epoch),
                "after_round": int(after_round),
            })
        return self

    def net_delay(
        self, member, ms: float, epoch: int = 0, count: int = 1
    ) -> "FaultPlan":
        """Member ``member`` lags every outgoing frame of gang ``epoch``
        by ``ms`` milliseconds (the slow peer the soft slow-peer detector
        and, past the io deadline, the revoke path exist for). String
        targets stall the next ``count`` matching HTTP requests."""
        if isinstance(member, str):
            self._net.append({
                "target": "http", "kind": "delay",
                "url_part": str(member), "n": int(count), "ms": float(ms),
            })
        else:
            self._net.append({
                "target": "gang", "kind": "delay", "member": int(member),
                "ms": float(ms), "epoch": int(epoch),
            })
        return self

    def net_drop(
        self, member, p: float, epoch: int = 0, count: int = 1
    ) -> "FaultPlan":
        """Each outgoing frame of gang member ``member`` is dropped with
        probability ``p``, drawn from the worker's seeded RNG — a lossy
        link, reproducible run to run. String targets make the next
        ``count`` matching HTTP requests time out."""
        if isinstance(member, str):
            self._net.append({
                "target": "http", "kind": "drop",
                "url_part": str(member), "n": int(count), "p": float(p),
            })
        else:
            self._net.append({
                "target": "gang", "kind": "drop", "member": int(member),
                "p": float(p), "epoch": int(epoch),
            })
        return self

    def net_corrupt(
        self, member, n: int = 1, epoch: int = 0
    ) -> "FaultPlan":
        """The next ``n`` frames gang member ``member`` sends are
        bit-flipped *after* checksumming — on-the-wire corruption the
        receiver's CRC check must reject and the bounded retransmit must
        absorb (the fit stays byte-identical). String targets garble the
        next ``n`` matching HTTP response bodies, exercising the
        malformed-payload tolerance of the consumer."""
        if isinstance(member, str):
            self._net.append({
                "target": "http", "kind": "corrupt",
                "url_part": str(member), "n": int(n),
            })
        else:
            self._net.append({
                "target": "gang", "kind": "corrupt", "member": int(member),
                "n": int(n), "epoch": int(epoch),
            })
        return self

    def net_directives(self, epoch: Optional[int] = None) -> List[dict]:
        """JSON-serializable gang-targeted net directives (for ``epoch``
        when given) for the supervisor to embed in the epoch spec. Not
        consumed here — the driver pops them via :meth:`mark_net_fired`
        when it observes the degradation's effect."""
        with self._lock:
            return [
                dict(d) for d in self._net
                if d["target"] == "gang"
                and (epoch is None or d["epoch"] == int(epoch))
            ]

    def mark_net_fired(
        self, kind: str, member: Optional[int] = None,
        epoch: Optional[int] = None,
    ) -> bool:
        """Driver-side acknowledgement: the supervisor observed the effect
        of a gang net directive (a partition-triggered revocation, a
        retransmit-absorbed corruption). Pops the first matching directive
        and books it in ``fired`` as ``("net_<kind>", member, epoch)``."""

        def _involves(d: dict, m: int) -> bool:
            if "member" in d:
                return int(d["member"]) == m
            return m in (int(d.get("a", -1)), int(d.get("b", -1)))

        with self._lock:
            popped = None
            for i, d in enumerate(self._net):
                if d["target"] != "gang" or d["kind"] != str(kind):
                    continue
                if epoch is not None and d["epoch"] != int(epoch):
                    continue
                if member is not None and not _involves(d, int(member)):
                    continue
                popped = self._net.pop(i)
                break
        if popped is None:
            return False
        who = member if member is not None else popped.get(
            "member", popped.get("a", -1))
        self.fired.append((f"net_{kind}", int(who), int(popped["epoch"])))
        return True

    def apply_on_socket(self, url: str) -> Optional[dict]:
        """Pop the first HTTP-targeted net directive matching ``url``, or
        None. The caller (:func:`check_net`, below ``http_storm`` in the
        client stack) enacts it at the socket boundary: raise the
        unreachable error, sleep the delay, time out, or garble the
        response body. Consumed in registration order, one per request."""
        with self._lock:
            directive = None
            for d in self._net:
                if (
                    d["target"] == "http" and d["n"] > 0
                    and d["url_part"] in url
                ):
                    d["n"] -= 1
                    directive = dict(d)
                    break
            if directive is None:
                return None
            self._net = [
                d for d in self._net
                if d["target"] != "http" or d["n"] > 0
            ]
            seq = self._net_seq
            self._net_seq += 1
        self.fired.append((f"net_{directive['kind']}", seq, 0))
        return directive

    def oom_task(
        self, index: int, kind: str = "host", attempt: int = 0
    ) -> "FaultPlan":
        """Attempt ``attempt`` of task ``index`` exhausts memory at its
        boundary: ``kind="host"`` raises ``MemoryError`` (host RSS blown),
        ``kind="device"`` raises :class:`DeviceOomError` with a
        ``RESOURCE_EXHAUSTED`` message (HBM allocation failure). Device
        OOMs registered against a GBDT fit fire from the histogram
        dispatch instead (``index`` = fit iteration), so the in-loop
        degradation ladder is what absorbs them."""
        if kind not in ("host", "device"):
            raise ValueError(
                f"unknown OOM kind {kind!r} (expected 'host' or 'device')"
            )
        self._oom[(int(index), int(attempt))] = str(kind)
        return self

    def disk_full(self, path_substr: str, count: int = 1) -> "FaultPlan":
        """The next ``count`` guarded writes whose target path contains
        ``path_substr`` raise ``OSError(ENOSPC)`` before any byte is
        written — the volume under the journal/WAL/event log filling up.
        Consumed by :func:`check_write`, which every durable writer calls
        first, so the fault leaves no torn file behind."""
        self._disk_full.append({"substr": str(path_substr), "n": int(count)})
        return self

    def truncate_shard(self, path_substr: str, count: int = 1) -> "FaultPlan":
        """The next ``count`` guarded shard reads whose path contains
        ``path_substr`` raise :class:`CorruptShardError` — a torn file /
        stale CRC sidecar, surfaced at the read gate
        (:func:`check_record`) before any byte is decoded. Under
        ``mode=permissive`` the shard quarantines to the dead-letter
        store; under ``failfast`` the read dies exactly like a real
        ``PartitionLostError``."""
        self._truncate.append({"substr": str(path_substr), "n": int(count)})
        return self

    def corrupt_record(
        self, path_substr: str, index: int = 0, count: int = 1
    ) -> "FaultPlan":
        """Record ``index`` of the next ``count`` matching record-oriented
        sources (jsonl/json) is garbled after the file read
        (:func:`corrupt_record_bytes` flips its bytes), so the decode
        fails for *that record only* — the per-record quarantine path,
        as opposed to :meth:`truncate_shard`'s whole-file path."""
        self._corrupt_record.append({
            "substr": str(path_substr), "index": int(index), "n": int(count),
        })
        return self

    def malformed_request(self, count: int = 1, kind: str = "json") -> "FaultPlan":
        """Loadgen's ``--malformed`` phase pops the next directive per
        poison request (:meth:`take_malformed`) and emits the matching
        payload class: ``"json"`` (undecodable bytes), ``"schema"``
        (wrong-width feature vector), ``"nan"`` (non-finite features).
        The serving edge must answer structured 400s and the per-client
        breaker must shed the flood — never a batch-loop exception."""
        if kind not in ("json", "schema", "nan"):
            raise ValueError(
                f"unknown malformed-request kind {kind!r} "
                "(expected 'json', 'schema' or 'nan')"
            )
        self._malformed.append({"kind": str(kind), "n": int(count)})
        return self

    def apply_on_record(self, path: str) -> None:
        """Pop the first registered ``truncate_shard`` directive matching
        ``path`` and raise :class:`CorruptShardError`. Called by shard
        readers (via :func:`check_record`) right before decoding a file,
        so the injected corruption surfaces exactly where a real torn
        file would. Directives are consumed in order, one per read."""
        with self._lock:
            matched = None
            for d in self._truncate:
                if d["n"] > 0 and d["substr"] in str(path):
                    d["n"] -= 1
                    matched = d
                    break
            if matched is None:
                return
            self._truncate = [d for d in self._truncate if d["n"] > 0]
            seq = self._record_seq
            self._record_seq += 1
        self.fired.append(("truncate_shard", seq, 0))
        raise CorruptShardError(
            f"truncated shard (injected): {path}"
        )

    def apply_on_record_bytes(self, path: str, index: int, data: bytes) -> bytes:
        """Pop the first registered ``corrupt_record`` directive matching
        (``path``, ``index``) and return a garbled copy of ``data`` (the
        raw bytes of that one record); unmatched reads get ``data`` back
        untouched. Called by record-oriented loaders per record."""
        with self._lock:
            matched = None
            for d in self._corrupt_record:
                if (
                    d["n"] > 0 and d["substr"] in str(path)
                    and d["index"] == int(index)
                ):
                    d["n"] -= 1
                    matched = d
                    break
            if matched is None:
                return data
            self._corrupt_record = [
                d for d in self._corrupt_record if d["n"] > 0
            ]
        self.fired.append(("corrupt_record", int(index), 0))
        # Prefix with bytes no JSON decoder accepts, keeping the original
        # visible for debugging quarantined records.
        return b"\xff\xfe<corrupt>" + bytes(data)

    def take_malformed(self) -> Optional[str]:
        """Pop one malformed-request directive and return its kind
        (``"json"``/``"schema"``/``"nan"``), or None when the storm is
        exhausted. Booked in ``fired`` as ``("malformed_request", seq, 0)``."""
        with self._lock:
            directive = None
            for d in self._malformed:
                if d["n"] > 0:
                    d["n"] -= 1
                    directive = d
                    break
            if directive is None:
                return None
            self._malformed = [d for d in self._malformed if d["n"] > 0]
            seq = self._malformed_seq
            self._malformed_seq += 1
        self.fired.append(("malformed_request", seq, 0))
        return directive["kind"]

    def will_corrupt(self, index: int, attempt: int) -> bool:
        """True while a ``corrupt_result`` fault is registered for this
        (task, attempt) — the executor checks this to know it must take
        the result checksum even when ``policy.result_integrity`` is off."""
        with self._lock:
            return (int(index), int(attempt)) in self._corrupt

    @property
    def pending(self) -> int:
        with self._lock:
            return (
                len(self._kill) + len(self._delay) + len(self._drop_beat)
                + len(self._slow) + len(self._corrupt)
                + len(self._kill_process) + len(self._kill_stream)
                + sum(d["n"] for d in self._http)
                + len(self._oom)
                + sum(d["n"] for d in self._disk_full)
                + sum(d["n"] for d in self._truncate)
                + sum(d["n"] for d in self._corrupt_record)
                + sum(d["n"] for d in self._malformed)
                + sum(
                    d["n"] if d["target"] == "http" else 1
                    for d in self._net
                )
            )

    # -- worker-side hook ----------------------------------------------------

    def apply_on_start(
        self,
        index: int,
        attempt: int,
        worker=None,
        superseded: Optional[threading.Event] = None,
    ) -> None:
        """Fire any faults registered for this (task, attempt). Called by
        the executor worker immediately before running the task body."""
        key = (int(index), int(attempt))
        with self._lock:
            delay = self._delay.pop(key, None)
            slow = self._slow.pop(key, None)
            drop = self._drop_beat.pop(key, None)
            kill = self._kill.pop(key, None)
            oom = self._oom.pop(key, None)
        if delay is not None:
            self.fired.append(("delay", index, attempt))
            time.sleep(delay)
        if slow is not None:
            self.fired.append(("slow_task", index, attempt))
            # straggle, but stay cancellable: a speculative win (or any
            # supersede) sets the event and this attempt stops stalling
            if superseded is not None:
                superseded.wait(timeout=slow)
            else:
                time.sleep(slow)
        if drop is not None:
            self.fired.append(("drop_heartbeat", index, attempt))
            if worker is not None:
                worker.beat_suppressed = True
            # hang (no heartbeats) until the scheduler declares this
            # executor lost and re-dispatches, then die like one
            if superseded is not None:
                superseded.wait(timeout=drop)
            else:
                time.sleep(drop)
            raise ExecutorDeathError(
                f"injected heartbeat loss on task {index} attempt {attempt}"
            )
        if kill:
            self.fired.append(("kill", index, attempt))
            raise ExecutorDeathError(
                f"injected executor death on task {index} attempt {attempt}"
            )
        if oom is not None:
            self.fired.append((f"oom_{oom}", index, attempt))
            if oom == "host":
                raise MemoryError(
                    f"injected host OOM on task {index} attempt {attempt}"
                )
            raise DeviceOomError(
                "RESOURCE_EXHAUSTED: injected device OOM on task "
                f"{index} attempt {attempt}"
            )

    def apply_on_histogram(self, iteration: int, attempt: int) -> None:
        """Consulted by the GBDT histogram dispatch before each launch.
        Pops a registered *device* OOM keyed (iteration, retry-attempt)
        and raises it as :class:`DeviceOomError` — the train loop's
        ``RESOURCE_EXHAUSTED`` catch then walks the degradation ladder
        and retries the same iteration. Host OOMs are never fired here;
        they belong to the task boundary."""
        key = (int(iteration), int(attempt))
        with self._lock:
            kind = self._oom.get(key)
            if kind != "device":
                return
            self._oom.pop(key)
        self.fired.append(("oom_device", int(iteration), int(attempt)))
        raise DeviceOomError(
            "RESOURCE_EXHAUSTED: injected device OOM at histogram "
            f"iteration {iteration} attempt {attempt}"
        )

    def apply_on_result(self, index: int, attempt: int, result):
        """Consulted by the executor AFTER the task body returns and AFTER
        the result checksum is taken. If a ``corrupt_result`` fault is
        registered for this (task, attempt), return a corrupted copy of
        ``result`` (deterministic bit flip) — simulating corruption between
        executor and driver; otherwise return ``result`` unchanged."""
        key = (int(index), int(attempt))
        with self._lock:
            corrupt = self._corrupt.pop(key, None)
        if not corrupt:
            return result
        self.fired.append(("corrupt_result", index, attempt))
        return _corrupted_copy(result)

    # -- HTTP-side hook (consulted by io/http clients per request) -----------

    def apply_on_http(self, url: str) -> Optional[dict]:
        """Pop the first registered HTTP fault matching ``url``, or None.
        The caller (the HTTP client) enacts the directive: synthesize the
        status, sleep the delay, or raise the reset. Directives are
        consumed in registration order, one per request."""
        with self._lock:
            directive = None
            for d in self._http:
                if d["n"] > 0 and d["url_part"] in url:
                    d["n"] -= 1
                    directive = dict(d)
                    break
            if directive is None:
                return None
            self._http = [d for d in self._http if d["n"] > 0]
            seq = self._http_seq
            self._http_seq += 1
        kind = directive["kind"]
        self.fired.append((
            f"http_{kind}",
            seq,
            directive["status"] if kind == "status" else 0,
        ))
        return directive

    # -- write-side hook (consulted by durable writers per file) -------------

    def apply_on_write(self, path: str) -> None:
        """Pop the first registered ``disk_full`` directive matching
        ``path`` and raise ``OSError(ENOSPC)`` — before the caller opens
        the file, so the failed write is clean (no torn temp file).
        Directives are consumed in registration order, one per write."""
        with self._lock:
            matched = None
            for d in self._disk_full:
                if d["n"] > 0 and d["substr"] in str(path):
                    d["n"] -= 1
                    matched = d
                    break
            if matched is None:
                return
            self._disk_full = [d for d in self._disk_full if d["n"] > 0]
            seq = self._write_seq
            self._write_seq += 1
        self.fired.append(("disk_full", seq, 0))
        raise OSError(
            errno.ENOSPC, "No space left on device (injected)", str(path)
        )


class _TaintedResult:
    """Opaque stand-in for a result corrupted beyond byte-flipping (the
    payload was not a buffer type). Never equal to the clean value, and
    pickles to different bytes, so every checksum path catches it."""

    def __init__(self, original):
        self.original = original

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"_TaintedResult({self.original!r})"


def _corrupted_copy(result):
    """A deterministically corrupted copy of ``result``: byte-flip for
    buffer-like payloads, a tainted wrapper otherwise."""
    if isinstance(result, np.ndarray) and result.size and result.dtype != object:
        bad = result.copy()
        view = bad.view(np.uint8).reshape(-1)
        view[0] ^= 0xFF
        return bad
    if isinstance(result, (bytes, bytearray)) and len(result):
        bad = bytearray(result)
        bad[0] ^= 0xFF
        return bytes(bad)
    return _TaintedResult(result)


# -- ambient injection (reaches schedulers created inside fit/serve calls) --

_ACTIVE: List[FaultPlan] = []


@contextlib.contextmanager
def inject_faults(plan: FaultPlan) -> Iterator[FaultPlan]:
    """Make ``plan`` visible to every scheduler whose policy carries no
    explicit plan — the way a test injects executor death into a
    ``LightGBMClassifier.fit`` without threading a plan through the API."""
    _ACTIVE.append(plan)
    try:
        yield plan
    finally:
        _ACTIVE.remove(plan)


def current_faults() -> Optional[FaultPlan]:
    return _ACTIVE[-1] if _ACTIVE else None


def check_write(path: str) -> None:
    """Guarded-write gate: every durable writer (journal checkpoints,
    ModelStore commits, streaming WAL/commit, event-log sink, incident
    bundles) calls this with its target path before touching the
    filesystem. Raises ``OSError(ENOSPC)`` when the ambient plan holds a
    matching :meth:`FaultPlan.disk_full` directive; no-op otherwise."""
    plan = current_faults()
    if plan is not None:
        plan.apply_on_write(path)


def check_record(path: str) -> None:
    """Guarded-read gate: shard/file readers call this with the source
    path before decoding it. Raises :class:`CorruptShardError` when the
    ambient plan holds a matching :meth:`FaultPlan.truncate_shard`
    directive; no-op otherwise. The raise lands where a real torn file
    would, so read-mode handling (permissive quarantine vs failfast
    death) is exercised at the genuine catch site."""
    plan = current_faults()
    if plan is not None:
        plan.apply_on_record(path)


def corrupt_record_bytes(path: str, index: int, data: bytes) -> bytes:
    """Per-record corruption gate: record-oriented loaders (jsonl/json)
    pass each record's raw bytes through here after the file read. A
    matching :meth:`FaultPlan.corrupt_record` directive garbles the
    bytes so only that record fails to decode; otherwise ``data`` is
    returned untouched."""
    plan = current_faults()
    if plan is None:
        return data
    return plan.apply_on_record_bytes(path, index, data)


def check_net(url: str) -> Optional[dict]:
    """Net-chaos gate for outbound HTTP: every registry/router client
    calls this with its target URL right before opening the socket —
    *below* ``http_storm``, which answers without a socket at all. Enacts
    any ambient HTTP-targeted net directive: ``partition`` raises an
    unreachable ``OSError``, ``delay`` sleeps, ``drop`` raises
    ``socket.timeout``; a ``corrupt`` directive is returned for the
    caller to garble the received body with (callers that ignore the
    return value simply skip response corruption). No-op without a plan."""
    plan = current_faults()
    if plan is None:
        return None
    directive = plan.apply_on_socket(url)
    if directive is None:
        return None
    try:  # the counter is observability, never a reason to skip the fault
        from mmlspark_tpu.observability import get_registry

        get_registry().counter(
            "netchaos_http_faults_total",
            "Injected network degradations enacted on the HTTP client path",
        ).inc()
    except Exception:  # noqa: BLE001 - registry unavailable in stripped envs
        pass
    kind = directive["kind"]
    if kind == "partition":
        raise OSError(
            errno.EHOSTUNREACH, "Network partition (injected)", url
        )
    if kind == "delay":
        time.sleep(directive["ms"] / 1000.0)
        return None
    if kind == "drop":
        import socket

        raise socket.timeout(f"injected frame drop for {url}")
    return directive  # "corrupt": caller garbles the response body


def is_oom_error(err: BaseException) -> bool:
    """Classify ``err`` as memory exhaustion: a host ``MemoryError`` or
    any error whose message carries XLA's ``RESOURCE_EXHAUSTED`` marker
    (real ``XlaRuntimeError`` allocation failures and the injected
    :class:`DeviceOomError` alike)."""
    return isinstance(err, MemoryError) or "RESOURCE_EXHAUSTED" in str(err)
