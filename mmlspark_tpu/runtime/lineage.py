"""Partition lineage — recompute a lost shard instead of failing the job.

Spark's RDD lineage rebuilds a lost partition by replaying the narrow
dependencies that produced it. The reproduction here is deliberately
smaller: a shard's lineage is ``source`` (a zero-arg closure returning the
raw partition payload, e.g. a memmap slice read) plus an ordered tuple of
pure ``transforms`` applied to it. When an executor is lost *and* the task
failure indicates its input is gone (:class:`PartitionLostError`), the
scheduler asks the :class:`Lineage` registry to materialize the shard
again from source — the retry then runs on the recomputed payload.

Because every transform is pure and the source read is deterministic, a
recomputed partition is bit-identical to the original — which is what
makes fault-injected fits produce bit-identical model text.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
from typing import Any, Callable, Dict, Tuple


class PartitionLostError(RuntimeError):
    """A task's input partition is gone (evicted buffer, dead host). If the
    shard has recorded lineage the scheduler recomputes it and retries;
    otherwise the failure counts against the task's retry budget as usual."""


@dataclasses.dataclass
class ShardLineage:
    """How to rebuild one partition payload from scratch."""

    source: Callable[[], Any]
    transforms: Tuple[Callable[[Any], Any], ...] = ()
    describe: str = ""

    def materialize(self) -> Any:
        payload = self.source()
        for fn in self.transforms:
            payload = fn(payload)
        return payload


class Lineage:
    """Registry of per-task-index shard lineage for one partitioned job."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._shards: Dict[int, ShardLineage] = {}
        self.recomputes: "collections.Counter[int]" = collections.Counter()

    def record(
        self,
        index: int,
        source: Callable[[], Any],
        *transforms: Callable[[Any], Any],
        describe: str = "",
    ) -> ShardLineage:
        shard = ShardLineage(source=source, transforms=transforms, describe=describe)
        with self._lock:
            self._shards[int(index)] = shard
        return shard

    def has(self, index: int) -> bool:
        with self._lock:
            return int(index) in self._shards

    def recompute(self, index: int) -> Any:
        with self._lock:
            shard = self._shards[int(index)]
            self.recomputes[int(index)] += 1
        return shard.materialize()
