"""Resource-pressure watchdog and the process-wide pressure level.

Spark's memory plane is managed: UnifiedMemoryManager arbitrates
execution vs. storage inside a ``spark.memory.fraction`` budget, spills
to disk under pressure, and (with OOM-aware ``excludeOnFailure``) routes
work away from executors that keep dying. A JAX/TPU stack has no
manager to hide behind — an HBM allocation either fits or raises
``RESOURCE_EXHAUSTED`` — so this module supplies the *observed* analogue:

- :class:`ResourceWatchdog` samples HBM (``Device.memory_stats()`` via
  the profiler), host RSS (``/proc/self/status``), and free disk on the
  checkpoint and event-log volumes; threshold crossings publish
  :class:`~mmlspark_tpu.observability.events.MemoryPressure` /
  ``DiskPressure`` events, export ``pressure_*`` gauges, and set the
  process-wide :class:`PressureLevel`;
- :func:`current_pressure_level` is the cheap ambient read consumers
  poll: the serving admission controller and batch loop tighten their
  bounds under WARN/CRITICAL (shed *before* OOM) and restore when the
  level clears; ``ShardedDataset`` splits bin tasks into smaller row
  ranges under host-memory pressure;
- :func:`reduced_footprint` is the scheduler's relaunch hint: a task
  that OOMed is retried under a footprint hint equal to its OOM failure
  count, so the task body (when it cares) can shrink its working set —
  the "retry smaller" half of graceful degradation.

Level transitions publish BOTH the onset (warn/critical) and the
recovery (level ``"ok"``), so every pressure onset in an event log pairs
with either a degradation event or a recovery record
(``tools/check_eventlog.py --pressure`` enforces this).
"""

from __future__ import annotations

import contextlib
import enum
import os
import shutil
import threading
from typing import Callable, Dict, List, Optional, Tuple

from mmlspark_tpu.core.profiling import get_logger

logger = get_logger("mmlspark_tpu.runtime")


class PressureLevel(enum.IntEnum):
    """Ordered severity of resource pressure; comparable with ``>=``."""

    OK = 0
    WARN = 1
    CRITICAL = 2


_LEVEL_LOCK = threading.Lock()
_LEVELS: Dict[str, PressureLevel] = {
    "memory": PressureLevel.OK,
    "disk": PressureLevel.OK,
}


def current_pressure_level(kind: str = "memory") -> PressureLevel:
    """The process-wide pressure level for ``kind`` ("memory"/"disk").
    One dict read — cheap enough for per-request consultation."""
    with _LEVEL_LOCK:
        return _LEVELS.get(kind, PressureLevel.OK)


def set_pressure_level(kind: str, level: PressureLevel) -> PressureLevel:
    """Set the ambient level (the watchdog's job; tests drive it
    directly to exercise consumers). Returns the previous level."""
    with _LEVEL_LOCK:
        prev = _LEVELS.get(kind, PressureLevel.OK)
        _LEVELS[kind] = PressureLevel(level)
    return prev


# -- reduced-footprint relaunch hint ------------------------------------------

_FOOTPRINT = threading.local()


def reduced_footprint() -> int:
    """How many times the current task attempt has OOMed before (0 = a
    clean first run). Task bodies that allocate proportionally consult
    this to shrink their working set on an OOM relaunch."""
    return int(getattr(_FOOTPRINT, "level", 0))


@contextlib.contextmanager
def _footprint_hint(level: int):
    """Scheduler-side: run a task attempt under a reduced-footprint
    hint (its OOM failure count)."""
    prev = getattr(_FOOTPRINT, "level", 0)
    _FOOTPRINT.level = int(level)
    try:
        yield
    finally:
        _FOOTPRINT.level = prev


# -- samplers (injectable for tests) ------------------------------------------


def sample_hbm() -> List[Tuple[str, float, float]]:
    """(device, bytes_in_use, bytes_limit) per reporting device; [] on
    backends that don't report (CPU) — always safe."""
    try:
        from mmlspark_tpu.observability.profiler import get_profiler

        stats = get_profiler().sample_memory()
    except Exception:  # noqa: BLE001 - no backend is a valid state
        return []
    out = []
    for device, rec in stats.items():
        used = rec.get("bytes_in_use")
        limit = rec.get("bytes_limit")
        if used is not None and limit:
            out.append((device, float(used), float(limit)))
    return out


def sample_host_rss() -> Optional[Tuple[float, float]]:
    """(rss_bytes, total_bytes) for this process vs. the host, or None
    when the platform doesn't expose either (non-Linux without
    ``resource``)."""
    rss = total = None
    try:
        with open("/proc/self/status", "r", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    rss = float(line.split()[1]) * 1024.0
                    break
        with open("/proc/meminfo", "r", encoding="ascii") as fh:
            for line in fh:
                if line.startswith("MemTotal:"):
                    total = float(line.split()[1]) * 1024.0
                    break
    except OSError:
        pass
    if rss is None:
        try:
            import resource

            # ru_maxrss is KiB on Linux
            rss = float(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            ) * 1024.0
        except Exception:  # noqa: BLE001
            return None
    if not total:
        return None
    return rss, total


def sample_disk(path: str) -> Optional[Tuple[float, float]]:
    """(free_bytes, total_bytes) for the volume holding ``path``."""
    try:
        usage = shutil.disk_usage(path)
    except OSError:
        return None
    return float(usage.free), float(usage.total)


class ResourceWatchdog:
    """Periodic sampler of HBM / host RSS / durable-volume free space.

    ``poll()`` takes one sample round: each source's utilisation is
    compared against ``warn_fraction`` / ``critical_fraction`` (for disk
    the *used* fraction of the volume), the worst source sets the
    process-wide level for its kind, and level *transitions* publish
    ``MemoryPressure``/``DiskPressure`` events — onset AND recovery, so
    the event log's pressure pairing always closes. ``start()`` runs
    ``poll`` on a daemon thread every ``interval_s``.
    """

    def __init__(
        self,
        checkpoint_dir: Optional[str] = None,
        eventlog_dir: Optional[str] = None,
        warn_fraction: float = 0.85,
        critical_fraction: float = 0.95,
        interval_s: float = 10.0,
        registry=None,
        hbm_sampler: Callable[[], List[Tuple[str, float, float]]] = sample_hbm,
        rss_sampler: Callable[[], Optional[Tuple[float, float]]] = sample_host_rss,
        disk_sampler: Callable[[str], Optional[Tuple[float, float]]] = sample_disk,
    ):
        from mmlspark_tpu.observability.registry import get_registry
        from mmlspark_tpu.runtime.journal import default_checkpoint_dir

        if checkpoint_dir is None:
            checkpoint_dir = default_checkpoint_dir()
        if eventlog_dir is None:
            log = os.environ.get("MMLSPARK_TPU_EVENT_LOG", "").strip()
            eventlog_dir = os.path.dirname(log) or "." if log else None
        self.checkpoint_dir = checkpoint_dir
        self.eventlog_dir = eventlog_dir
        self.warn_fraction = float(warn_fraction)
        self.critical_fraction = float(critical_fraction)
        self.interval_s = float(interval_s)
        self._hbm = hbm_sampler
        self._rss = rss_sampler
        self._disk = disk_sampler
        reg = registry if registry is not None else get_registry()
        self._g_mem_level = reg.gauge(
            "pressure_memory_level", "Process memory-pressure level (0/1/2)"
        )
        self._g_disk_level = reg.gauge(
            "pressure_disk_level", "Process disk-pressure level (0/1/2)"
        )
        self._g_hbm = reg.gauge(
            "pressure_hbm_fraction", "Worst-device HBM used fraction"
        )
        self._g_rss = reg.gauge(
            "pressure_host_rss_bytes", "Host RSS of this process"
        )
        self._g_free = reg.gauge(
            "pressure_disk_free_bytes", "Free bytes on a watched volume"
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- one sample round ----------------------------------------------------

    def _level_for(self, fraction: float) -> PressureLevel:
        if fraction >= self.critical_fraction:
            return PressureLevel.CRITICAL
        if fraction >= self.warn_fraction:
            return PressureLevel.WARN
        return PressureLevel.OK

    def poll(self) -> Dict[str, PressureLevel]:
        """One sample round; returns the levels it settled on."""
        from mmlspark_tpu.observability.events import (
            DiskPressure, MemoryPressure, get_bus,
        )

        bus = get_bus()
        # memory: worst of HBM devices and host RSS
        mem_level = PressureLevel.OK
        worst: Tuple[str, float, float] = ("", 0.0, 0.0)
        worst_frac = 0.0
        for device, used, limit in self._hbm():
            frac = used / limit
            if frac > worst_frac:
                worst_frac, worst = frac, (f"hbm:{device}", used, limit)
        if worst_frac:
            self._g_hbm.set(worst_frac)
        rss = self._rss()
        if rss is not None:
            rss_bytes, total = rss
            self._g_rss.set(rss_bytes)
            frac = rss_bytes / total
            if frac > worst_frac:
                worst_frac, worst = frac, ("host", rss_bytes, total)
        mem_level = self._level_for(worst_frac)
        prev = set_pressure_level("memory", mem_level)
        self._g_mem_level.set(int(mem_level))
        if mem_level != prev and bus.active:
            bus.publish(MemoryPressure(
                source=worst[0] or "host",
                level=(
                    "ok" if mem_level is PressureLevel.OK
                    else mem_level.name.lower()
                ),
                used_bytes=worst[1],
                limit_bytes=worst[2],
                detail=f"fraction={worst_frac:.3f}",
            ))
        if mem_level != prev:
            logger.warning(
                "memory pressure %s -> %s (%s at %.1f%%)",
                prev.name, mem_level.name, worst[0] or "host",
                worst_frac * 100.0,
            )
        # disk: worst of the watched volumes (used fraction)
        disk_level = PressureLevel.OK
        worst_disk: Tuple[str, float, float] = ("", 0.0, 0.0)
        worst_disk_frac = -1.0
        for path in {p for p in (self.checkpoint_dir, self.eventlog_dir) if p}:
            sampled = self._disk(path)
            if sampled is None:
                continue
            free, total = sampled
            self._g_free.labels(path=path).set(free)
            frac = 1.0 - free / total if total else 0.0
            if frac > worst_disk_frac:
                worst_disk_frac, worst_disk = frac, (path, free, total)
        if worst_disk_frac >= 0.0:
            disk_level = self._level_for(worst_disk_frac)
            prev_disk = set_pressure_level("disk", disk_level)
            self._g_disk_level.set(int(disk_level))
            if disk_level != prev_disk and bus.active:
                bus.publish(DiskPressure(
                    path=worst_disk[0],
                    level=(
                        "ok" if disk_level is PressureLevel.OK
                        else disk_level.name.lower()
                    ),
                    free_bytes=worst_disk[1],
                    total_bytes=worst_disk[2],
                ))
            if disk_level != prev_disk:
                logger.warning(
                    "disk pressure %s -> %s (%s, %.1f%% used)",
                    prev_disk.name, disk_level.name, worst_disk[0],
                    worst_disk_frac * 100.0,
                )
        return {"memory": mem_level, "disk": disk_level}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ResourceWatchdog":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll()
            except Exception as e:  # noqa: BLE001 - the watchdog must survive
                logger.debug("watchdog poll failed: %s", e)
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


# -- process-global watchdog --------------------------------------------------

_WATCHDOG: Optional[ResourceWatchdog] = None
_WATCHDOG_LOCK = threading.Lock()


def get_watchdog(**kwargs) -> ResourceWatchdog:
    """The process-global watchdog (created lazily, not auto-started;
    callers that want the background thread call ``.start()``)."""
    global _WATCHDOG
    with _WATCHDOG_LOCK:
        if _WATCHDOG is None:
            _WATCHDOG = ResourceWatchdog(**kwargs)
        return _WATCHDOG
