"""Durable fit journal, partition checkpoints, and atomic model commit.

Spark answers "the driver died mid-job" with lineage plus checkpointing:
``rdd.checkpoint()`` persists a computed partition so recovery replays
nothing before it, and committed output is made visible atomically
(rename into place) so readers never observe a torn write. This module
is that durability plane for the thread runtime:

- :class:`FitJournal` — one directory per (journal root, job key)
  holding an append-only JSON-lines ``journal.jsonl`` of task
  completions plus one checksummed checkpoint file per finished
  partition. ``Scheduler.run(..., journal=...)`` restores completed
  partitions at startup (zero re-execution) and records each new
  completion durably: checkpoint first (tmp + fsync + atomic rename),
  journal line second, so a crash between the two at worst re-runs one
  task, never resurrects a torn checkpoint;
- :class:`ModelStore` — atomic model commit: the fitted model text is
  written to a versioned file via tmp+rename with a CRC32 sidecar, then
  a ``CURRENT`` pointer is atomically swapped. :meth:`ModelStore.latest`
  is the recovery scan a warm-restarting server runs at startup — it
  trusts ``CURRENT`` when valid and otherwise falls back to the highest
  checksummed version on disk, so a crash mid-commit can never serve a
  half-written model;
- :func:`default_checkpoint_dir` — the ambient ``MMLSPARK_TPU_CHECKPOINT_DIR``
  root that activates all of this without API threading.

Checkpoint format: 4-byte big-endian CRC32 of the pickled payload,
then the pickle bytes. Loads verify the CRC and unpickle; a mismatch
(torn write, bit rot) drops the entry — the scheduler just recomputes
that partition, which is always safe.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import re
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.runtime.faults import check_write

logger = get_logger("mmlspark_tpu.runtime")

#: env var naming the durable root; unset disables checkpointing
CHECKPOINT_DIR_ENV = "MMLSPARK_TPU_CHECKPOINT_DIR"

_JOURNAL_NAME = "journal.jsonl"
_META_NAME = "meta.json"


def default_checkpoint_dir() -> Optional[str]:
    """The ambient durable root (``MMLSPARK_TPU_CHECKPOINT_DIR``), or None."""
    path = os.environ.get(CHECKPOINT_DIR_ENV, "").strip()
    return path or None


def result_crc(result: Any) -> int:
    """CRC32 of the pickled result — the end-to-end integrity token used
    by checkpoints AND the executor->driver corrupt-result check."""
    return zlib.crc32(pickle.dumps(result, protocol=4)) & 0xFFFFFFFF


def _safe_key(key: str) -> str:
    """A filesystem-safe directory name for a job key: readable prefix
    plus a hash so distinct keys never collide after sanitising."""
    slug = re.sub(r"[^A-Za-z0-9._-]+", "_", key)[:48].strip("_") or "job"
    digest = hashlib.sha256(key.encode("utf-8")).hexdigest()[:12]
    return f"{slug}-{digest}"


def _atomic_write(path: str, data: bytes) -> None:
    """tmp + fsync + rename: the file at ``path`` is either the old
    content or the complete new content, never a prefix. The guarded-write
    gate (``FaultPlan.disk_full``) fires before the temp file opens, so an
    injected ENOSPC leaves no trace on disk."""
    check_write(path)
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


class FitJournal:
    """Append-only journal + checksummed checkpoints for one partitioned job.

    ``key`` identifies the job (estimator params + data fingerprint): a
    re-run with the same key under the same root resumes; a different
    key lands in a different subdirectory and starts clean. When the
    on-disk task count disagrees with ``num_tasks`` the journal resets —
    stale state from a differently-partitioned run must not leak in.
    """

    def __init__(self, root: str, key: str, num_tasks: Optional[int] = None):
        self.key = key
        self.dir = os.path.join(root, _safe_key(key))
        os.makedirs(self.dir, exist_ok=True)
        self.num_tasks = num_tasks
        self._lock = threading.Lock()
        self._recorded: Dict[int, str] = {}
        #: journal lines appended by THIS process (re-executions measure)
        self.appended = 0
        self._load_meta()
        self._fh = open(os.path.join(self.dir, _JOURNAL_NAME), "a", encoding="utf-8")

    def _load_meta(self) -> None:
        meta_path = os.path.join(self.dir, _META_NAME)
        meta = None
        try:
            with open(meta_path, "r", encoding="utf-8") as fh:
                meta = json.load(fh)
        except (OSError, ValueError):
            meta = None
        stale = meta is not None and (
            meta.get("key") != self.key
            or (
                self.num_tasks is not None
                and meta.get("num_tasks") not in (None, self.num_tasks)
            )
        )
        if meta is None or stale:
            if stale:
                logger.warning(
                    "journal %s is stale (key/task-count mismatch); resetting",
                    self.dir,
                )
                for name in os.listdir(self.dir):
                    if name.endswith((".ckpt", ".tmp")) or name == _JOURNAL_NAME:
                        try:
                            os.remove(os.path.join(self.dir, name))
                        except OSError:
                            pass
            _atomic_write(
                meta_path,
                json.dumps({"key": self.key, "num_tasks": self.num_tasks}).encode(),
            )

    # -- recovery ------------------------------------------------------------

    def restore(self) -> Dict[int, Any]:
        """Completed task results from the journal, CRC-verified. Corrupt
        or missing checkpoints are skipped (their tasks just recompute);
        a malformed trailing journal line (crash mid-append) is ignored."""
        out: Dict[int, Any] = {}
        path = os.path.join(self.dir, _JOURNAL_NAME)
        try:
            with open(path, "r", encoding="utf-8") as fh:
                lines = fh.readlines()
        except OSError:
            return out
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                index, ckpt = int(rec["task"]), str(rec["ckpt"])
            except (ValueError, KeyError, TypeError):
                continue  # torn tail line
            result = self._load_checkpoint(os.path.join(self.dir, ckpt))
            if result is not _MISSING:
                out[index] = result
                with self._lock:
                    self._recorded[index] = ckpt
        return out

    @staticmethod
    def _load_checkpoint(path: str):
        try:
            with open(path, "rb") as fh:
                blob = fh.read()
        except OSError:
            return _MISSING
        if len(blob) < 4:
            return _MISSING
        (want,) = struct.unpack(">I", blob[:4])
        payload = blob[4:]
        if zlib.crc32(payload) & 0xFFFFFFFF != want:
            logger.warning("checkpoint %s failed CRC verification; dropping", path)
            return _MISSING
        try:
            return pickle.loads(payload)
        except Exception:  # noqa: BLE001 - a bad pickle is a bad checkpoint
            logger.warning("checkpoint %s failed to unpickle; dropping", path)
            return _MISSING

    # -- recording -----------------------------------------------------------

    def record(self, index: int, result: Any) -> bool:
        """Durably record task ``index`` as complete: checkpoint (atomic,
        checksummed) then journal line. Returns False when the task was
        already recorded (recovered or raced by a speculative sibling) —
        nothing is written, which is what "zero re-executions" means.
        An injected/real ENOSPC fires before the index is reserved, so a
        failed record leaves the journal state clean and the ``OSError``
        propagates to the caller (the epoch/task owner decides)."""
        index = int(index)
        check_write(os.path.join(self.dir, f"task-{index:05d}.ckpt"))
        with self._lock:
            if index in self._recorded:
                return False
            # reserve under the lock so concurrent completions of the same
            # task write one checkpoint; the file I/O happens outside
            self._recorded[index] = f"task-{index:05d}.ckpt"
            ckpt = self._recorded[index]
        payload = pickle.dumps(result, protocol=4)
        blob = struct.pack(">I", zlib.crc32(payload) & 0xFFFFFFFF) + payload
        _atomic_write(os.path.join(self.dir, ckpt), blob)
        line = json.dumps({"task": index, "ckpt": ckpt, "bytes": len(payload)})
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self.appended += 1
        return True

    def completed(self) -> List[int]:
        with self._lock:
            return sorted(self._recorded)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None  # type: ignore[assignment]

    def __enter__(self) -> "FitJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _Missing:
    __slots__ = ()


_MISSING = _Missing()


class ModelStore:
    """Atomic, versioned model commits under a durable directory.

    ``commit`` writes ``<name>-<version>.txt`` (tmp + fsync + rename)
    with a CRC32 sidecar, then atomically swaps ``<name>.CURRENT`` to
    point at it. ``latest`` is the startup recovery scan: trust CURRENT
    when its target verifies, otherwise fall back to the newest version
    whose checksum holds — a crash at ANY point mid-commit leaves the
    previous committed model fully readable.
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()

    def _current_path(self, name: str) -> str:
        return os.path.join(self.root, f"{name}.CURRENT")

    def commit(self, text: str, name: str = "model") -> int:
        """Commit ``text`` as the next version of ``name``; returns the
        committed version number."""
        data = text.encode("utf-8")
        crc = zlib.crc32(data) & 0xFFFFFFFF
        with self._lock:
            versions = self._scan_versions(name)
            version = versions[-1][0] + 1 if versions else 1
            fname = f"{name}-{version:06d}.txt"
            _atomic_write(os.path.join(self.root, fname), data)
            _atomic_write(
                os.path.join(self.root, fname + ".crc32"),
                f"{crc:08x}".encode(),
            )
            _atomic_write(
                self._current_path(name),
                json.dumps({"file": fname, "crc32": f"{crc:08x}"}).encode(),
            )
        return version

    def _scan_versions(self, name: str) -> List[Tuple[int, str]]:
        pat = re.compile(re.escape(name) + r"-(\d{6})\.txt$")
        found = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        for fname in names:
            m = pat.match(fname)
            if m:
                found.append((int(m.group(1)), fname))
        return sorted(found)

    def _read_verified(self, fname: str, want_crc: Optional[str] = None) -> Optional[str]:
        path = os.path.join(self.root, fname)
        try:
            with open(path, "rb") as fh:
                data = fh.read()
        except OSError:
            return None
        crc = f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"
        if want_crc is None:
            try:
                with open(path + ".crc32", "r", encoding="utf-8") as fh:
                    want_crc = fh.read().strip()
            except OSError:
                return None
        if crc != want_crc:
            logger.warning("model file %s failed CRC verification", fname)
            return None
        return data.decode("utf-8")

    def current_version(self, name: str = "model") -> Optional[int]:
        """The version the ``CURRENT`` pointer names, or None — one small
        read, no model-text load or CRC verification, so a hot-swap
        watcher can poll it cheaply between requests (verification
        happens in :meth:`latest` when the watcher decides to load)."""
        try:
            with open(self._current_path(name), "r", encoding="utf-8") as fh:
                cur = json.load(fh)
            m = re.search(r"-(\d{6})\.txt$", str(cur["file"]))
            return int(m.group(1)) if m else None
        except (OSError, ValueError, KeyError):
            return None

    def _artifact_name(self, name: str, version: int, kind: str) -> str:
        if not re.fullmatch(r"[A-Za-z0-9_-]+", kind):
            raise ValueError(f"artifact kind must be a bare slug, got {kind!r}")
        return f"{name}-{version:06d}.{kind}.json"

    def commit_artifact(
        self, name: str, version: int, kind: str, payload: Dict[str, Any]
    ) -> str:
        """Commit a JSON artifact riding next to ``<name>-<version>`` —
        e.g. the quality plane's reference profile (``kind="quality"``).
        Written with the same tmp+fsync+rename discipline and CRC32
        sidecar as the model text itself; returns the artifact filename.
        Artifacts never touch the ``CURRENT`` pointer: a model version is
        live regardless of which sidecars it carries."""
        data = json.dumps(payload, sort_keys=True).encode("utf-8")
        crc = zlib.crc32(data) & 0xFFFFFFFF
        fname = self._artifact_name(name, version, kind)
        with self._lock:
            _atomic_write(os.path.join(self.root, fname), data)
            _atomic_write(
                os.path.join(self.root, fname + ".crc32"),
                f"{crc:08x}".encode(),
            )
        return fname

    def read_artifact(
        self, name: str, version: int, kind: str
    ) -> Optional[Dict[str, Any]]:
        """The verified JSON artifact for ``<name>-<version>``, or None
        when it is absent or fails its sidecar checksum (a torn artifact
        reads as missing, never as garbage)."""
        fname = self._artifact_name(name, version, kind)
        text = self._read_verified(fname)
        if text is None:
            return None
        try:
            payload = json.loads(text)
        except ValueError:
            logger.warning("artifact %s is not valid JSON", fname)
            return None
        return payload if isinstance(payload, dict) else None

    def latest(self, name: str = "model") -> Optional[Tuple[int, str]]:
        """(version, text) of the last committed model, or None. CURRENT
        is trusted when its target verifies; otherwise scan versions
        newest-first for one whose sidecar checksum holds."""
        try:
            with open(self._current_path(name), "r", encoding="utf-8") as fh:
                cur = json.load(fh)
            fname = str(cur["file"])
            text = self._read_verified(fname, str(cur.get("crc32")) or None)
            if text is not None:
                m = re.search(r"-(\d{6})\.txt$", fname)
                return (int(m.group(1)) if m else 0), text
        except (OSError, ValueError, KeyError):
            pass
        for version, fname in reversed(self._scan_versions(name)):
            text = self._read_verified(fname)
            if text is not None:
                return version, text
        return None
