"""Corrupt-record read modes — Spark's ``mode`` option for the TPU stack.

The reference's patched readers sit on Spark's corrupt-record machinery
(PAPER.md layer 1): ``spark.read.option("mode", ...)`` with three
contracts, reproduced here for :class:`~mmlspark_tpu.data.sharded.ShardedDataset`
and :class:`~mmlspark_tpu.streaming.source.FileStreamSource`:

- ``PERMISSIVE`` — a torn/corrupt record is quarantined (captured with
  its source, index and reason, and dead-lettered when a store is
  configured — the ``badRecordsPath`` analogue) and the read continues
  over the survivors;
- ``DROPMALFORMED`` — corrupt records are dropped and counted, but not
  captured;
- ``FAILFAST`` — the first corrupt record raises (the pre-dataguard
  behavior, and the default: silently tolerating corruption must be
  opted into).

Surviving-row order is deterministic — sources are consumed in listing
order and a quarantined unit contributes zero rows — so a fit over a
corrupted input is byte-identical to a fit over the clean complement
(CI-enforced by ``tools/data_chaos_smoke.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Sequence

#: the three Spark read modes, normalized lowercase
PERMISSIVE = "permissive"
DROPMALFORMED = "dropmalformed"
FAILFAST = "failfast"

_MODES = (PERMISSIVE, DROPMALFORMED, FAILFAST)


def normalize_mode(mode: str) -> str:
    """Case-insensitive mode normalization (``"PERMISSIVE"`` and
    ``"permissive"`` are the same option, as in Spark)."""
    low = str(mode).strip().lower()
    if low not in _MODES:
        raise ValueError(
            f"unknown read mode {mode!r} (expected one of "
            f"{', '.join(m.upper() for m in _MODES)})"
        )
    return low


class BadRecordsError(ValueError):
    """A ``FAILFAST`` read hit a corrupt record, or a ``fail``-policy fit
    guard hit invalid values. Carries the structured quarantine records
    so callers can report *which* units were bad."""

    def __init__(self, message: str, records: Sequence["CorruptRecord"] = ()):
        super().__init__(message)
        self.records = list(records)


@dataclasses.dataclass
class CorruptRecord:
    """One quarantined unit: a whole shard/file (``index`` -1) or one
    record within it (``index`` >= 0). JSON-serializable via
    :meth:`to_record` for the dead-letter store."""

    source: str
    index: int
    reason: str
    detail: str = ""

    def to_record(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_error(
        cls, source: str, err: BaseException, index: int = -1
    ) -> "CorruptRecord":
        return cls(
            source=str(source), index=int(index),
            reason=type(err).__name__, detail=str(err)[:200],
        )


def summarize_reasons(records: Sequence[CorruptRecord]) -> str:
    """Compact ``reason=count`` summary for events/logs, reason-sorted so
    the string is deterministic."""
    counts: Dict[str, int] = {}
    for rec in records:
        counts[rec.reason] = counts.get(rec.reason, 0) + 1
    return ",".join(f"{k}={v}" for k, v in sorted(counts.items()))


def as_corrupt_records(items: Sequence[Any]) -> List[CorruptRecord]:
    """Coerce a mixed list (CorruptRecord or plain dicts) into records."""
    out: List[CorruptRecord] = []
    for item in items:
        if isinstance(item, CorruptRecord):
            out.append(item)
        else:
            out.append(CorruptRecord(
                source=str(item.get("source", "?")),
                index=int(item.get("index", -1)),
                reason=str(item.get("reason", "unknown")),
                detail=str(item.get("detail", "")),
            ))
    return out
