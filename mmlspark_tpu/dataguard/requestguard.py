"""Serving-edge request validation and the poison-client breaker.

Two pieces, both consulted by the HTTP handler *before* a request is
enqueued for the batch loop:

- :class:`RequestValidator` — structural validation of the decoded
  payload against what the model's ``transform_schema`` admits: the
  input column must be present, element types must be numeric-or-text,
  numeric values must be finite, and (when the model's feature width is
  known) vectors must match it. A failing payload becomes a structured
  400 at the edge instead of an exception inside the batch loop, where
  it would poison every co-batched request.

- :class:`MalformedRateBreaker` — a per-client rolling-window counter.
  A client whose malformed-request rate crosses the threshold is shed
  with 429s for ``reset_s`` (the body is still drained so keep-alive
  survives); healthy clients on the same replica are unaffected, and —
  unlike the replica :class:`~mmlspark_tpu.resilience.breaker.CircuitBreaker`,
  which counts 408/5xx — 400s never trip fleet routing away from a
  healthy replica that happens to face a poison flood.

Both take injectable clocks; events are published outside locks (the
graftlint lock-discipline rule covers ``dataguard/``).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Optional, Tuple

from mmlspark_tpu.core.profiling import get_logger

logger = get_logger("mmlspark_tpu.dataguard")

#: (kind, detail) tuple describing why a payload was rejected
Rejection = Tuple[str, str]


def _check_numbers(value: Any, path: str) -> Optional[Rejection]:
    """Recursively reject None / non-finite numbers inside a payload
    element. Strings and bools pass through (text models take strings)."""
    if value is None:
        return ("null-value", f"{path} is null")
    if isinstance(value, bool):
        return None
    if isinstance(value, (int, float)):
        if not math.isfinite(value):
            return ("non-finite-value", f"{path} is {value!r}")
        return None
    if isinstance(value, (list, tuple)):
        for i, item in enumerate(value):
            bad = _check_numbers(item, f"{path}[{i}]")
            if bad is not None:
                return bad
        return None
    if isinstance(value, dict):
        return ("invalid-type", f"{path} is an object, expected scalar/array")
    return None  # strings and anything exotic: the model's problem


class RequestValidator:
    """Structural pre-admission validation for one serving endpoint.

    ``width`` pins the expected feature-vector length when known (see
    :meth:`for_model`); ``None`` skips the shape check. ``enabled=False``
    turns the validator into a pass-through (the pre-dataguard edge).
    """

    def __init__(
        self,
        input_col: str = "input",
        width: Optional[int] = None,
        enabled: bool = True,
    ):
        self.input_col = input_col
        self.width = int(width) if width else None
        self.enabled = enabled

    @classmethod
    def for_model(cls, model: Any, input_col: str = "input") -> "RequestValidator":
        """Best-effort width inference from the model: booster feature
        count or an explicit ``num_features``. Unknown models validate
        structure only — inference must never block serving startup."""
        width: Optional[int] = None
        for probe in (
            lambda m: m.num_features,
            lambda m: m.booster.num_features,
            lambda m: m.getNumFeatures(),
        ):
            try:
                got = probe(model)
                if got:
                    width = int(got)
                    break
            except Exception:  # noqa: BLE001 - probing, any failure means "unknown"
                continue
        return cls(input_col=input_col, width=width)

    def check_payload(self, payload: Any) -> Optional[Rejection]:
        """Validate a decoded JSON payload (the whole request body).
        Returns None when admissible, else a (kind, detail) rejection."""
        if not self.enabled:
            return None
        if payload is None:
            return ("empty-payload", "request body is empty")
        if isinstance(payload, dict) and self.input_col not in payload:
            return (
                "missing-input-col",
                f"payload object lacks required key {self.input_col!r}",
            )
        value = payload[self.input_col] if isinstance(payload, dict) else payload
        return self.check_value(value)

    def check_value(self, value: Any) -> Optional[Rejection]:
        """Validate the unwrapped input value itself."""
        if not self.enabled:
            return None
        bad = _check_numbers(value, self.input_col)
        if bad is not None:
            return bad
        if self.width is not None and isinstance(value, (list, tuple)):
            rows = value if value and isinstance(value[0], (list, tuple)) else [value]
            for i, row in enumerate(rows):
                if isinstance(row, (list, tuple)) and len(row) != self.width:
                    return (
                        "shape-mismatch",
                        f"{self.input_col}[{i}] has {len(row)} feature(s), "
                        f"model expects {self.width}",
                    )
        return None


class MalformedRateBreaker:
    """Per-client malformed-request breaker with a rolling window.

    ``record_malformed(client)`` books one malformed request; once a
    client accumulates ``threshold`` of them within ``window_s`` it is
    blocked for ``reset_s`` (checked by ``blocked(client)``), then
    released on its next probe. Trips publish
    :class:`~mmlspark_tpu.observability.events.PoisonClientBlocked`,
    releases :class:`~mmlspark_tpu.observability.events.PoisonClientReleased`
    — both outside the lock.
    """

    def __init__(
        self,
        threshold: int = 16,
        window_s: float = 5.0,
        reset_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        registry=None,
    ):
        self.threshold = max(1, int(threshold))
        self.window_s = float(window_s)
        self.reset_s = float(reset_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._events: Dict[str, Deque[float]] = {}
        self._blocked_at: Dict[str, float] = {}
        if registry is None:
            from mmlspark_tpu.observability.registry import get_registry

            registry = get_registry()
        self._reg_malformed = registry.counter(
            "dataguard_malformed_requests_total",
            "Malformed serving requests rejected before admission",
        )
        self._reg_shed = registry.counter(
            "dataguard_poison_shed_total",
            "Requests shed because the client tripped the malformed-rate breaker",
        )

    def record_malformed(self, client: str, kind: str = "unknown") -> bool:
        """Book one malformed request for ``client``; returns True when
        this request tripped the breaker (client newly blocked)."""
        self._reg_malformed.labels(kind=kind).inc()
        now = self.clock()
        tripped = False
        with self._lock:
            dq = self._events.setdefault(client, deque())
            dq.append(now)
            while dq and dq[0] < now - self.window_s:
                dq.popleft()
            if len(dq) >= self.threshold and client not in self._blocked_at:
                self._blocked_at[client] = now
                dq.clear()
                tripped = True
        if tripped:
            self._publish_tripped(client)
        return tripped

    def blocked(self, client: str) -> bool:
        """True while ``client`` is being shed; releases (and publishes)
        once ``reset_s`` has elapsed since the trip."""
        now = self.clock()
        released_after: Optional[float] = None
        with self._lock:
            at = self._blocked_at.get(client)
            if at is None:
                return False
            if now - at < self.reset_s:
                blocked = True
            else:
                del self._blocked_at[client]
                released_after = now - at
                blocked = False
        if released_after is not None:
            self._publish_released(client, released_after)
        return blocked

    def note_shed(self, client: str) -> None:
        """Book one request shed while blocked (metrics only)."""
        self._reg_shed.labels(client=client).inc()

    # -- events (always outside the lock) ------------------------------------

    def _publish_tripped(self, client: str) -> None:
        from mmlspark_tpu.observability.events import PoisonClientBlocked, get_bus

        bus = get_bus()
        if bus.active:
            bus.publish(PoisonClientBlocked(
                client=client, malformed=self.threshold,
                window_s=self.window_s,
            ))
        logger.warning(
            "poison breaker: client %s blocked (%d malformed in %.1fs)",
            client, self.threshold, self.window_s,
        )

    def _publish_released(self, client: str, blocked_s: float) -> None:
        from mmlspark_tpu.observability.events import PoisonClientReleased, get_bus

        bus = get_bus()
        if bus.active:
            bus.publish(PoisonClientReleased(client=client, blocked_s=blocked_s))
        logger.info(
            "poison breaker: client %s released after %.2fs", client, blocked_s
        )
