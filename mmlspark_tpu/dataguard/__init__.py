"""dataguard — the poison-tolerant data plane.

The last failure domain the resilience stack covers: *the data itself*.
Four pieces, spanning ingest → fit → streaming → serving:

- :mod:`mmlspark_tpu.dataguard.modes` — Spark's corrupt-record read
  modes (``PERMISSIVE``/``DROPMALFORMED``/``FAILFAST``) consumed by
  :class:`~mmlspark_tpu.data.sharded.ShardedDataset` and
  :class:`~mmlspark_tpu.streaming.source.FileStreamSource`;
- :mod:`mmlspark_tpu.dataguard.dlq` — the epoch-keyed, CRC-sidecar'd
  dead-letter store (``badRecordsPath`` with a replay API and
  exactly-once semantics under the streaming WAL);
- :mod:`mmlspark_tpu.dataguard.guards` — NaN/Inf/label-domain fit
  guards with fail/drop/impute policies (``Pipeline.setInvalidDataPolicy``);
- :mod:`mmlspark_tpu.dataguard.requestguard` — serving-edge request
  validation and the per-client malformed-rate breaker.

Chaos coverage: ``FaultPlan.corrupt_record`` / ``truncate_shard`` /
``malformed_request`` (:mod:`mmlspark_tpu.runtime.faults`), the CI
corruption storm in ``tools/data_chaos_smoke.py``, and the
``--malformed`` loadgen phase. Cookbook: docs/resilience.md "Bad data".
"""

from mmlspark_tpu.dataguard.dlq import DeadLetterStore
from mmlspark_tpu.dataguard.guards import (
    GuardReport,
    guard_arrays,
    guard_table,
    normalize_policy,
)
from mmlspark_tpu.dataguard.modes import (
    DROPMALFORMED,
    FAILFAST,
    PERMISSIVE,
    BadRecordsError,
    CorruptRecord,
    normalize_mode,
    summarize_reasons,
)
from mmlspark_tpu.dataguard.requestguard import (
    MalformedRateBreaker,
    RequestValidator,
)

__all__ = [
    "PERMISSIVE",
    "DROPMALFORMED",
    "FAILFAST",
    "normalize_mode",
    "BadRecordsError",
    "CorruptRecord",
    "summarize_reasons",
    "DeadLetterStore",
    "GuardReport",
    "guard_arrays",
    "guard_table",
    "normalize_policy",
    "RequestValidator",
    "MalformedRateBreaker",
]
