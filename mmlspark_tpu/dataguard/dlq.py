"""Dead-letter store — the durable ``badRecordsPath`` analogue.

Spark writes corrupt records as JSON under ``badRecordsPath`` with no
integrity or replay story; this store is the checkpoint-grade version:

    <root>/records/NNNNNN.jsonl         one JSON object per quarantined
                                        record (source, index, reason,
                                        detail), written tmp+rename
    <root>/records/NNNNNN.jsonl.crc32   CRC32 sidecar over the bytes
    <root>/manifest/NNNNNN.json         the epoch's commit point:
                                        {"epoch", "count", "crc32",
                                         "reasons"}

The manifest file is written LAST (atomically), so its existence is the
only commit signal — a SIGKILL between the records file and the manifest
leaves an uncommitted epoch that the replayed epoch simply rewrites.
:meth:`DeadLetterStore.commit_epoch` is epoch-keyed idempotent: a
replayed streaming epoch (WAL'd but SIGKILL'd before its commit log)
re-quarantines the identical records, finds the manifest already
present, and letters nothing twice — exactly-once under the streaming
WAL, the same contract the sinks keep.

Committing publishes :class:`~mmlspark_tpu.observability.events.RecordsDeadLettered`
and feeds the ``dataguard_*`` metrics; :meth:`DeadLetterStore.replay`
CRC-verifies every records file before handing the rows back.
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Any, Dict, List, Optional, Sequence

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.dataguard.modes import (
    CorruptRecord,
    as_corrupt_records,
    summarize_reasons,
)
from mmlspark_tpu.runtime.journal import _atomic_write

logger = get_logger("mmlspark_tpu.dataguard")


class DeadLetterStore:
    """Epoch-keyed, CRC-sidecar'd quarantine under a durable root.

    ``name`` labels the owning dataset/query in events and metrics.
    Batch readers with no natural epoch use :meth:`letter`, which
    allocates the next free epoch index; streaming queries use
    :meth:`commit_epoch` keyed by their WAL epoch so replays dedup.
    """

    def __init__(self, root: str, name: str = "dataguard", registry=None):
        self.root = root
        self.name = name
        self._records_dir = os.path.join(root, "records")
        self._manifest_dir = os.path.join(root, "manifest")
        os.makedirs(self._records_dir, exist_ok=True)
        os.makedirs(self._manifest_dir, exist_ok=True)
        self._lock = threading.Lock()
        if registry is None:
            from mmlspark_tpu.observability.registry import get_registry

            registry = get_registry()
        labels = {"source": name}
        self._reg_quarantined = registry.counter(
            "dataguard_quarantined_total",
            "Records quarantined to the dead-letter store",
        ).labels(**labels)
        self._reg_epochs = registry.counter(
            "dataguard_dlq_epochs_total",
            "Dead-letter epochs committed (manifest written)",
        ).labels(**labels)
        self._reg_replayed = registry.counter(
            "dataguard_replayed_total",
            "Dead-lettered records handed back by replay()",
        ).labels(**labels)

    # -- paths ---------------------------------------------------------------

    def _records_path(self, epoch: int) -> str:
        return os.path.join(self._records_dir, f"{epoch:06d}.jsonl")

    def _manifest_path(self, epoch: int) -> str:
        return os.path.join(self._manifest_dir, f"{epoch:06d}.json")

    # -- write side ----------------------------------------------------------

    def has_epoch(self, epoch: int) -> bool:
        """True when ``epoch`` is committed (its manifest exists)."""
        return os.path.exists(self._manifest_path(int(epoch)))

    def epochs(self) -> List[int]:
        """Committed epoch ids, ascending."""
        try:
            names = os.listdir(self._manifest_dir)
        except OSError:
            return []
        return sorted(
            int(n[:-5]) for n in names
            if n.endswith(".json") and n[:-5].isdigit()
        )

    def commit_epoch(self, epoch: int, records: Sequence[Any]) -> bool:
        """Durably letter ``records`` under ``epoch``. Returns True when
        this call committed the epoch, False when the epoch was already
        committed (a replayed epoch — nothing is written twice). Events
        and metrics are booked only on a fresh commit."""
        epoch = int(epoch)
        recs = as_corrupt_records(records)
        if not recs:
            return False
        with self._lock:
            if self.has_epoch(epoch):
                logger.info(
                    "dead-letter store %r: epoch %d already committed "
                    "(replay) — skipping %d record(s)",
                    self.name, epoch, len(recs),
                )
                return False
            data = "".join(
                json.dumps(r.to_record(), sort_keys=True) + "\n" for r in recs
            ).encode("utf-8")
            crc = zlib.crc32(data) & 0xFFFFFFFF
            _atomic_write(self._records_path(epoch), data)
            _atomic_write(
                self._records_path(epoch) + ".crc32", f"{crc:08x}".encode()
            )
            reasons = summarize_reasons(recs)
            _atomic_write(
                self._manifest_path(epoch),
                json.dumps({
                    "epoch": epoch, "count": len(recs), "crc32": f"{crc:08x}",
                    "reasons": reasons,
                }, sort_keys=True).encode("utf-8"),
            )
        self._reg_quarantined.inc(len(recs))
        self._reg_epochs.inc()
        from mmlspark_tpu.observability.events import (
            RecordsDeadLettered, get_bus,
        )

        bus = get_bus()
        if bus.active:
            bus.publish(RecordsDeadLettered(
                source=self.name, epoch=epoch, count=len(recs),
                reasons=reasons,
            ))
        logger.warning(
            "dead-letter store %r: epoch %d quarantined %d record(s) (%s)",
            self.name, epoch, len(recs), reasons,
        )
        return True

    def letter(self, records: Sequence[Any]) -> Optional[int]:
        """Letter ``records`` under the next free epoch index (batch
        readers with no WAL epoch). Returns the epoch used, or None when
        there was nothing to letter."""
        recs = as_corrupt_records(records)
        if not recs:
            return None
        with self._lock:
            existing = self.epochs()
            epoch = (existing[-1] + 1) if existing else 0
        self.commit_epoch(epoch, recs)
        return epoch

    # -- read side -----------------------------------------------------------

    def manifest(self) -> Dict[int, Dict[str, Any]]:
        """Per-epoch manifest fold: epoch -> {count, crc32, reasons}."""
        out: Dict[int, Dict[str, Any]] = {}
        for epoch in self.epochs():
            try:
                with open(self._manifest_path(epoch), "r", encoding="utf-8") as fh:
                    out[epoch] = json.load(fh)
            except (OSError, ValueError) as e:
                logger.warning(
                    "dead-letter store %r: unreadable manifest for epoch "
                    "%d: %s", self.name, epoch, e,
                )
        return out

    def replay(self, epoch: Optional[int] = None) -> List[CorruptRecord]:
        """Hand back the quarantined records (one epoch, or all epochs in
        order), CRC-verifying every records file first — a torn or
        bit-rotted quarantine raises instead of replaying garbage."""
        epochs = [int(epoch)] if epoch is not None else self.epochs()
        out: List[CorruptRecord] = []
        for ep in epochs:
            path = self._records_path(ep)
            with open(path, "rb") as fh:
                data = fh.read()
            got = f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"
            try:
                with open(path + ".crc32", "r", encoding="utf-8") as fh:
                    want = fh.read().strip()
            except OSError:
                want = got  # no sidecar: trust the manifest crc below
            manifest = self.manifest().get(ep, {})
            want = manifest.get("crc32", want)
            if got != want:
                raise ValueError(
                    f"dead-letter records for epoch {ep} failed CRC "
                    f"verification (want {want}, got {got})"
                )
            for line in data.decode("utf-8").splitlines():
                if not line.strip():
                    continue
                rec = json.loads(line)
                out.append(CorruptRecord(
                    source=rec["source"], index=int(rec["index"]),
                    reason=rec["reason"], detail=rec.get("detail", ""),
                ))
        self._reg_replayed.inc(len(out))
        return out

    def count(self) -> int:
        """Total records committed across all epochs (from manifests)."""
        return sum(int(m.get("count", 0)) for m in self.manifest().values())
