"""Fit-time data validation — NaN/Inf and label-domain guards.

A single NaN label silently poisons a whole GBDT fit (every gradient it
touches goes NaN); an Inf feature does the same to quantile binning.
Before dataguard these reached the training loop unchecked. The guard
runs at ``Pipeline.fit`` (and is callable directly on any Table or array
set) under one of three policies, mirroring the read modes one level up:

- ``fail``   — raise :class:`~mmlspark_tpu.dataguard.modes.BadRecordsError`
  naming the offending columns/counts (the default posture for training
  jobs where bad data means a broken producer);
- ``drop``   — rows with any non-finite feature or out-of-domain label
  are removed, in order, so the surviving fit equals a fit over the
  clean complement;
- ``impute`` — non-finite *feature* values are replaced by the column
  mean over its finite entries (0.0 for an all-bad column); rows with a
  bad *label* are still dropped — a label cannot be conjured.

Label-domain: labels must be finite always; ``label_domain="classifier"``
additionally requires non-negative integers (the LightGBM classifier
contract — a 0.5 label would silently train a broken multiclass model).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.dataguard.modes import BadRecordsError, CorruptRecord

logger = get_logger("mmlspark_tpu.dataguard")

POLICIES = ("fail", "drop", "impute")


def normalize_policy(policy: str) -> str:
    low = str(policy).strip().lower()
    if low not in POLICIES:
        raise ValueError(
            f"unknown invalid-data policy {policy!r} "
            f"(expected one of {', '.join(POLICIES)})"
        )
    return low


@dataclasses.dataclass
class GuardReport:
    """What the guard did: rows seen/dropped, values imputed, and the
    per-column non-finite counts that drove it."""

    rows_in: int = 0
    rows_dropped: int = 0
    values_imputed: int = 0
    bad_label_rows: int = 0
    bad_columns: Dict[str, int] = dataclasses.field(default_factory=dict)

    @property
    def clean(self) -> bool:
        return not self.bad_columns

    def summary(self) -> str:
        cols = ",".join(f"{k}={v}" for k, v in sorted(self.bad_columns.items()))
        return (
            f"rows={self.rows_in} dropped={self.rows_dropped} "
            f"imputed={self.values_imputed} bad_labels={self.bad_label_rows}"
            + (f" [{cols}]" if cols else "")
        )


def _book_metrics(report: GuardReport) -> None:
    from mmlspark_tpu.observability.registry import get_registry

    reg = get_registry()
    if report.rows_dropped:
        reg.counter(
            "dataguard_fit_rows_dropped_total",
            "Rows dropped by the fit guard (non-finite or out-of-domain)",
        ).inc(report.rows_dropped)
    if report.values_imputed:
        reg.counter(
            "dataguard_fit_values_imputed_total",
            "Non-finite feature values imputed by the fit guard",
        ).inc(report.values_imputed)


def _bad_label_mask(y: np.ndarray, label_domain: Optional[str]) -> np.ndarray:
    bad = ~np.isfinite(y)
    if label_domain == "classifier":
        finite = ~bad
        vals = y[finite]
        domain_bad = np.zeros_like(bad)
        domain_bad[finite] = (vals < 0) | (vals != np.floor(vals))
        bad = bad | domain_bad
    return bad


def guard_arrays(
    X: np.ndarray,
    y: Optional[np.ndarray] = None,
    w: Optional[np.ndarray] = None,
    policy: str = "fail",
    label_domain: Optional[str] = None,
    name: str = "fit",
) -> Tuple[np.ndarray, Optional[np.ndarray], Optional[np.ndarray], GuardReport]:
    """Apply the fit guard to a feature matrix / label / weight triple.
    Returns the (possibly filtered/imputed) arrays plus a report; under
    ``policy="fail"`` any invalid value raises :class:`BadRecordsError`."""
    policy = normalize_policy(policy)
    X = np.asarray(X)
    report = GuardReport(rows_in=len(X))
    bad_feat = ~np.isfinite(X) if np.issubdtype(X.dtype, np.floating) else \
        np.zeros(X.shape, dtype=bool)
    feat_rows = bad_feat.any(axis=tuple(range(1, X.ndim))) if X.ndim > 1 \
        else bad_feat
    for j in range(X.shape[1] if X.ndim > 1 else 0):
        n_bad = int(bad_feat[:, j].sum())
        if n_bad:
            report.bad_columns[f"f{j}"] = n_bad
    bad_label = np.zeros(len(X), dtype=bool)
    if y is not None:
        y = np.asarray(y, dtype=np.float64)
        bad_label = _bad_label_mask(y, label_domain)
        report.bad_label_rows = int(bad_label.sum())
        if report.bad_label_rows:
            report.bad_columns["label"] = report.bad_label_rows
    if w is not None:
        w = np.asarray(w, dtype=np.float64)
        bad_w = ~np.isfinite(w)
        if bad_w.any():
            report.bad_columns["weight"] = int(bad_w.sum())
            bad_label = bad_label | bad_w  # a bad weight drops the row too
    if report.clean:
        return X, y, w, report
    if policy == "fail":
        raise BadRecordsError(
            f"invalid values in fit input ({report.summary()}); set the "
            "invalid-data policy to 'drop' or 'impute' to tolerate them",
            records=[
                CorruptRecord(source=name, index=-1, reason="invalid-value",
                              detail=f"{col}: {n} non-finite/out-of-domain")
                for col, n in sorted(report.bad_columns.items())
            ],
        )
    if policy == "impute":
        X = np.array(X, dtype=np.float64, copy=True)
        for j in range(X.shape[1] if X.ndim > 1 else 0):
            col_bad = bad_feat[:, j]
            if not col_bad.any():
                continue
            finite = X[~col_bad, j]
            fill = float(finite.mean()) if len(finite) else 0.0
            X[col_bad, j] = fill
            report.values_imputed += int(col_bad.sum())
        keep = ~bad_label
    else:  # drop
        keep = ~(feat_rows | bad_label)
    report.rows_dropped = int((~keep).sum())
    if report.rows_dropped:
        X = X[keep]
        y = y[keep] if y is not None else None
        w = w[keep] if w is not None else None
    _book_metrics(report)
    logger.warning("fit guard (%s, policy=%s): %s", name, policy,
                   report.summary())
    return X, y, w, report


def guard_table(
    table,
    policy: str = "fail",
    label_col: Optional[str] = None,
    label_domain: Optional[str] = None,
    name: str = "fit",
):
    """Apply the fit guard to a Table: float columns are scanned for
    non-finite values (and ``label_col`` for domain violations); returns
    (guarded table, report). Non-float columns pass through untouched."""
    policy = normalize_policy(policy)
    report = GuardReport(rows_in=table.num_rows)
    n = table.num_rows
    bad_rows = np.zeros(n, dtype=bool)
    imputed: Dict[str, np.ndarray] = {}
    for col in table.columns:
        arr = table.column(col)
        if not isinstance(arr, np.ndarray) or \
                not np.issubdtype(arr.dtype, np.floating):
            continue
        if col == label_col:
            bad = _bad_label_mask(
                arr if arr.ndim == 1 else arr.reshape(n, -1)[:, 0],
                label_domain,
            )
            if bad.any():
                report.bad_columns[col] = int(bad.sum())
                report.bad_label_rows = int(bad.sum())
                bad_rows |= bad  # labels are never imputable
            continue
        bad = ~np.isfinite(arr)
        if not bad.any():
            continue
        report.bad_columns[col] = int(bad.sum())
        if policy == "impute":
            fixed = np.array(arr, dtype=np.float64, copy=True)
            finite = fixed[~bad] if arr.ndim == 1 else fixed[~bad]
            fill = float(finite.mean()) if finite.size else 0.0
            fixed[bad] = fill
            imputed[col] = fixed
            report.values_imputed += int(bad.sum())
        else:
            bad_rows |= bad.any(axis=tuple(range(1, arr.ndim))) \
                if arr.ndim > 1 else bad
    if report.clean:
        return table, report
    if policy == "fail":
        raise BadRecordsError(
            f"invalid values in fit input ({report.summary()}); set "
            "invalidDataPolicy='drop' or 'impute' to tolerate them",
            records=[
                CorruptRecord(source=name, index=-1, reason="invalid-value",
                              detail=f"{col}: {cnt} bad value(s)")
                for col, cnt in sorted(report.bad_columns.items())
            ],
        )
    out = table.with_columns(imputed) if imputed else table
    if bad_rows.any():
        report.rows_dropped = int(bad_rows.sum())
        out = out.filter(~bad_rows)
    _book_metrics(report)
    logger.warning("fit guard (%s, policy=%s): %s", name, policy,
                   report.summary())
    return out, report
