"""Ring attention — sequence-parallel attention over the mesh ``seq`` axis.

Long-context support is first-class in this framework: sequences longer
than one chip's HBM shard over the ``seq`` mesh axis, and attention runs
as a RING — each device holds its local Q/K/V block, K/V blocks rotate
around the ring via ``lax.ppermute`` (neighbor exchanges ride the ICI
torus), and every device accumulates its queries' attention over all
blocks with the numerically-stable ONLINE softmax (flash-attention's
running max/denominator), so the full (S, S) score matrix never exists.

Communication: (S/p) x d K/V tiles move p-1 times per device —
all bandwidth on nearest-neighbor ICI links, overlapping compute, the
standard TPU ring-collective shape. The causal variant masks by GLOBAL
position, so rotated blocks mask correctly regardless of ring step.

API:
- :func:`ring_attention` — shard_map'd entry over a mesh with a ``seq``
  axis; inputs (B, S, H, D) sharded on S.
- :func:`attention_reference` — O(S^2) single-device reference used by
  tests and small inputs.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mmlspark_tpu.ops.shmap import shard_map
from mmlspark_tpu.parallel.mesh import AXIS_SEQ


def attention_reference(q, k, v, causal: bool = False, scale: Optional[float] = None):
    """Plain O(S^2) attention: q/k/v (B, S, H, D) -> (B, S, H, D)."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    # (B, H, S, S)
    scores = jnp.einsum("bshd,bthd->bhst", q, k) * scale
    if causal:
        s, t = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s, t), dtype=bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhst,bthd->bshd", probs, v)


def _block_attend(q, k, v, q_pos, k_pos, acc, row_max, denom, causal, scale):
    """One ring step: attend local q to one K/V block with online softmax.

    q (B, Sq, H, D); k/v (B, Sk, H, D); q_pos (Sq,), k_pos (Sk,) GLOBAL
    positions; acc (B, Sq, H, D) running numerator; row_max/denom
    (B, Sq, H) running stats. Returns updated (acc, row_max, denom)."""
    scores = jnp.einsum("bshd,bthd->bhst", q, k) * scale  # (B, H, Sq, Sk)
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]  # (Sq, Sk) global causal
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    block_max = scores.max(axis=-1)  # (B, H, Sq)
    new_max = jnp.maximum(row_max, block_max.transpose(0, 2, 1))  # (B, Sq, H)
    # guard: rows with no visible keys anywhere yet keep -inf max
    safe_max = jnp.where(jnp.isfinite(new_max), new_max, 0.0)
    correction = jnp.exp(
        jnp.where(jnp.isfinite(row_max), row_max - safe_max, -jnp.inf)
    )  # (B, Sq, H)
    probs = jnp.exp(
        scores - safe_max.transpose(0, 2, 1)[..., None]
    )  # (B, H, Sq, Sk); -inf rows -> 0
    block_num = jnp.einsum("bhst,bthd->bshd", probs, v)
    block_den = probs.sum(axis=-1).transpose(0, 2, 1)  # (B, Sq, H)
    acc = acc * correction[..., None] + block_num
    denom = denom * correction + block_den
    return acc, new_max, denom


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    causal: bool = False,
    scale: Optional[float] = None,
):
    """Sequence-parallel attention: (B, S, H, D) inputs sharded over the
    mesh ``seq`` axis; output identically sharded. Falls back to the
    reference when the seq axis is 1."""
    p = int(mesh.shape.get(AXIS_SEQ, 1))
    if p <= 1:
        return attention_reference(q, k, v, causal=causal, scale=scale)
    d = q.shape[-1]
    scale_val = scale if scale is not None else 1.0 / (d ** 0.5)
    s_global = q.shape[1]
    if s_global % p != 0:
        raise ValueError(f"sequence {s_global} not divisible by seq axis {p}")
    s_local = s_global // p

    def local_fn(q_l, k_l, v_l):
        # my ring position and my queries' global positions
        idx = lax.axis_index(AXIS_SEQ)
        q_pos = idx * s_local + jnp.arange(s_local)

        b, _, h, _ = q_l.shape
        acc = jnp.zeros_like(q_l)
        row_max = jnp.full((b, s_local, h), -jnp.inf, dtype=q_l.dtype)
        denom = jnp.zeros((b, s_local, h), dtype=q_l.dtype)

        perm = [(i, (i + 1) % p) for i in range(p)]  # ring: pass K/V right

        # Iteration 0 (the local block) is peeled out of the loop so the
        # ppermute inside the loop body is UNCONDITIONAL: a collective under
        # value-dependent control flow is fragile across XLA backends even
        # with a replicated predicate (deadlock if the pattern is ever
        # disturbed). p-1 rotations total, no discarded final permute.
        acc, row_max, denom = _block_attend(
            q_l, k_l, v_l, q_pos, idx * s_local + jnp.arange(s_local),
            acc, row_max, denom, causal, scale_val,
        )

        def step(i, carry):
            k_blk, v_blk, acc, row_max, denom = carry
            k_blk = lax.ppermute(k_blk, AXIS_SEQ, perm)
            v_blk = lax.ppermute(v_blk, AXIS_SEQ, perm)
            # the block we hold at ring step i originated at (idx - i) mod p
            src = (idx - i) % p
            k_pos = src * s_local + jnp.arange(s_local)
            acc, row_max, denom = _block_attend(
                q_l, k_blk, v_blk, q_pos, k_pos, acc, row_max, denom,
                causal, scale_val,
            )
            return k_blk, v_blk, acc, row_max, denom

        _, _, acc, row_max, denom = lax.fori_loop(
            1, p, step, (k_l, v_l, acc, row_max, denom)
        )
        # rows with zero visible keys (can't happen causally: self is visible)
        return acc / jnp.maximum(denom, 1e-30)[..., None]

    from mmlspark_tpu.parallel.mesh import AXIS_DATA

    # batch rides the data axis simultaneously (attention is batch-local),
    # so a data x seq mesh uses both without gathers
    spec = P(AXIS_DATA if int(mesh.shape.get(AXIS_DATA, 1)) > 1 else None, AXIS_SEQ)
    shard = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return shard(q, k, v)
