"""Pipeline parallelism over the mesh ``pipe`` axis (GPipe schedule).

A stack of layers is split into contiguous stages, one stage per device on
the ``pipe`` axis; a batch is split into microbatches that flow through the
stages in a bubble schedule: at step t, stage s processes microbatch
t - s while activations hop stage→stage over ``lax.ppermute`` (neighbor
ICI links). With M microbatches and p stages the bubble is the standard
(p-1)/(M+p-1) fraction.

API: :func:`pipeline_apply` — stage params stacked on a leading axis
sharded over ``pipe``; the output is replicated. Shapes must be uniform
across stages (each stage maps (mb, d) -> (mb, d)); project in/out around
the pipeline.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mmlspark_tpu.ops.shmap import shard_map
from mmlspark_tpu.parallel.mesh import AXIS_PIPE


def pipeline_apply(
    stage_fn: Callable,
    stage_params,
    x: jax.Array,
    mesh,
    num_microbatches: int,
):
    """Run ``x`` through ``p`` pipeline stages.

    ``stage_fn(params_one_stage, h) -> h`` applies ONE stage;
    ``stage_params`` is a pytree whose leaves have a leading axis of size
    ``p`` (one slice per stage), sharded over the ``pipe`` mesh axis;
    ``x`` is (B, D) with B divisible by ``num_microbatches``. Returns the
    (B, D_out) result, replicated. Falls back to a sequential scan over
    stages when the pipe axis is 1."""
    p = int(mesh.shape.get(AXIS_PIPE, 1))
    n_stages = jax.tree.leaves(stage_params)[0].shape[0]
    if p > 1 and n_stages != p:
        raise ValueError(
            f"{n_stages} stages but pipe axis of {p} — the schedule places "
            "exactly one stage per device; fold layers into stages so the "
            "leading params axis equals the pipe size"
        )
    if p <= 1:
        def seq_body(h, params_s):
            return stage_fn(params_s, h), None

        out, _ = lax.scan(seq_body, x, stage_params)
        return out

    b = x.shape[0]
    m = num_microbatches
    if b % m != 0:
        raise ValueError(f"batch {b} not divisible by {m} microbatches")
    mb = b // m
    xs = x.reshape(m, mb, *x.shape[1:])

    perm = [(i, i + 1) for i in range(p - 1)]  # stage s -> s+1

    def local_fn(params_local, xs_l):
        # params_local leaves arrive as (1, ...) slices of the stage axis
        params_local = jax.tree.map(lambda a: a[0], params_local)
        s = lax.axis_index(AXIS_PIPE)
        steps = m + p - 1
        zero_mb = jnp.zeros_like(stage_fn(params_local, xs_l[0]))
        recv = jnp.zeros_like(xs_l[0])
        outputs = jnp.zeros((m,) + zero_mb.shape, zero_mb.dtype)

        def step(t, carry):
            recv, outputs = carry
            feed_idx = jnp.clip(t, 0, m - 1)
            inp = jnp.where(s == 0, xs_l[feed_idx], recv)
            out = stage_fn(params_local, inp)
            # last stage records microbatch t-(p-1) BEFORE the hop
            rec_idx = jnp.clip(t - (p - 1), 0, m - 1)
            record = (s == p - 1) & (t >= p - 1)
            outputs = outputs.at[rec_idx].set(
                jnp.where(record, out, outputs[rec_idx])
            )
            recv = lax.ppermute(out, AXIS_PIPE, perm)
            return recv, outputs

        _, outputs = lax.fori_loop(0, steps, step, (recv, outputs))
        # only the last stage holds real outputs; psum replicates them
        outputs = jnp.where(s == p - 1, outputs, 0.0)
        return lax.psum(outputs, AXIS_PIPE)

    # strip the stage axis onto the mesh; microbatches replicated
    out = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(AXIS_PIPE), stage_params),
            P(),
        ),
        out_specs=P(),
        check_vma=False,
    )(stage_params, xs)
    return out.reshape(b, *out.shape[2:])
