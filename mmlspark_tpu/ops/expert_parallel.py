"""Expert parallelism over the mesh ``expert`` axis (MoE dispatch).

Experts shard one-per-device over the ``expert`` axis. Two dispatch
formulations, both static-shape:

- :func:`moe_apply` — masked-dense: every device applies ITS expert to the
  full (replicated) token batch, masks the tokens routed elsewhere, and a
  ``lax.psum`` combines. Dense compute trades FLOPs for zero
  load-imbalance stalls; right while the batch fits replicated.
- :func:`moe_apply_a2a` — capacity-based ``all_to_all`` (the GShard
  layout): tokens shard over the expert axis, each device packs its local
  tokens into fixed-capacity per-expert send buffers, ONE all_to_all
  routes buffers to the owning expert, the expert runs on its received
  tokens, and the reverse all_to_all brings outputs home. Compute and
  memory per device stay ∝ B/E; tokens beyond an expert's capacity are
  dropped (output zero), the standard capacity-factor contract.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mmlspark_tpu.ops.shmap import shard_map
from mmlspark_tpu.parallel.mesh import AXIS_EXPERT


def moe_apply(
    expert_fn: Callable,
    expert_params,
    x: jax.Array,
    gate_logits: jax.Array,
    mesh,
):
    """Top-1 mixture of experts.

    ``expert_fn(params_one_expert, x) -> y`` applies one expert to a token
    batch; ``expert_params`` leaves carry a leading axis of size E sharded
    over ``expert``; ``x`` is (B, D); ``gate_logits`` is (B, E). Returns
    (B, D_out) = gate_prob[chosen] * expert_chosen(x), replicated. Falls
    back to a sequential scan when the expert axis is 1."""
    e_mesh = int(mesh.shape.get(AXIS_EXPERT, 1))
    e_total = jax.tree.leaves(expert_params)[0].shape[0]
    if e_mesh > 1 and e_total != e_mesh:
        raise ValueError(
            f"{e_total} experts but expert axis of {e_mesh} — the masked "
            "dispatch places exactly one expert per device"
        )
    probs = jax.nn.softmax(gate_logits, axis=1)
    assign = jnp.argmax(gate_logits, axis=1)  # (B,)
    chosen_p = jnp.take_along_axis(probs, assign[:, None], axis=1)  # (B, 1)

    if e_mesh <= 1:
        def seq_body(acc, inputs):
            eidx, params_e = inputs
            mask = (assign == eidx)[:, None]
            return acc + expert_fn(params_e, x) * mask * chosen_p, None

        shape = jax.eval_shape(
            expert_fn, jax.tree.map(lambda a: a[0], expert_params), x
        )
        zero = jnp.zeros(shape.shape, shape.dtype)
        out, _ = lax.scan(
            seq_body, zero, (jnp.arange(e_total), expert_params)
        )
        return out

    def local_fn(params_local, x_l, assign_l, chosen_l):
        params_one = jax.tree.map(lambda a: a[0], params_local)
        eidx = lax.axis_index(AXIS_EXPERT)
        mask = (assign_l == eidx)[:, None]
        out = expert_fn(params_one, x_l) * mask * chosen_l
        return lax.psum(out, AXIS_EXPERT)

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(AXIS_EXPERT), expert_params),
            P(),
            P(),
            P(),
        ),
        out_specs=P(),
        check_vma=False,
    )(expert_params, x, assign, chosen_p)


def moe_apply_a2a(
    expert_fn: Callable,
    expert_params,
    x: jax.Array,
    gate_logits: jax.Array,
    mesh,
    capacity_factor: float = 1.25,
):
    """Top-1 MoE via capacity-based all_to_all dispatch.

    ``x`` (B, D) and ``gate_logits`` (B, E) shard their batch over the
    ``expert`` mesh axis (B divisible by E); expert e lives on device e.
    Each device packs its B/E local tokens into (E, C) send slots with
    ``C = ceil(B/E/E * capacity_factor)`` per destination, one
    ``all_to_all`` delivers every expert its (E, C) received tokens, the
    expert runs once on E*C tokens, and the reverse all_to_all routes
    outputs back. Tokens that overflow an expert's local capacity are
    DROPPED (zero output, the capacity-factor contract). Falls back to the
    masked-dense form when the expert axis is 1."""
    e_mesh = int(mesh.shape.get(AXIS_EXPERT, 1))
    if e_mesh <= 1:
        return moe_apply(expert_fn, expert_params, x, gate_logits, mesh)
    e_total = jax.tree.leaves(expert_params)[0].shape[0]
    if e_total != e_mesh:
        raise ValueError(
            f"{e_total} experts but expert axis of {e_mesh} — one expert "
            "per device"
        )
    b, d = x.shape
    if b % e_mesh != 0:
        raise ValueError(f"batch {b} not divisible by expert axis {e_mesh}")
    b_local = b // e_mesh
    import math

    cap = max(1, math.ceil(b_local / e_mesh * capacity_factor))

    probs = jax.nn.softmax(gate_logits, axis=1)
    assign = jnp.argmax(gate_logits, axis=1).astype(jnp.int32)  # (B,)
    chosen_p = jnp.take_along_axis(probs, assign[:, None], axis=1)  # (B, 1)

    def local_fn(params_local, x_l, assign_l, chosen_l):
        params_one = jax.tree.map(lambda a: a[0], params_local)
        # position of each local token within its destination expert's
        # send buffer (rank among same-destination tokens, in order)
        dest_oh = (
            assign_l[:, None] == jnp.arange(e_mesh, dtype=jnp.int32)[None, :]
        )  # (b_local, E)
        pos = jnp.cumsum(dest_oh.astype(jnp.int32), axis=0) - 1  # rank per dest
        my_pos = jnp.take_along_axis(pos, assign_l[:, None], axis=1)[:, 0]
        keep = my_pos < cap  # overflow tokens dropped

        # scatter local tokens into (E, C, D) send buffers; slot (e, c)
        # holds the c-th kept token destined for expert e
        slot = jnp.where(keep, assign_l * cap + my_pos, e_mesh * cap)  # drop->OOB
        send = jnp.zeros((e_mesh * cap, x_l.shape[1]), x_l.dtype).at[slot].set(
            x_l, mode="drop"
        ).reshape(e_mesh, cap, x_l.shape[1])

        # deliver: device e receives the e-th buffer from every source
        recv = lax.all_to_all(send, AXIS_EXPERT, split_axis=0, concat_axis=0,
                              tiled=True)
        # flatten (E, C, D) -> (E*C, D): expert_fn's contract is a 2-D token
        # batch, same as the masked-dense path
        recv = recv.reshape(e_mesh * cap, recv.shape[-1])
        out = expert_fn(params_one, recv)  # (E*C, D_out)

        # route home: reverse all_to_all returns each source its slots
        back = lax.all_to_all(
            out.reshape(e_mesh, cap, out.shape[-1]), AXIS_EXPERT,
            split_axis=0, concat_axis=0, tiled=True,
        ).reshape(e_mesh * cap, out.shape[-1])

        # gather my tokens' outputs from their slots; dropped -> zero
        safe_slot = jnp.where(keep, slot, 0)
        y = back[safe_slot] * keep[:, None] * chosen_l
        return y

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(AXIS_EXPERT), expert_params),
            P(AXIS_EXPERT),
            P(AXIS_EXPERT),
            P(AXIS_EXPERT),
        ),
        out_specs=P(AXIS_EXPERT),
        check_vma=False,
    )(expert_params, x, assign, chosen_p)
