"""Expert parallelism over the mesh ``expert`` axis (MoE dispatch).

Experts shard one-per-device over the ``expert`` axis. Routing is top-1 by
gate score; the static-shape TPU formulation is masked-dense dispatch:
every device applies ITS expert to the full token batch, masks the tokens
routed elsewhere, scales by the gate probability, and a single
``lax.psum`` combines the expert outputs (each token received exactly one
expert's contribution). Dense compute trades FLOPs for static shapes and
zero load-imbalance stalls; the capacity-based all_to_all variant is the
follow-on once expert counts outgrow the masked form.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mmlspark_tpu.parallel.mesh import AXIS_EXPERT


def moe_apply(
    expert_fn: Callable,
    expert_params,
    x: jax.Array,
    gate_logits: jax.Array,
    mesh,
):
    """Top-1 mixture of experts.

    ``expert_fn(params_one_expert, x) -> y`` applies one expert to a token
    batch; ``expert_params`` leaves carry a leading axis of size E sharded
    over ``expert``; ``x`` is (B, D); ``gate_logits`` is (B, E). Returns
    (B, D_out) = gate_prob[chosen] * expert_chosen(x), replicated. Falls
    back to a sequential scan when the expert axis is 1."""
    e_mesh = int(mesh.shape.get(AXIS_EXPERT, 1))
    e_total = jax.tree.leaves(expert_params)[0].shape[0]
    if e_mesh > 1 and e_total != e_mesh:
        raise ValueError(
            f"{e_total} experts but expert axis of {e_mesh} — the masked "
            "dispatch places exactly one expert per device"
        )
    probs = jax.nn.softmax(gate_logits, axis=1)
    assign = jnp.argmax(gate_logits, axis=1)  # (B,)
    chosen_p = jnp.take_along_axis(probs, assign[:, None], axis=1)  # (B, 1)

    if e_mesh <= 1:
        def seq_body(acc, inputs):
            eidx, params_e = inputs
            mask = (assign == eidx)[:, None]
            return acc + expert_fn(params_e, x) * mask * chosen_p, None

        shape = jax.eval_shape(
            expert_fn, jax.tree.map(lambda a: a[0], expert_params), x
        )
        zero = jnp.zeros(shape.shape, shape.dtype)
        out, _ = lax.scan(
            seq_body, zero, (jnp.arange(e_total), expert_params)
        )
        return out

    def local_fn(params_local, x_l, assign_l, chosen_l):
        params_one = jax.tree.map(lambda a: a[0], params_local)
        eidx = lax.axis_index(AXIS_EXPERT)
        mask = (assign_l == eidx)[:, None]
        out = expert_fn(params_one, x_l) * mask * chosen_l
        return lax.psum(out, AXIS_EXPERT)

    return jax.shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(AXIS_EXPERT), expert_params),
            P(),
            P(),
            P(),
        ),
        out_specs=P(),
        check_vma=False,
    )(expert_params, x, assign, chosen_p)
