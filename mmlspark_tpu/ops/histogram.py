"""Gradient/hessian/count histogram building over (node, feature, bin).

The hot op of GBDT training — the TPU replacement for LightGBM's native
per-leaf histogram construction (``LGBM_BoosterUpdateOneIter``'s inner loop,
reference ``lightgbm/TrainUtils.scala:220-315``). Two implementations:

- ``segment``: flat ``segment_sum`` scatter-add. Fast on CPU; on TPU XLA
  lowers it to serialized scatters, so it is the fallback path.
- ``onehot``: per-feature one-hot matmul ``one_hot(node*B + bin) @ [g,h,c]``.
  Dense MXU work with static shapes — the TPU-first formulation: ~N*K*3
  FLOPs per feature beat sparse scatter on the systolic array.
- ``pallas``: hand-written kernel fusing one-hot construction with the
  reduction in VMEM (``ops/pallas_histogram.py``); falls back to
  ``onehot`` when K exceeds its VMEM budget. A/B numbers and the roofline
  argument live in ``docs/perf_histogram.md``.

Distribution: callers shard rows across the mesh ``data`` axis; the
histogram is a sum over rows, so under jit XLA inserts the cross-device
``all-reduce`` automatically — this *is* the ``data_parallel`` histogram
allreduce that LightGBM runs over its socket mesh.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _default_method() -> str:
    # pallas (VMEM-fused one-hot) measures 1.6x faster than the XLA one-hot
    # at the leafwise hot shape on v5e (docs/perf_histogram.md); it falls
    # back to onehot itself when K exceeds its VMEM budget.
    return "pallas" if jax.default_backend() in ("tpu", "axon") else "segment"


def build_histograms(
    bins: jax.Array,  # (N, F) integer bin indices
    grad: jax.Array,  # (N,)
    hess: jax.Array,  # (N,)
    count: jax.Array,  # (N,) sample weight-of-presence (0/1 under bagging)
    node: jax.Array,  # (N,) int32 local node index in [0, num_nodes)
    num_nodes: int,
    num_bins: int,
    method: Optional[str] = None,
    chunk_rows: bool = True,
) -> jax.Array:
    """Returns (num_nodes, F, num_bins, 3) float32: per-cell [sum_g, sum_h, count].

    ``chunk_rows=False`` disables the bounded-transient row chunking of the
    onehot/panel formulations — required under a mesh, where padding and
    scan-slicing the ROW-SHARDED dimension would force GSPMD to all-gather
    the full matrix per pass (each device's shard is 1/devices of N there,
    so the unchunked transient is already bounded)."""
    method = method or _default_method()
    n, f = bins.shape
    bins = bins.astype(jnp.int32)
    node = node.astype(jnp.int32)
    data = jnp.stack(
        [grad.astype(jnp.float32), hess.astype(jnp.float32), count.astype(jnp.float32)],
        axis=-1,
    )  # (N, 3)

    if method == "segment":
        # ids[i, j] = ((node_i * F) + j) * B + bins[i, j]
        ids = (node[:, None] * f + jnp.arange(f, dtype=jnp.int32)[None, :]) * num_bins + bins
        flat_ids = ids.reshape(-1)
        flat_data = jnp.broadcast_to(data[:, None, :], (n, f, 3)).reshape(-1, 3)
        seg = jax.ops.segment_sum(
            flat_data, flat_ids, num_segments=num_nodes * f * num_bins
        )
        return seg.reshape(num_nodes, f, num_bins, 3)

    if method == "pallas":
        from mmlspark_tpu.ops.pallas_histogram import (
            build_histograms_pallas,
            build_histograms_panel_pallas,
            panel_fits,
            pick_bw,
        )

        # Multi-node passes route to the panel kernel: its one-hot build —
        # the VPU-bound resource — is independent of the node count (the
        # node key rides in MXU lane padding), so k nodes cost ~one.
        if num_nodes > 1 and panel_fits(num_nodes, num_bins):
            return build_histograms_panel_pallas(
                bins, grad, hess, count, node, num_nodes, num_bins
            )
        k = num_nodes * num_bins
        # Below one lane group the XLA one-hot wins (measured 6x at K=64,
        # docs/perf_histogram.md); above the VMEM budget pallas refuses.
        if k >= 128 and pick_bw(k):
            return build_histograms_pallas(
                bins, grad, hess, count, node, num_nodes, num_bins
            )
        method = "onehot"

    if method == "panel" or (method == "onehot" and num_nodes > 1 and 3 * num_nodes <= 128):
        # XLA panel formulation (mesh-compatible — plain jnp, so GSPMD can
        # row-shard it and insert the allreduce): bin-only one-hot against a
        # node-keyed (N, 3k) data panel. Rows with node outside [0, k) get a
        # zero panel row, which callers use as the in-leaf mask.
        # The one-hot is built in bounded ROW CHUNKS: an (N, B) f32 one-hot
        # at multi-million rows is gigabytes of transient per scan step and
        # crashes the TPU worker (this is the >1M fallback path — the
        # precomputed-U formulation gates off on its own HBM budget there).
        from mmlspark_tpu.ops.pallas_histogram import build_node_panel

        k = num_nodes
        panel = build_node_panel(grad, hess, count, node, k)
        if not chunk_rows:
            def per_feature_whole(_, feat_col):
                oh = jax.nn.one_hot(feat_col, num_bins, dtype=panel.dtype)
                return None, oh.T @ panel  # (B, 3k)

            _, hists = lax.scan(per_feature_whole, None, bins.T)
            return hists.reshape(f, num_bins, 3, k).transpose(3, 0, 1, 2)
        chunk = max(1, min(n, (64 << 20) // max(4 * num_bins, 1)))
        pad = (-n) % chunk
        bins_p = jnp.pad(bins, ((0, pad), (0, 0))) if pad else bins
        panel_p = jnp.pad(panel, ((0, pad), (0, 0))) if pad else panel
        r = (n + pad) // chunk
        bins_r = bins_p.reshape(r, chunk, f).transpose(2, 0, 1)  # (F, R, chunk)
        panel_r = panel_p.reshape(r, chunk, 3 * k)

        def per_feature_panel(_, feat_rows):  # (R, chunk)
            def per_chunk(acc, rc):
                fc, pl = rc  # padded rows carry zero panel rows => no-op
                oh = jax.nn.one_hot(fc, num_bins, dtype=panel.dtype)
                return acc + oh.T @ pl, None

            h0 = jnp.zeros((num_bins, 3 * k), panel.dtype)
            h, _ = lax.scan(per_chunk, h0, (feat_rows, panel_r))
            return None, h

        _, hists = lax.scan(per_feature_panel, None, bins_r)  # (F, B, 3k)
        return hists.reshape(f, num_bins, 3, k).transpose(3, 0, 1, 2)

    if method == "onehot":
        k = num_nodes * num_bins
        base = node * num_bins  # (N,)
        if not chunk_rows:
            def per_feature_whole(_, feat_col):
                oh = jax.nn.one_hot(base + feat_col, k, dtype=jnp.float32)
                return None, oh.T @ data  # (K, 3) — MXU matmul

            _, hists = lax.scan(per_feature_whole, None, bins.T)
            return hists.reshape(f, num_nodes, num_bins, 3).transpose(1, 0, 2, 3)
        chunk = max(1, min(n, (64 << 20) // max(4 * k, 1)))
        pad = (-n) % chunk
        bins_p = jnp.pad(bins, ((0, pad), (0, 0))) if pad else bins
        base_p = jnp.pad(base, (0, pad)) if pad else base
        data_p = jnp.pad(data, ((0, pad), (0, 0))) if pad else data
        r = (n + pad) // chunk
        bins_r = bins_p.reshape(r, chunk, f).transpose(2, 0, 1)  # (F, R, chunk)
        base_r = base_p.reshape(r, chunk)
        data_r = data_p.reshape(r, chunk, 3)

        def per_feature(_, feat_rows):  # (R, chunk)
            def per_chunk(acc, rc):
                fc, bc, dc = rc  # padded rows carry zero data rows => no-op
                oh = jax.nn.one_hot(bc + fc, k, dtype=jnp.float32)
                return acc + oh.T @ dc, None

            h0 = jnp.zeros((k, 3), jnp.float32)
            h, _ = lax.scan(per_chunk, h0, (feat_rows, base_r, data_r))
            return None, h

        _, hists = lax.scan(per_feature, None, bins_r)  # (F, K, 3)
        return hists.reshape(f, num_nodes, num_bins, 3).transpose(1, 0, 2, 3)

    raise ValueError(f"unknown histogram method {method!r}")
