"""Pallas TPU kernel for GBDT histogram building.

The hot op (reference ``lightgbm/TrainUtils.scala:220-315`` runs it natively
per iteration) re-expressed for the MXU: instead of materializing a one-hot
matrix in HBM and matmuling (the XLA ``onehot`` path in
``ops/histogram.py``), the kernel fuses one-hot construction and the
reduction entirely in VMEM:

- grid (F, N/block): each step loads one feature's combined-id tile
  (``node*B + bin``, pre-added outside the kernel so XLA fuses it into the
  transpose pass) and the (g, h, c) data tile;
- builds the (8, bw, K) one-hot *in VMEM* via an iota compare (never
  written to HBM); rows are tiled (8, bw) because Mosaic cannot flatten a
  sublane×lane tile to 1D, so the contraction is a sublane-batched
  ``dot_general`` summed over the batch;
- accumulates into the (K, 3) output block, which stays resident in VMEM
  across the whole row loop (revisited output block = accumulation idiom);
- default MXU precision (1-pass bf16 inputs, f32 accumulation) measures
  3.3x faster than ``Precision.HIGHEST`` on v5e and matches what the XLA
  one-hot path does on TPU anyway; the one-hot side is exactly
  representable, so only g/h pick up bf16 input rounding (~0.4%% relative
  per element, unbiased — the same class of approximation as LightGBM's
  own histogram binning). ``precision="highest"`` restores exact f32.

HBM traffic is therefore just the operands — the id matrix (4·N·F bytes),
data (12·N bytes, re-read per feature tile) and the (F·K·3·4)-byte result —
the bandwidth floor of the op. See ``docs/perf_histogram.md`` for the
measured A/B against the XLA formulation and the roofline argument.

VMEM budget gates the row-block size: the one-hot tile is 8·bw·K·4 bytes,
so ``bw`` shrinks as K = num_nodes·num_bins grows; below the minimum lane
width the kernel refuses and the caller falls back to XLA.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

# One-hot VMEM budget. 6 MiB leaves room for the id/data tiles, the (K, 3)
# accumulator, and double buffering within ~16 MiB of VMEM.
_ONEHOT_BYTES = 6 << 20
_SUBLANES = 8
_MIN_BW = 128
_MAX_BW = 512


def pick_bw(k: int) -> int:
    """Lane width bw whose one-hot (8, bw, K) f32 tile fits the VMEM budget;
    0 when even the minimum would blow it (caller must fall back to XLA)."""
    bw = _ONEHOT_BYTES // (4 * _SUBLANES * max(k, 1))
    bw = min(_MAX_BW, (bw // _MIN_BW) * _MIN_BW)
    return bw if bw >= _MIN_BW else 0


def panel_fits(num_nodes: int, num_bins: int) -> bool:
    """Whether the panel kernel applies: the node panel must fit one MXU
    lane group and the bin one-hot must fill at least one."""
    return 3 * num_nodes <= 128 and num_bins >= 128 and pick_bw(num_bins) > 0


def build_node_panel(grad, hess, count, node, num_nodes: int):
    """(N, 3*num_nodes) stat-major data panel [g·nodes | h·nodes | c·nodes]:
    row i carries its (g, h, c) in the node[i]-keyed columns and zeros
    elsewhere; out-of-range node keys zero the whole row (the in-leaf mask
    convention). The ONE definition of the panel layout — the pallas and XLA
    histogram paths both decode it as reshape(F, B, 3, k).transpose(3,0,1,2),
    so they must share the encoder."""
    node = node.astype(jnp.int32)
    nodeoh = (
        node[:, None] == jnp.arange(num_nodes, dtype=jnp.int32)[None, :]
    ).astype(jnp.float32)  # (N, k)
    data = jnp.stack(
        [grad.astype(jnp.float32), hess.astype(jnp.float32), count.astype(jnp.float32)],
        axis=-1,
    )  # (N, 3)
    return (data[:, :, None] * nodeoh[:, None, :]).reshape(node.shape[0], 3 * num_nodes)


def build_histograms_panel_pallas(
    bins: jax.Array,  # (N, F) integer bin indices
    grad: jax.Array,  # (N,)
    hess: jax.Array,  # (N,)
    count: jax.Array,  # (N,)
    node: jax.Array,  # (N,) int32 node key; out-of-range ⇒ row contributes 0
    num_nodes: int,
    num_bins: int,
    *,
    bw: Optional[int] = None,
    interpret: Optional[bool] = None,
    precision: str = "default",
) -> jax.Array:
    """(num_nodes, F, num_bins, 3) float32 via the panel formulation: the
    node key moves from the one-hot ids (where each node adds B VPU-built
    one-hot columns) into a precomputed (N, 3*num_nodes) data panel whose
    lane dimension the MXU pads to 128 anyway — so up to ``floor(128/3) =
    42`` nodes cost the same pass as one. The panel is built by ONE fused
    XLA pass over the rows (node one-hot × [g,h,c]); the kernel itself is
    the same VMEM-fused bin one-hot as the combined-id kernel, just with a
    wide data operand. This is what makes multi-leaf-per-pass leafwise
    growth ~free (train.py).

    Unlike the combined-id kernel, rows whose node key is outside
    [0, num_nodes) contribute nothing (zero panel row) — callers exploit
    this as the in-leaf mask, so no grad/hess pre-masking pass is needed."""
    n, f = bins.shape
    if 3 * num_nodes > 128:
        raise ValueError(f"panel width 3*{num_nodes} exceeds one lane group")
    if bw is None:
        bw = pick_bw(num_bins)
    if not bw:
        raise ValueError(f"num_bins={num_bins} too large for the VMEM budget")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    block_n = _SUBLANES * bw
    panel = build_node_panel(grad, hess, count, node, num_nodes)
    ids = bins.astype(jnp.int32)

    pad = (-n) % block_n
    if pad:
        ids = jnp.pad(ids, ((0, pad), (0, 0)))
        panel = jnp.pad(panel, ((0, pad), (0, 0)))
    n_pad = n + pad
    tiles = n_pad // block_n
    d = 3 * num_nodes

    ids3 = ids.T.reshape(f, tiles * _SUBLANES, bw)
    panel3 = panel.reshape(tiles * _SUBLANES, bw, d)

    prec = lax.Precision.HIGHEST if precision == "highest" else None
    out = pl.pallas_call(
        functools.partial(_hist_kernel, bw=bw, k=num_bins, precision=prec),
        grid=(f, tiles),
        in_specs=[
            pl.BlockSpec((1, _SUBLANES, bw), lambda j, t: (j, t, 0)),
            pl.BlockSpec((_SUBLANES, bw, d), lambda j, t: (t, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, num_bins, d), lambda j, t: (j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((f, num_bins, d), jnp.float32),
        interpret=interpret,
    )(ids3, panel3)
    # (F, B, 3*nodes) stat-major → (nodes, F, B, 3)
    return out.reshape(f, num_bins, 3, num_nodes).transpose(3, 0, 1, 2)


def _hist_kernel(ids_ref, data_ref, out_ref, *, bw: int, k: int, precision):
    t = pl.program_id(1)
    ids = ids_ref[0]  # (8, bw) int32 combined node*B + bin
    onehot = (
        ids[:, :, None] == lax.broadcasted_iota(jnp.int32, (_SUBLANES, bw, k), 2)
    ).astype(jnp.float32)
    # Sublane-batched (8, K, 3) matmul on the MXU, then fold the batch.
    contrib = lax.dot_general(
        onehot,
        data_ref[:],
        (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=precision,
    ).sum(axis=0)

    @pl.when(t == 0)
    def _init():
        out_ref[0] = contrib

    @pl.when(t != 0)
    def _acc():
        out_ref[0] += contrib


def build_histograms_pallas(
    bins: jax.Array,  # (N, F) integer bin indices
    grad: jax.Array,  # (N,)
    hess: jax.Array,  # (N,)
    count: jax.Array,  # (N,)
    node: jax.Array,  # (N,) int32 local node index
    num_nodes: int,
    num_bins: int,
    *,
    bw: Optional[int] = None,
    interpret: Optional[bool] = None,
    precision: str = "default",
) -> jax.Array:
    """(num_nodes, F, num_bins, 3) float32 — same contract as
    ``ops.histogram.build_histograms``. Raises ValueError when K exceeds
    the VMEM budget (callers gate on :func:`pick_bw`)."""
    n, f = bins.shape
    k = num_nodes * num_bins
    if bw is None:
        bw = pick_bw(k)
    if not bw:
        raise ValueError(
            f"histogram K={k} too large for the Pallas VMEM budget; "
            "use the XLA fallback"
        )
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    block_n = _SUBLANES * bw
    data = jnp.stack(
        [grad.astype(jnp.float32), hess.astype(jnp.float32), count.astype(jnp.float32)],
        axis=-1,
    )  # (N, 3)
    ids = bins.astype(jnp.int32) + (node.astype(jnp.int32) * num_bins)[:, None]

    pad = (-n) % block_n
    if pad:
        # Padding rows carry zero data, so their one-hot contribution is 0.
        ids = jnp.pad(ids, ((0, pad), (0, 0)))
        data = jnp.pad(data, ((0, pad), (0, 0)))
    n_pad = n + pad
    tiles = n_pad // block_n

    ids3 = ids.T.reshape(f, tiles * _SUBLANES, bw)
    data3 = data.reshape(tiles * _SUBLANES, bw, 3)

    prec = lax.Precision.HIGHEST if precision == "highest" else None
    out = pl.pallas_call(
        functools.partial(_hist_kernel, bw=bw, k=k, precision=prec),
        grid=(f, tiles),
        in_specs=[
            pl.BlockSpec((1, _SUBLANES, bw), lambda j, t: (j, t, 0)),
            # Trailing dim 3 = the packed (g, h, 1) stat triple; Mosaic pads
            # the lane axis to 128 and the deliberate waste is the measured
            # win over splitting stats into three aligned operands.
            pl.BlockSpec((_SUBLANES, bw, 3), lambda j, t: (t, 0, 0)),  # graftlint: disable=pallas-tile-alignment
        ],
        out_specs=pl.BlockSpec((1, k, 3), lambda j, t: (j, 0, 0)),  # graftlint: disable=pallas-tile-alignment
        out_shape=jax.ShapeDtypeStruct((f, k, 3), jnp.float32),
        interpret=interpret,
    )(ids3, data3)
    return out.reshape(f, num_nodes, num_bins, 3).transpose(1, 0, 2, 3)
