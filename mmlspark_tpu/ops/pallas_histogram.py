"""Pallas TPU kernel for GBDT histogram building.

The hot op (reference ``lightgbm/TrainUtils.scala:220-315`` runs it natively
per iteration) re-expressed for the MXU: instead of materializing a one-hot
matrix in HBM and matmuling (the XLA ``onehot`` path in
``ops/histogram.py``), the kernel fuses one-hot construction and the
reduction entirely in VMEM:

- grid (F, N/block): each step loads one feature's combined-id tile
  (``node*B + bin``, pre-added outside the kernel so XLA fuses it into the
  transpose pass) and the (g, h, c) data tile;
- builds the (8, bw, K) one-hot *in VMEM* via an iota compare (never
  written to HBM); rows are tiled (8, bw) because Mosaic cannot flatten a
  sublane×lane tile to 1D, so the contraction is a sublane-batched
  ``dot_general`` summed over the batch;
- accumulates into the (K, 3) output block, which stays resident in VMEM
  across the whole row loop (revisited output block = accumulation idiom);
- default MXU precision (1-pass bf16 inputs, f32 accumulation) measures
  3.3x faster than ``Precision.HIGHEST`` on v5e and matches what the XLA
  one-hot path does on TPU anyway; the one-hot side is exactly
  representable, so only g/h pick up bf16 input rounding (~0.4%% relative
  per element, unbiased — the same class of approximation as LightGBM's
  own histogram binning). ``precision="highest"`` restores exact f32.

HBM traffic is therefore just the operands — the id matrix (4·N·F bytes),
data (12·N bytes, re-read per feature tile) and the (F·K·3·4)-byte result —
the bandwidth floor of the op. See ``docs/perf_histogram.md`` for the
measured A/B against the XLA formulation and the roofline argument.

VMEM budget gates the row-block size: the one-hot tile is 8·bw·K·4 bytes,
so ``bw`` shrinks as K = num_nodes·num_bins grows; below the minimum lane
width the kernel refuses and the caller falls back to XLA.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# One-hot VMEM budget. 6 MiB leaves room for the id/data tiles, the (K, 3)
# accumulator, and double buffering within ~16 MiB of VMEM.
_ONEHOT_BYTES = 6 << 20
_SUBLANES = 8
_MIN_BW = 128
_MAX_BW = 512


def pick_bw(k: int) -> int:
    """Lane width bw whose one-hot (8, bw, K) f32 tile fits the VMEM budget;
    0 when even the minimum would blow it (caller must fall back to XLA)."""
    bw = _ONEHOT_BYTES // (4 * _SUBLANES * max(k, 1))
    bw = min(_MAX_BW, (bw // _MIN_BW) * _MIN_BW)
    return bw if bw >= _MIN_BW else 0


def panel_fits(num_nodes: int, num_bins: int) -> bool:
    """Whether the panel kernel applies: the node panel must fit one MXU
    lane group and the bin one-hot must fill at least one."""
    return 3 * num_nodes <= 128 and num_bins >= 128 and pick_bw(num_bins) > 0


def build_node_panel(grad, hess, count, node, num_nodes: int):
    """(N, 3*num_nodes) stat-major data panel [g·nodes | h·nodes | c·nodes]:
    row i carries its (g, h, c) in the node[i]-keyed columns and zeros
    elsewhere; out-of-range node keys zero the whole row (the in-leaf mask
    convention). The ONE definition of the panel layout — the pallas and XLA
    histogram paths both decode it as reshape(F, B, 3, k).transpose(3,0,1,2),
    so they must share the encoder."""
    node = node.astype(jnp.int32)
    nodeoh = (
        node[:, None] == jnp.arange(num_nodes, dtype=jnp.int32)[None, :]
    ).astype(jnp.float32)  # (N, k)
    data = jnp.stack(
        [grad.astype(jnp.float32), hess.astype(jnp.float32), count.astype(jnp.float32)],
        axis=-1,
    )  # (N, 3)
    return (data[:, :, None] * nodeoh[:, None, :]).reshape(node.shape[0], 3 * num_nodes)


def build_histograms_panel_pallas(
    bins: jax.Array,  # (N, F) integer bin indices
    grad: jax.Array,  # (N,)
    hess: jax.Array,  # (N,)
    count: jax.Array,  # (N,)
    node: jax.Array,  # (N,) int32 node key; out-of-range ⇒ row contributes 0
    num_nodes: int,
    num_bins: int,
    *,
    bw: Optional[int] = None,
    interpret: Optional[bool] = None,
    precision: str = "default",
) -> jax.Array:
    """(num_nodes, F, num_bins, 3) float32 via the panel formulation: the
    node key moves from the one-hot ids (where each node adds B VPU-built
    one-hot columns) into a precomputed (N, 3*num_nodes) data panel whose
    lane dimension the MXU pads to 128 anyway — so up to ``floor(128/3) =
    42`` nodes cost the same pass as one. The panel is built by ONE fused
    XLA pass over the rows (node one-hot × [g,h,c]); the kernel itself is
    the same VMEM-fused bin one-hot as the combined-id kernel, just with a
    wide data operand. This is what makes multi-leaf-per-pass leafwise
    growth ~free (train.py).

    Unlike the combined-id kernel, rows whose node key is outside
    [0, num_nodes) contribute nothing (zero panel row) — callers exploit
    this as the in-leaf mask, so no grad/hess pre-masking pass is needed."""
    n, f = bins.shape
    if 3 * num_nodes > 128:
        raise ValueError(f"panel width 3*{num_nodes} exceeds one lane group")
    if bw is None:
        bw = pick_bw(num_bins)
    if not bw:
        raise ValueError(f"num_bins={num_bins} too large for the VMEM budget")
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    block_n = _SUBLANES * bw
    panel = build_node_panel(grad, hess, count, node, num_nodes)
    ids = bins.astype(jnp.int32)

    pad = (-n) % block_n
    if pad:
        ids = jnp.pad(ids, ((0, pad), (0, 0)))
        panel = jnp.pad(panel, ((0, pad), (0, 0)))
    n_pad = n + pad
    tiles = n_pad // block_n
    d = 3 * num_nodes

    ids3 = ids.T.reshape(f, tiles * _SUBLANES, bw)
    panel3 = panel.reshape(tiles * _SUBLANES, bw, d)

    prec = lax.Precision.HIGHEST if precision == "highest" else None
    out = pl.pallas_call(
        functools.partial(_hist_kernel, bw=bw, k=num_bins, precision=prec),
        grid=(f, tiles),
        in_specs=[
            pl.BlockSpec((1, _SUBLANES, bw), lambda j, t: (j, t, 0)),
            pl.BlockSpec((_SUBLANES, bw, d), lambda j, t: (t, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, num_bins, d), lambda j, t: (j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((f, num_bins, d), jnp.float32),
        interpret=interpret,
    )(ids3, panel3)
    # (F, B, 3*nodes) stat-major → (nodes, F, B, 3)
    return out.reshape(f, num_bins, 3, num_nodes).transpose(3, 0, 1, 2)


def _hist_kernel(ids_ref, data_ref, out_ref, *, bw: int, k: int, precision):
    t = pl.program_id(1)
    ids = ids_ref[0]  # (8, bw) int32 combined node*B + bin
    onehot = (
        ids[:, :, None] == lax.broadcasted_iota(jnp.int32, (_SUBLANES, bw, k), 2)
    ).astype(jnp.float32)
    # Sublane-batched (8, K, 3) matmul on the MXU, then fold the batch.
    contrib = lax.dot_general(
        onehot,
        data_ref[:],
        (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=precision,
    ).sum(axis=0)

    @pl.when(t == 0)
    def _init():
        out_ref[0] = contrib

    @pl.when(t != 0)
    def _acc():
        out_ref[0] += contrib


def build_histograms_pallas(
    bins: jax.Array,  # (N, F) integer bin indices
    grad: jax.Array,  # (N,)
    hess: jax.Array,  # (N,)
    count: jax.Array,  # (N,)
    node: jax.Array,  # (N,) int32 local node index
    num_nodes: int,
    num_bins: int,
    *,
    bw: Optional[int] = None,
    interpret: Optional[bool] = None,
    precision: str = "default",
) -> jax.Array:
    """(num_nodes, F, num_bins, 3) float32 — same contract as
    ``ops.histogram.build_histograms``. Raises ValueError when K exceeds
    the VMEM budget (callers gate on :func:`pick_bw`)."""
    n, f = bins.shape
    k = num_nodes * num_bins
    if bw is None:
        bw = pick_bw(k)
    if not bw:
        raise ValueError(
            f"histogram K={k} too large for the Pallas VMEM budget; "
            "use the XLA fallback"
        )
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")

    block_n = _SUBLANES * bw
    data = jnp.stack(
        [grad.astype(jnp.float32), hess.astype(jnp.float32), count.astype(jnp.float32)],
        axis=-1,
    )  # (N, 3)
    ids = bins.astype(jnp.int32) + (node.astype(jnp.int32) * num_bins)[:, None]

    pad = (-n) % block_n
    if pad:
        # Padding rows carry zero data, so their one-hot contribution is 0.
        ids = jnp.pad(ids, ((0, pad), (0, 0)))
        data = jnp.pad(data, ((0, pad), (0, 0)))
    n_pad = n + pad
    tiles = n_pad // block_n

    ids3 = ids.T.reshape(f, tiles * _SUBLANES, bw)
    data3 = data.reshape(tiles * _SUBLANES, bw, 3)

    prec = lax.Precision.HIGHEST if precision == "highest" else None
    out = pl.pallas_call(
        functools.partial(_hist_kernel, bw=bw, k=k, precision=prec),
        grid=(f, tiles),
        in_specs=[
            pl.BlockSpec((1, _SUBLANES, bw), lambda j, t: (j, t, 0)),
            # Trailing dim 3 = the packed (g, h, 1) stat triple; Mosaic pads
            # the lane axis to 128 and the deliberate waste is the measured
            # win over splitting stats into three aligned operands.
            pl.BlockSpec((_SUBLANES, bw, 3), lambda j, t: (t, 0, 0)),  # graftlint: disable=pallas-tile-alignment
        ],
        out_specs=pl.BlockSpec((1, k, 3), lambda j, t: (j, 0, 0)),  # graftlint: disable=pallas-tile-alignment
        out_shape=jax.ShapeDtypeStruct((f, k, 3), jnp.float32),
        interpret=interpret,
    )(ids3, data3)
    return out.reshape(f, num_nodes, num_bins, 3).transpose(1, 0, 2, 3)


# ---------------------------------------------------------------------------
# Fused bin + scatter-add pass: the U contraction without the U.
# ---------------------------------------------------------------------------

_SCATTER_TN = 512  # rows per N-tile (lane-dim block of the bins stream)
_SCATTER_VMEM = 24 << 20


def bin_scatter_fits_vmem(k_pad: int, num_features: int, tn: int = _SCATTER_TN) -> bool:
    """VMEM gate for the fused bin+scatter pass: the per-tile one-hot
    scratch (k_pad x tn s8), the resident accumulator block (k_pad x 128,
    <= 4 B), the double-buffered bins tiles (F x tn s32) and the panel all
    have to sit inside the ~24 MB working budget."""
    f_pad = -(-max(num_features, 1) // _SUBLANES) * _SUBLANES
    return (
        k_pad * (tn + 4 * 128) + 2 * f_pad * tn * 4 + 8 * tn * 4
    ) <= _SCATTER_VMEM


def _bin_scatter_kernel(
    ids_ref, aux_ref, out_ref, u_scr, *, k: int, spec, quant: bool, tn: int
):
    """One N-tile of the fused pass. Reads the raw binned rows (F x tn s32
    — F bytes-per-row-class traffic instead of the K_pad-byte one-hot
    re-stream of the resident-U pass), rebuilds the packed one-hot tile in
    a VMEM scratch (per-feature iota compare at each feature's static
    packed offset — the "bin" half), and scatter-adds it into the
    VMEM-resident accumulator block through one MXU contraction against
    the node-keyed stat panel (the "scatter-add" half: on TPU a keyed
    scatter IS a one-hot matmul). The accumulator block never leaves VMEM
    until the last tile, and on the quantized path it carries the narrow
    integer dtype picked by ``histogram_acc_dtype``."""
    j2 = lax.broadcasted_iota(jnp.int32, (128, tn), 0)
    leaf = (j2 % k).astype(jnp.float32)
    sidx = j2 // k
    g, h, c = aux_ref[0:1, :], aux_ref[1:2, :], aux_ref[2:3, :]
    nodev = aux_ref[3:4, :]
    val = jnp.where(sidx == 0, g, jnp.where(sidx == 1, h, c))
    panel = jnp.where((nodev == leaf) & (j2 < 3 * k), val, 0.0)  # (128, tn)

    # Bin: packed one-hot tile, one static-offset compare block per
    # feature (row ranges are the USpec layout, so bins >= width match
    # nothing — identical semantics to build_u's local-id compare).
    for j, (off, w) in enumerate(zip(spec.offsets, spec.widths)):
        local = lax.broadcasted_iota(jnp.int32, (w, tn), 0)
        u_scr[off : off + w, :] = (ids_ref[j : j + 1, :] == local).astype(
            jnp.int8
        )
    if spec.k < spec.k_pad:
        u_scr[spec.k :, :] = jnp.zeros((spec.k_pad - spec.k, tn), jnp.int8)

    if quant:
        acc = lax.dot_general(
            u_scr[...], panel.astype(jnp.int8),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
    else:
        acc = lax.dot_general(
            u_scr[...].astype(jnp.bfloat16), panel.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    out_ref[...] += acc.astype(out_ref.dtype)


def build_histograms_bin_scatter(
    bins: jax.Array,  # (N, F) integer bin indices (ORIGINAL layout, no U)
    grad: jax.Array,  # (N,) — ignored when stats is given
    hess: jax.Array,
    count: jax.Array,
    node: jax.Array,  # (N,) int32; out-of-range => row contributes nothing
    num_nodes: int,
    spec,  # ops.u_histogram.USpec (packed row layout)
    *,
    stats=None,  # (3, N) bf16 stat rows, or (stats_i8, scales) quant tuple
    dequant: bool = True,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused bin+scatter-add histogram pass — same contract as
    ``ops.u_histogram.build_histograms_u`` but fed by the RAW binned rows:
    per row the pass streams 4F bytes of bins + 32 bytes of stats instead
    of the K_pad-byte one-hot column of the resident-U formulation (at the
    bench hot shape: 144 B/row vs ~7 KB/row), trading that HBM saving for
    the in-VMEM one-hot rebuild each tile. The A/B against the MXU U-path
    (``benchmarks/hist_u_ab.py``) decides which side of that trade the
    current chip lands on; the pass exists so the answer is measurable.

    Quant path: s8 x s8 MXU scatter into a VMEM accumulator of the narrow
    ``histogram_acc_dtype`` width (int16 when the whole-pass 127 * N bound
    proves it overflow-free, int32 otherwise — deterministic promotion,
    never a runtime saturation). ``dequant=False`` returns the spec-space
    integer histogram for exact sibling subtraction, as in
    ``build_histograms_u``."""
    from mmlspark_tpu.ops.u_histogram import (
        _expand_packed,
        histogram_acc_dtype,
        stat_rows,
    )

    scales = None
    if isinstance(stats, tuple):
        stats, scales = stats
    if 3 * num_nodes > 128:
        raise ValueError(f"panel width 3*{num_nodes} exceeds one lane group")
    k = num_nodes
    n, f = bins.shape
    if not bin_scatter_fits_vmem(spec.k_pad, f):
        raise ValueError(
            f"bin+scatter tile k_pad={spec.k_pad} too large for the VMEM "
            "budget; use the U or compare-built paths"
        )
    if interpret is None:
        interpret = jax.default_backend() not in ("tpu", "axon")
    if stats is None:
        stats = stat_rows(grad, hess, count)
    quant = scales is not None

    tn = _SCATTER_TN
    pad = (-n) % tn
    f_pad = -(-f // _SUBLANES) * _SUBLANES
    ids_t = bins.astype(jnp.int32).T  # (F, N)
    ids_t = jnp.pad(ids_t, ((0, f_pad - f), (0, pad)), constant_values=-1)
    aux = jnp.concatenate(
        [
            stats.astype(jnp.float32),  # quantized values are small ints
            node.astype(jnp.float32)[None, :],
            jnp.zeros((4, n), jnp.float32),
        ]
    )
    if pad:
        aux = jnp.pad(aux, ((0, 0), (0, pad)))
        aux = aux.at[3, n:].set(-1.0)  # pad rows match no leaf
    n_pad = n + pad

    acc_dtype = histogram_acc_dtype(n, quant)
    packed = pl.pallas_call(
        functools.partial(
            _bin_scatter_kernel, k=k, spec=spec, quant=quant, tn=tn
        ),
        grid=(n_pad // tn,),
        in_specs=[
            pl.BlockSpec((f_pad, tn), lambda i: (0, i)),
            pl.BlockSpec((8, tn), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((spec.k_pad, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((spec.k_pad, 128), acc_dtype),
        scratch_shapes=[pltpu.VMEM((spec.k_pad, tn), jnp.int8)],
        interpret=interpret,
    )(ids_t, aux)
    packed = packed[:, : 3 * k]
    if quant and dequant:
        packed = packed.astype(jnp.int32)
    return _expand_packed(packed, scales, spec, k, dequant=dequant)
