"""``shard_map`` version shim.

``jax.shard_map`` graduated out of ``jax.experimental.shard_map`` (and
renamed ``check_rep`` → ``check_vma``) in newer JAX releases; older ones
only ship the experimental spelling. Every explicit-SPMD op routes
through this one wrapper so the rest of the tree can use the modern
surface unconditionally.
"""

from __future__ import annotations

from typing import Any, Callable

import jax


def shard_map(
    f: Callable[..., Any],
    *,
    mesh: Any,
    in_specs: Any,
    out_specs: Any,
    check_vma: bool = True,
) -> Callable[..., Any]:
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
    )
