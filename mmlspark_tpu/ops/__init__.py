"""Compute ops: histograms, hashing, image kernels (XLA + Pallas paths)."""
