"""MurmurHash3 (x86 32-bit) — vectorized host hashing for VW-style featurization.

Re-implements the hashing the reference does JVM-side for performance
(``VowpalWabbitMurmurWithPrefix``, ``vw/VowpalWabbitMurmurWithPrefix.scala``;
Spark-side featurizer hashing in ``vw/VowpalWabbitFeaturizer.scala``):
keeping hashing out of the native hot loop was their "major performance
improvement" (docs/vw.md) — here it runs vectorized in numpy on the host
(C++ drop-in planned; same layout), and only integer indices reach the TPU.

``murmur32_ints`` matches VW's hashing of integer feature indices;
``murmur32_bytes`` hashes utf-8 feature-name strings; a prefix-seeded
variant mirrors the reference's prefix optimization (hash the namespace
once, reuse as seed).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _require_host(values) -> None:
    """Fail fast with an actionable message when a JAX tracer reaches the
    host-only hashing path (graftlint's traced-reachability index keeps
    callers honest statically; this guards the dynamic paths it cannot
    see, e.g. a hash call smuggled in through a callback). Without the
    guard the failure is a TracerArrayConversionError raised from deep
    inside ``np.asarray``. The import stays lazy: this module is
    numpy-only unless JAX types actually show up."""
    if not type(values).__module__.startswith("jax"):
        return
    import jax

    if isinstance(values, jax.core.Tracer):
        raise TypeError(
            "murmur3 hashing is host-side only (SURVEY: hashing stays off "
            "the accelerator; only integer indices reach the TPU) — call "
            "it before jit, or hoist the result as a static input"
        )


def _rotl32(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _mix_k(k: np.ndarray) -> np.ndarray:
    k = (k * _C1).astype(np.uint32)
    k = _rotl32(k, 15)
    return (k * _C2).astype(np.uint32)


def _mix_h(h: np.ndarray, k: np.ndarray) -> np.ndarray:
    h = h ^ k
    h = _rotl32(h, 13)
    return (h * np.uint32(5) + np.uint32(0xE6546B64)).astype(np.uint32)


def _fmix(h: np.ndarray) -> np.ndarray:
    h = h ^ (h >> np.uint32(16))
    h = (h * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h = h ^ (h >> np.uint32(13))
    h = (h * np.uint32(0xC2B2AE35)).astype(np.uint32)
    return h ^ (h >> np.uint32(16))


def murmur32_ints(values: np.ndarray, seed: int = 0) -> np.ndarray:
    """Hash each int32/uint32 value as a 4-byte murmur3 block (VW's
    ``hash_uniform`` over integer feature ids). Dispatches to the host C++
    library when built; vectorized numpy otherwise."""
    _require_host(values)
    from mmlspark_tpu.native import murmur3_ints_native

    native = murmur3_ints_native(np.asarray(values), seed)
    if native is not None:
        return native
    with np.errstate(over="ignore"):
        k = np.asarray(values, dtype=np.uint32)
        h = np.full(k.shape, np.uint32(seed & 0xFFFFFFFF), dtype=np.uint32)
        h = _mix_h(h, _mix_k(k))
        h = h ^ np.uint32(4)  # length
        return _fmix(h)


def murmur32_bytes(data: bytes, seed: int = 0) -> int:
    """Scalar murmur3_x86_32 over a byte string (feature-name hashing).
    Dispatches to the host C++ library when built."""
    from mmlspark_tpu.native import murmur3_bytes_native

    native = murmur3_bytes_native(data, seed)
    if native is not None:
        return native
    with np.errstate(over="ignore"):
        h = np.uint32(seed & 0xFFFFFFFF)
        n = len(data)
        nblocks = n // 4
        for i in range(nblocks):
            k = np.uint32(int.from_bytes(data[4 * i : 4 * i + 4], "little"))
            h = _mix_h(np.asarray(h), _mix_k(np.asarray(k)))
        k = np.uint32(0)
        tail = data[nblocks * 4 :]
        for i, b in enumerate(tail):
            k = k ^ np.uint32(b << (8 * i))
        if tail:
            h = np.asarray(h) ^ _mix_k(np.asarray(k))
        h = np.asarray(h) ^ np.uint32(n)
        return int(_fmix(h))


def murmur32_strings(
    values: Iterable[str], seed: int = 0, cache: Optional[dict] = None
) -> np.ndarray:
    """Hash an iterable of strings (object column). Pass a ``cache`` dict to
    memoize across calls — per-row callers (the featurizer) reuse one cache
    per column so recurring tokens hash once for the whole table."""
    if cache is None:
        cache = {}
    out = []
    for v in values:
        h = cache.get(v)
        if h is None:
            h = murmur32_bytes(str(v).encode("utf-8"), seed)
            cache[v] = h
        out.append(h)
    return np.asarray(out, dtype=np.uint32)


def namespace_seed(namespace: str, seed: int = 0) -> int:
    """Prefix-hash a namespace once and reuse as the seed for its features —
    the ``VowpalWabbitMurmurWithPrefix`` trick."""
    return murmur32_bytes(namespace.encode("utf-8"), seed)


def mask_bits(h: np.ndarray, num_bits: int) -> np.ndarray:
    return (h & np.uint32((1 << num_bits) - 1)).astype(np.int32)
