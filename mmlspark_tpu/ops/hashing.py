"""MurmurHash3 (x86 32-bit) — vectorized host hashing for VW-style featurization.

Re-implements the hashing the reference does JVM-side for performance
(``VowpalWabbitMurmurWithPrefix``, ``vw/VowpalWabbitMurmurWithPrefix.scala``;
Spark-side featurizer hashing in ``vw/VowpalWabbitFeaturizer.scala``):
keeping hashing out of the native hot loop was their "major performance
improvement" (docs/vw.md) — here it runs vectorized in numpy on the host
(C++ drop-in planned; same layout), and only integer indices reach the TPU.

``murmur32_ints`` matches VW's hashing of integer feature indices;
``murmur32_bytes`` hashes utf-8 feature-name strings; a prefix-seeded
variant mirrors the reference's prefix optimization (hash the namespace
once, reuse as seed).
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Sequence

import numpy as np

_C1 = np.uint32(0xCC9E2D51)
_C2 = np.uint32(0x1B873593)


def _require_host(values) -> None:
    """Fail fast with an actionable message when a JAX tracer reaches the
    host-only hashing path (graftlint's traced-reachability index keeps
    callers honest statically; this guards the dynamic paths it cannot
    see, e.g. a hash call smuggled in through a callback). Without the
    guard the failure is a TracerArrayConversionError raised from deep
    inside ``np.asarray``. The import stays lazy: this module is
    numpy-only unless JAX types actually show up."""
    if not type(values).__module__.startswith("jax"):
        return
    import jax

    if isinstance(values, jax.core.Tracer):
        raise TypeError(
            "murmur3 hashing is host-side only (SURVEY: hashing stays off "
            "the accelerator; only integer indices reach the TPU) — call "
            "it before jit, or hoist the result as a static input"
        )


def _rotl32(x: np.ndarray, r: int) -> np.ndarray:
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))


def _mix_k(k: np.ndarray) -> np.ndarray:
    k = (k * _C1).astype(np.uint32)
    k = _rotl32(k, 15)
    return (k * _C2).astype(np.uint32)


def _mix_h(h: np.ndarray, k: np.ndarray) -> np.ndarray:
    h = h ^ k
    h = _rotl32(h, 13)
    return (h * np.uint32(5) + np.uint32(0xE6546B64)).astype(np.uint32)


def _fmix(h: np.ndarray) -> np.ndarray:
    h = h ^ (h >> np.uint32(16))
    h = (h * np.uint32(0x85EBCA6B)).astype(np.uint32)
    h = h ^ (h >> np.uint32(13))
    h = (h * np.uint32(0xC2B2AE35)).astype(np.uint32)
    return h ^ (h >> np.uint32(16))


def _coerce_u32(values) -> np.ndarray:
    """One dtype-coercion rule for integer hashing, shared by the native and
    numpy paths: round toward the int64 grid first, then reinterpret as
    uint32. Without the int64 hop, float inputs hit C float->unsigned
    conversion (undefined for negatives and platform-dependent), so
    ``murmur32_ints(np.zeros(1))`` (float64) and
    ``murmur32_ints(np.zeros(1, np.uint32))`` could diverge between paths."""
    arr = np.asarray(values)
    if arr.dtype == np.uint32:
        return arr
    with np.errstate(over="ignore", invalid="ignore"):
        return arr.astype(np.int64).astype(np.uint32)


def murmur32_ints(values: np.ndarray, seed: int = 0) -> np.ndarray:
    """Hash each int32/uint32 value as a 4-byte murmur3 block (VW's
    ``hash_uniform`` over integer feature ids). Dispatches to the host C++
    library when built; vectorized numpy otherwise."""
    _require_host(values)
    from mmlspark_tpu.native import murmur3_ints_native

    k = _coerce_u32(values)
    native = murmur3_ints_native(k, seed)
    if native is not None:
        return native
    with np.errstate(over="ignore"):
        h = np.full(k.shape, np.uint32(seed & 0xFFFFFFFF), dtype=np.uint32)
        h = _mix_h(h, _mix_k(k))
        h = h ^ np.uint32(4)  # length
        return _fmix(h)


def murmur32_bytes(data: bytes, seed: int = 0) -> int:
    """Scalar murmur3_x86_32 over a byte string (feature-name hashing).
    Dispatches to the host C++ library when built."""
    from mmlspark_tpu.native import murmur3_bytes_native

    native = murmur3_bytes_native(data, seed)
    if native is not None:
        return native
    with np.errstate(over="ignore"):
        h = np.uint32(seed & 0xFFFFFFFF)
        n = len(data)
        nblocks = n // 4
        for i in range(nblocks):
            k = np.uint32(int.from_bytes(data[4 * i : 4 * i + 4], "little"))
            h = _mix_h(np.asarray(h), _mix_k(np.asarray(k)))
        k = np.uint32(0)
        tail = data[nblocks * 4 :]
        for i, b in enumerate(tail):
            k = k ^ np.uint32(b << (8 * i))
        if tail:
            h = np.asarray(h) ^ _mix_k(np.asarray(k))
        h = np.asarray(h) ^ np.uint32(n)
        return int(_fmix(h))


def batch_hash_is_native() -> bool:
    """True when :func:`murmur32_bytes_batch` will dispatch to the C++
    array-of-strings entry — callers use this to decide whether host-side
    token dedup is worth its sort (it never is when the C path is one call)."""
    from mmlspark_tpu.native import load_library

    lib = load_library()
    return lib is not None and getattr(lib, "murmur3_strings_u32", None) is not None


def murmur32_bytes_batch(
    buf: np.ndarray,
    starts: np.ndarray,
    lens: np.ndarray,
    seed: int = 0,
    prefix: bytes = b"",
) -> np.ndarray:
    """murmur3_x86_32 over an ARRAY of byte strings packed in one buffer:
    string i is ``buf[starts[i] : starts[i] + lens[i]]``, with ``prefix``
    virtually prepended to every string (the namespace/column-name prefix —
    never materialized per token). This is the batch entry the VW featurizer
    hashes whole columns through: one native call when the C++ library is
    built, otherwise a vectorized numpy block mixer that walks murmur's
    4-byte blocks across all strings at once — no per-token Python.

    Exactly equal to ``murmur32_bytes(prefix + s, seed)`` for every string.
    """
    from mmlspark_tpu.native import murmur3_strings_native

    starts = np.asarray(starts, dtype=np.int64)
    lens = np.asarray(lens, dtype=np.int64)
    if starts.size == 0:
        return np.zeros(0, dtype=np.uint32)
    native = murmur3_strings_native(buf, starts, lens, seed, prefix)
    if native is not None:
        return native

    buf = np.ascontiguousarray(buf, dtype=np.uint8)
    if buf.size == 0:
        buf = np.zeros(1, dtype=np.uint8)  # keep masked gathers in-bounds
    pre = np.frombuffer(prefix, dtype=np.uint8)
    P = len(prefix)
    last = buf.size - 1
    with np.errstate(over="ignore"):
        total = lens + P
        h = np.full(starts.shape, np.uint32(seed & 0xFFFFFFFF), dtype=np.uint32)
        # Whole 4-byte blocks of prefix+string, one vectorized pass per block
        # position. Position p = 4*b + j is the same scalar for every string,
        # so prefix bytes (p < P) mix in as scalars — only string bytes
        # gather. Strings too short for block b keep their state via where().
        for b in range(int(total.max()) // 4):
            active = total >= 4 * (b + 1)
            if not active.any():
                break
            k = np.zeros(starts.shape, dtype=np.uint32)
            for j in range(4):
                p = 4 * b + j
                if p < P:
                    k |= np.uint32(pre[p]) << np.uint32(8 * j)
                else:
                    g = buf[np.minimum(starts + (p - P), last)]
                    k |= np.where(active, g, 0).astype(np.uint32) << np.uint32(8 * j)
            h = np.where(active, _mix_h(h, _mix_k(k)), h)
        # 1-3 byte tails (per-string tail offsets differ, so prefix bytes can
        # land in a tail too when P % 4 != 0 and the string is short).
        tail_len = (total & 3).astype(np.int64)
        tail_base = total - tail_len
        k = np.zeros(starts.shape, dtype=np.uint32)
        for j in range(3):
            has = tail_len > j
            p = tail_base + j
            g = buf[np.minimum(np.maximum(starts + (p - P), 0), last)]
            if P:
                from_pre = pre[np.minimum(np.maximum(p, 0), P - 1)]
                g = np.where(p < P, from_pre, g)
            k = np.where(has, k ^ (g.astype(np.uint32) << np.uint32(8 * j)), k)
        h = np.where(tail_len > 0, h ^ _mix_k(k), h)
        h = h ^ total.astype(np.uint32)
        return _fmix(h)


def murmur32_strings(
    values: Iterable[str], seed: int = 0, cache: Optional[dict] = None
) -> np.ndarray:
    """Hash an iterable of strings (object column). Pass a ``cache`` dict to
    memoize across calls — per-row callers (the featurizer) reuse one cache
    per column so recurring tokens hash once for the whole table."""
    if cache is None:
        cache = {}
    out = []
    for v in values:
        h = cache.get(v)
        if h is None:
            h = murmur32_bytes(str(v).encode("utf-8"), seed)
            cache[v] = h
        out.append(h)
    return np.asarray(out, dtype=np.uint32)


def namespace_seed(namespace: str, seed: int = 0) -> int:
    """Prefix-hash a namespace once and reuse as the seed for its features —
    the ``VowpalWabbitMurmurWithPrefix`` trick."""
    return murmur32_bytes(namespace.encode("utf-8"), seed)


def mask_bits(h: np.ndarray, num_bits: int) -> np.ndarray:
    # masked values fit in 30 bits (num_bits <= 30), so the int32 reinterpret
    # is free and exact — no astype copy
    return (h & np.uint32((1 << num_bits) - 1)).view(np.int32)
