"""All-to-all (Ulysses-style) sequence-parallel attention.

The second of the two standard long-context layouts (ring attention in
``ops/ring_attention.py`` is the other): instead of rotating K/V blocks
around a ring, ONE ``all_to_all`` re-shards the activations from
sequence-parallel to head-parallel — each device receives the FULL
sequence for H/p of the heads, runs ordinary (flash-style) attention
locally with no inner loop, and a second ``all_to_all`` restores the
sequence sharding.

Trade-offs vs the ring (why both exist):

- a2a moves each activation tensor twice total (2·S/p·H·D per device per
  tensor), independent of p; the ring moves K/V p−1 times. For p ≫ 2 the
  a2a wins on bytes, and both patterns ride ICI.
- a2a needs ``num_heads % p == 0`` (head-parallel inner layout); the ring
  has no head-count constraint and never holds more than an S/p block of
  K/V — a2a materializes (B, S, H/p) activations, so its memory
  high-water mark grows with S while the ring's stays at S/p.
- the ring overlaps communication with compute step by step; a2a is two
  bulk collectives around one big MXU-friendly attention — typically the
  faster choice until S/p attention no longer fits.

API matches :func:`~mmlspark_tpu.ops.ring_attention.ring_attention`:
inputs (B, S, H, D) sharded over the mesh ``seq`` axis.
"""

from __future__ import annotations

from typing import Optional

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from mmlspark_tpu.ops.ring_attention import attention_reference
from mmlspark_tpu.ops.shmap import shard_map
from mmlspark_tpu.parallel.mesh import AXIS_SEQ


def a2a_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh,
    causal: bool = False,
    scale: Optional[float] = None,
):
    """Sequence-parallel attention via head↔sequence all_to_all.

    q/k/v (B, S, H, D) sharded over ``seq``; output identically sharded.
    Requires ``H % p == 0``; falls back to the reference when p == 1."""
    p = int(mesh.shape.get(AXIS_SEQ, 1))
    if p <= 1:
        return attention_reference(q, k, v, causal=causal, scale=scale)
    b, s_global, h, d = q.shape
    if h % p != 0:
        raise ValueError(
            f"a2a attention needs num_heads divisible by the seq axis "
            f"({h} % {p} != 0); use ring_attention for odd head counts"
        )
    if s_global % p != 0:
        raise ValueError(f"sequence {s_global} not divisible by seq axis {p}")

    def local_fn(q_l, k_l, v_l):
        # (B, S/p, H, D) -> all_to_all -> (B, S, H/p, D): scatter the head
        # axis, gather the sequence axis.
        def to_heads(x):
            return lax.all_to_all(
                x, AXIS_SEQ, split_axis=2, concat_axis=1, tiled=True
            )

        def to_seq(x):
            return lax.all_to_all(
                x, AXIS_SEQ, split_axis=1, concat_axis=2, tiled=True
            )

        qh, kh, vh = to_heads(q_l), to_heads(k_l), to_heads(v_l)
        out = attention_reference(qh, kh, vh, causal=causal, scale=scale)
        return to_seq(out)

    from mmlspark_tpu.parallel.mesh import AXIS_DATA

    spec = P(AXIS_DATA if int(mesh.shape.get(AXIS_DATA, 1)) > 1 else None, AXIS_SEQ)
    shard = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return shard(q, k, v)
