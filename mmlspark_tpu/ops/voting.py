"""Voting-parallel histogram reduction (PV-Tree) over the mesh data axis.

LightGBM's ``tree_learner=voting_parallel`` (reference
``lightgbm/LightGBMParams.scala:20-24``, ``topK`` param) cuts the histogram
allreduce from F features to ~topK: each worker *votes* for its locally best
features, the vote is aggregated, and only the winning features' histograms
are globally reduced. The data-parallel reduction moves ``k·F·B·3`` floats
per level; voting moves ``k·F`` vote counts plus ``k·topK·B·3`` floats —
a ~F/topK communication cut when F >> topK.

TPU-native formulation: an explicit ``shard_map`` over the mesh ``data``
axis replaces the worker socket mesh. Local histograms never leave the
device; ``lax.psum`` carries only votes, per-node totals, and the gathered
top-K feature histograms over ICI. The returned histogram has the full
(node, F, B, 3) shape with non-selected features zeroed, so the split
search works unchanged — their zero stats fail the ``min_data_in_leaf``
validity mask and can never win a split.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from mmlspark_tpu.ops.histogram import build_histograms


def _local_feature_gains(hist: jax.Array, l2: float = 1e-3) -> jax.Array:
    """(k, F) best split gain per feature from a LOCAL histogram — the
    voting score. Unregularized apart from a small l2 floor; only the
    *ranking* matters."""
    totals = hist.sum(axis=2)  # (k, F, 3)
    g_tot, h_tot = totals[..., 0], totals[..., 1]
    cum = jnp.cumsum(hist, axis=2)
    gl, hl = cum[..., 0], cum[..., 1]
    gr = g_tot[..., None] - gl
    hr = h_tot[..., None] - hl
    gain = gl * gl / (hl + l2) + gr * gr / (hr + l2)  # (k, F, B)
    return gain.max(axis=2)


def build_histograms_voting(
    bins: jax.Array,  # (N, F) int32
    grad: jax.Array,
    hess: jax.Array,
    count: jax.Array,
    node: jax.Array,
    num_nodes: int,
    num_bins: int,
    *,
    top_k: int = 20,
    mesh=None,
    method: Optional[str] = None,
    feature_mask: Optional[jax.Array] = None,  # (F,) 0/1
) -> Tuple[jax.Array, jax.Array]:
    """Returns (hist (k, F, B, 3) with non-winning features zeroed,
    totals (k, 3) exact). Falls back to the full reduction when unsharded.
    ``feature_mask`` (featureFraction subsampling) excludes features from the
    vote so the K reduced histograms are spent only on splittable features."""
    f = bins.shape[1]
    k_sel = min(top_k, f)

    meshed = mesh is not None and int(mesh.shape.get("data", 1)) > 1
    if not meshed or k_sel == f:
        m = method
        if meshed and m in (None, "pallas"):
            # Under jit with row-sharded inputs pallas_call has no GSPMD
            # partitioning rule — keep the shardable XLA formulations.
            m = "onehot" if jax.default_backend() in ("tpu", "axon") else "segment"
        hist = build_histograms(
            bins, grad, hess, count, node, num_nodes, num_bins, method=m,
            # row chunking must stay off when the N axis is GSPMD-sharded
            # (see build_histograms); the shard_map branch below chunks its
            # LOCAL shards safely
            chunk_rows=not meshed,
        )
        return hist, hist[:, 0, :, :].sum(axis=1)

    def local_fn(bins_l, grad_l, hess_l, count_l, node_l, fmask):
        h = build_histograms(
            bins_l, grad_l, hess_l, count_l, node_l, num_nodes, num_bins,
            method=method,
        )  # LOCAL (k, F, B, 3)
        totals = lax.psum(h[:, 0, :, :].sum(axis=1), "data")  # (k, 3) exact

        # Local vote: top-K features per node by local split gain; masked-out
        # features (featureFraction) may not spend vote slots.
        gains = _local_feature_gains(h)  # (k, F)
        gains = jnp.where(fmask[None, :] > 0, gains, -jnp.inf)
        _, local_top = lax.top_k(gains, k_sel)  # (k, K)
        votes = jnp.zeros((num_nodes, f), dtype=jnp.int32)
        votes = jax.vmap(lambda v, idx: v.at[idx].add(1))(votes, local_top)
        votes = lax.psum(votes, "data")

        # Global winners per node (ties break toward lower feature index).
        score = votes * (f + 1) - jnp.arange(f, dtype=jnp.int32)[None, :]
        _, sel = lax.top_k(score, k_sel)  # (k, K)

        # Reduce ONLY the winners' histograms — the communication saving.
        h_sel = jnp.take_along_axis(h, sel[:, :, None, None], axis=1)
        h_sel = lax.psum(h_sel, "data")  # (k, K, B, 3)

        full = jnp.zeros_like(h)
        full = jax.vmap(lambda fu, si, hs: fu.at[si].set(hs))(full, sel, h_sel)
        return full, totals

    from mmlspark_tpu.ops.shmap import shard_map

    sharded = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(
            P("data", None),
            P("data"),
            P("data"),
            P("data"),
            P("data"),
            P(),  # feature mask replicated
        ),
        out_specs=(P(), P()),
        check_vma=False,
    )
    if feature_mask is None:
        feature_mask = jnp.ones(f, dtype=jnp.float32)
    return sharded(bins, grad, hess, count, node, feature_mask)
