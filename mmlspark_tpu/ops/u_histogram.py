"""Precomputed-U histogram pass: hoist the one-hot build out of the hot loop.

The compare-built histogram kernels (``ops/pallas_histogram.py``) pay the
VPU one-hot construction — the binding resource of the op
(``docs/perf_histogram.md``) — on EVERY pass. But bins are static across a
fit: the one-hot matrix ``U[off_f + b, i] = (bins[i, f] == b)`` can be built
ONCE on device (int8, transposed so rows ride the lane dimension) and every
histogram pass becomes one MXU contraction against the node-keyed stat panel

    hist[col, d] = sum_i U[col, i] * panel[d, i]        (K, 3k) = U @ panelᵀ

an "NT" matmul with BOTH operands' contraction on their lane axis — no
relayout anywhere in the hot loop. That layout discipline is the whole
game on this toolchain: every (N,) -> (N, D) lane-broadcast or f32->int8
convert of row vectors measured 3-5 ms by itself (sublane<->lane shuffles),
as much as the dot. Measured at the bench hot shape (400k x 28 x 256, 8
nodes, v5e): 4.9 ms vs 12.7 ms for the compare-built panel kernel — the
one-hot is s8 (exact 0/1), the panel bf16, f32 accumulation: the IDENTICAL
precision model as the compare-built kernel's default MXU pass, so split
decisions and histogram sums agree in distribution (both: g/h bf16 input
rounding, counts exact).

This is the TPU analogue of the reference engine's bin-major feature
groups (its native dataset also fixes the bin layout once,
``lightgbm/LightGBMUtils.scala:212-239``) — pay the layout once, stream it
every pass.

Feature packing rides in the U row layout: feature f owns rows
``[off_f, off_f + width_f)`` where ``width_f`` is its ACTUAL bin count
(``BinMapper.num_bins``), so K = sum_f width_f, not F * max_bin — on real
datasets with low-cardinality features U (and the HBM re-stream that bounds
the pass) shrinks proportionally. A static (F, max_bin) gather map expands
the packed result back to the dense (k, F, B, 3) histogram the split search
consumes.

Memory: U is fit-resident HBM (K_pad · N_pad bytes as int8). Callers gate
on :func:`u_bytes` — at 400k x 28 x 256 that is ~2.9 GB (fine on 16 GB
v5e), at 4M it would be 29 GB (gate fails, compare-built kernels take
over).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_LANE = 128
_N_ALIGN = 512  # row padding granularity (lane-dim alignment for U tiles)


@dataclasses.dataclass(frozen=True)
class USpec:
    """Static host-side description of the packed one-hot layout (hashable:
    part of the jitted-program cache key)."""

    widths: Tuple[int, ...]  # per-feature bin count (incl. missing bin)
    offsets: Tuple[int, ...]  # per-feature first packed row of U
    k: int  # sum of widths
    k_pad: int  # k rounded up to the sublane block
    num_bins: int  # dense histogram width B the caller expects

    @property
    def num_features(self) -> int:
        return len(self.widths)


def make_u_spec(num_bins: int, num_features: int, per_feature=None) -> USpec:
    """``per_feature`` = BinMapper.num_bins (actual per-feature widths);
    None = uniform ``num_bins`` (no mapper — e.g. pre-binned input)."""
    if per_feature is None:
        widths = [num_bins] * num_features
    else:
        widths = [int(min(max(w, 1), num_bins)) for w in per_feature]
    offsets = np.concatenate([[0], np.cumsum(widths[:-1])]).astype(int)
    k = int(np.sum(widths))
    k_pad = ((k + _LANE - 1) // _LANE) * _LANE
    return USpec(
        widths=tuple(widths), offsets=tuple(int(o) for o in offsets),
        k=k, k_pad=k_pad, num_bins=num_bins,
    )


def u_bytes(n_rows: int, spec: USpec) -> int:
    """Resident HBM cost of the int8 U for ``n_rows`` (pre-padding)."""
    n_pad = ((n_rows + _N_ALIGN - 1) // _N_ALIGN) * _N_ALIGN
    return n_pad * spec.k_pad


@functools.lru_cache(maxsize=64)
def _col_maps_cached(spec: USpec) -> Tuple[np.ndarray, np.ndarray]:
    """Static per-spec column maps: ``feat_of_col[c]`` = feature owning
    packed row c, ``local_of_col[c]`` = c's bin id within that feature
    (-1 on the k..k_pad tail so tail rows match nothing)."""
    feat = np.zeros(spec.k_pad, np.int32)
    local = np.full(spec.k_pad, -1, np.int32)
    for j, (o, w) in enumerate(zip(spec.offsets, spec.widths)):
        feat[o : o + w] = j
        local[o : o + w] = np.arange(w)
    return feat, local


def build_u(bins: jax.Array, spec: USpec, dtype=jnp.int8) -> jax.Array:
    """(K_pad, N_pad) TRANSPOSED one-hot of the packed bin ids — ONE compare
    pass's worth of VPU work (~120 ms at 400k x 28 x 256), paid once per
    fit. The bin axis leads so the pass contraction is lane-on-lane.

    Built by a ``lax.scan`` over 128-row K blocks: trace size is O(1) in
    the feature count (a thousands-of-features dataset must not inflate
    trace/compile time — the original per-feature Python loop did), and
    the per-step gather transient is bounded at 128 x N_pad int32 — the
    single whole-K gather formulation made the TPU compiler itself crash
    at 1M rows (the (K_pad, N_pad) int32 intermediate is tens of GB).
    Pad rows carry bin id -1 and the k..k_pad tail carries local id -1,
    so both contribute nothing."""
    n, f = bins.shape
    pad = (-n) % _N_ALIGN
    ids = bins.astype(jnp.int32)
    if pad:
        ids = jnp.pad(ids, ((0, pad), (0, 0)), constant_values=-1)
    ids_t = ids.T  # (F, N_pad)
    feat_of_col, local_of_col = _col_maps_cached(spec)
    blk = _LANE  # k_pad is always a multiple of the lane block
    fo = jnp.asarray(feat_of_col).reshape(-1, blk)
    lo = jnp.asarray(local_of_col).reshape(-1, blk)

    def block(_, fl):
        fb, lb = fl
        rows = jnp.take(ids_t, fb, axis=0)  # (blk, N_pad)
        return None, (rows == lb[:, None]).astype(dtype)

    _, u = lax.scan(block, None, (fo, lo))
    return u.reshape(spec.k_pad, n + pad)


def _dense_maps(spec: USpec) -> Tuple[np.ndarray, np.ndarray]:
    """(F, B) packed-row gather map + validity mask for expanding the packed
    (K, D) result into the dense (F, B, D) histogram."""
    f, b = spec.num_features, spec.num_bins
    idx = np.zeros((f, b), np.int32)
    mask = np.zeros((f, b), np.float32)
    for j in range(f):
        w = spec.widths[j]
        idx[j, :w] = spec.offsets[j] + np.arange(w)
        mask[j, :w] = 1.0
    return idx, mask


@functools.lru_cache(maxsize=64)
def _dense_maps_cached(spec: USpec):
    return _dense_maps(spec)


def cat_row_maps(spec: USpec, cat_slots) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Static maps for the CATEGORICAL subset of U's packed rows:
    (row ids into U, feature id per row, local bin per row). Restricting
    the membership matmul to these rows streams only the categorical
    features' one-hot block per pass (~Σ cat widths instead of K_pad)."""
    rows, feats, locals_ = [], [], []
    for f_ in sorted(int(s) for s in cat_slots):
        w = spec.widths[f_]
        o = spec.offsets[f_]
        rows.extend(range(o, o + w))
        feats.extend([f_] * w)
        locals_.extend(range(w))
    return (
        np.asarray(rows, np.int32),
        np.asarray(feats, np.int32),
        np.asarray(locals_, np.int32),
    )


def membership_matmul(
    u_rows: jax.Array,  # (Kc, N_pad) int8 — the cat-feature rows of U
    feat_of_row: jax.Array,  # (Kc,) int32 feature id per row
    local_of_row: jax.Array,  # (Kc,) int32 feature-local bin per row
    sf: jax.Array,  # (k,) int32 split feature per leaf
    scm: jax.Array,  # (k, B) bool left-set mask per leaf (feature-local bins)
    n: int,
) -> jax.Array:
    """(k, n) bool: row in leaf jj's categorical left set — ONE standard
    (k, Kc) x (Kc, N) MXU matmul against the categorical rows of the
    fit-resident one-hot instead of per-leaf (N,) gathers (each tiny
    gather costs ~ms of layout round-trip in-context on TPU; measured
    ~35 ms/tree in the leafwise while_loop). Scatter each leaf's mask
    into packed-row space via the static row maps, dot, threshold.
    Numerically exact: the one-hot and the mask are 0/1 in bf16."""
    k = sf.shape[0]
    kc = feat_of_row.shape[0]
    sel = feat_of_row[None, :] == sf[:, None]
    masks = (
        jnp.take_along_axis(
            scm, jnp.broadcast_to(local_of_row[None, :], (k, kc)), axis=1
        )
        & sel
    )  # (k, Kc) — small (no N axis); bins hold feature-local ids
    in_set_f = lax.dot_general(
        masks.astype(jnp.bfloat16), u_rows.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (k, N_pad)
    return in_set_f[:, :n] > 0


def stat_rows(grad: jax.Array, hess: jax.Array, count: jax.Array) -> jax.Array:
    """(3, N) bf16 stat stack [g; h; c] in the row-on-lanes layout the panel
    wants. Node-independent — build it ONCE per tree and reuse across every
    pass of that tree (g/h/c are fixed within a tree)."""
    return jnp.stack(
        [grad, hess, count], axis=0
    ).astype(jnp.bfloat16)


def build_histograms_u(
    u: jax.Array,  # (K_pad, N_pad) int8 from build_u
    grad: jax.Array,  # (N,) — ignored when stats is given
    hess: jax.Array,
    count: jax.Array,
    node: jax.Array,  # (N,) int32; out-of-range => row contributes nothing
    num_nodes: int,
    spec: USpec,
    *,
    stats: Optional[jax.Array] = None,  # (3, N) bf16 from stat_rows()
) -> jax.Array:
    """(num_nodes, F, B, 3) float32 — same contract as
    ``ops.histogram.build_histograms`` but with the one-hot precomputed.

    The per-pass work is: a (3k, N) transposed panel (node-key select over
    the stat rows, built entirely in the row-on-lanes layout) and one
    s8 x bf16 NT matmul. Precision model = the compare-built kernel's
    default MXU pass (bf16 inputs, f32 accumulation; counts exact)."""
    if 3 * num_nodes > _LANE:
        raise ValueError(f"panel width 3*{num_nodes} exceeds one lane group")
    k = num_nodes
    n = node.shape[0]
    n_pad = u.shape[1]

    if stats is None:
        stats = stat_rows(grad, hess, count)
    # (3k, N) stat-major transposed panel: row s*k+j carries stat s for rows
    # whose node key is j, 0 elsewhere. node broadcasts across SUBLANES
    # (cheap); no lane-dim relayout anywhere.
    key = jnp.tile(jnp.arange(k, dtype=jnp.int32), 3)[:, None]  # (3k, 1)
    mask_t = key == node.astype(jnp.int32)[None, :]  # (3k, N)
    vals_t = jnp.repeat(stats, k, axis=0)  # (3k, N) bf16
    panel_t = jnp.where(mask_t, vals_t, jnp.bfloat16(0))
    if n_pad != n:
        panel_t = jnp.pad(panel_t, ((0, 0), (0, n_pad - n)))
    # Materialize: without the barrier XLA re-fuses the panel build into the
    # dot's rhs load and recomputes it per K-tile (measured ~2x slower).
    panel_t = lax.optimization_barrier(panel_t)

    packed = lax.dot_general(
        u.astype(jnp.bfloat16), panel_t,
        (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )  # (K_pad, 3k)

    f, b = spec.num_features, spec.num_bins
    idx, mask = _dense_maps_cached(spec)
    dense = packed[jnp.asarray(idx).reshape(-1)].reshape(f, b, 3 * k)
    dense = dense * jnp.asarray(mask)[:, :, None]
    return dense.reshape(f, b, 3, k).transpose(3, 0, 1, 2)
