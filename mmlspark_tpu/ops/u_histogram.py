"""Precomputed-U histogram pass: hoist the one-hot build out of the hot loop.

The compare-built histogram kernels (``ops/pallas_histogram.py``) pay the
VPU one-hot construction — the binding resource of the op
(``docs/perf_histogram.md``) — on EVERY pass. But bins are static across a
fit: the one-hot matrix ``U[off_f + b, i] = (bins[i, f] == b)`` can be built
ONCE on device (int8, transposed so rows ride the lane dimension) and every
histogram pass becomes one MXU contraction against the node-keyed stat panel

    hist[col, d] = sum_i U[col, i] * panel[d, i]        (K, 3k) = U @ panelᵀ

an "NT" matmul with BOTH operands' contraction on their lane axis — no
relayout anywhere in the hot loop. That layout discipline is the whole
game on this toolchain: every (N,) -> (N, D) lane-broadcast or f32->int8
convert of row vectors measured 3-5 ms by itself (sublane<->lane shuffles),
as much as the dot. Measured at the bench hot shape (400k x 28 x 256, 8
nodes, v5e): 4.9 ms vs 12.7 ms for the compare-built panel kernel — the
one-hot is s8 (exact 0/1), the panel bf16, f32 accumulation: the IDENTICAL
precision model as the compare-built kernel's default MXU pass, so split
decisions and histogram sums agree in distribution (both: g/h bf16 input
rounding, counts exact).

This is the TPU analogue of the reference engine's bin-major feature
groups (its native dataset also fixes the bin layout once,
``lightgbm/LightGBMUtils.scala:212-239``) — pay the layout once, stream it
every pass.

Feature packing rides in the U row layout: feature f owns rows
``[off_f, off_f + width_f)`` where ``width_f`` is its ACTUAL bin count
(``BinMapper.num_bins``), so K = sum_f width_f, not F * max_bin — on real
datasets with low-cardinality features U (and the HBM re-stream that bounds
the pass) shrinks proportionally. A static (F, max_bin) gather map expands
the packed result back to the dense (k, F, B, 3) histogram the split search
consumes.

Memory: U is fit-resident HBM (K_pad · N_pad bytes as int8). Callers gate
on :func:`u_bytes` — at 400k x 28 x 256 that is ~2.9 GB (fine on 16 GB
v5e), at 4M it would be 29 GB (gate fails, compare-built kernels take
over).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_LANE = 128
_N_ALIGN = 512  # row padding granularity (lane-dim alignment for U tiles)


def _ensure_barrier_batching() -> None:
    """Older JAX ships no batching rule for ``optimization_barrier``, and the
    multiclass trainer vmaps over classes straight through the panel build
    (train.py's per-class tree grower). The rule is the identity — pass the
    batched operands through the barrier, keep the batch dims — which is
    exactly what newer releases register; install it when missing."""
    try:
        from jax._src.lax import lax as _lax_internal
        from jax.interpreters import batching

        prim = _lax_internal.optimization_barrier_p
    except (ImportError, AttributeError):
        return
    if prim in batching.primitive_batchers:
        return

    def _rule(batched_args, batch_dims, **params):
        return prim.bind(*batched_args, **params), batch_dims

    batching.primitive_batchers[prim] = _rule


_ensure_barrier_batching()
# Fused Pallas panel+dot pass (MMLSPARK_TPU_U_FUSED=1 opts in). Default
# OFF: measured ~2.5% SLOWER end-to-end than the two-op XLA formulation on
# v5e (XLA's matmul pipeline beats the hand grid even though the fused
# kernel saves the panel's HBM round-trip) — kept env-gated for future
# toolchains and as the correctness-tested template for the fusion.
_FUSED = os.environ.get("MMLSPARK_TPU_U_FUSED", "0") == "1"


@dataclasses.dataclass(frozen=True)
class USpec:
    """Static host-side description of the packed one-hot layout (hashable:
    part of the jitted-program cache key)."""

    widths: Tuple[int, ...]  # per-feature bin count (incl. missing bin)
    offsets: Tuple[int, ...]  # per-feature first packed row of U
    k: int  # sum of widths
    k_pad: int  # k rounded up to the sublane block
    num_bins: int  # dense histogram width B the caller expects
    # 0 = fit-resident U (build_u once, stream it every pass). > 0 = the
    # ROW-CHUNKED pass: no full U is ever materialized — each histogram
    # pass scans ``chunk_rows``-row chunks of the (pre-laid-out) bins,
    # builds that chunk's one-hot in-trace, contracts it against the
    # chunk's stat panel, and accumulates the packed partial histograms
    # (build_histograms_u_chunked). This is how the MXU path survives past
    # the ~1M-row residency cliff: HBM holds one bins copy + O(chunk)
    # transients instead of the full K_pad x N_pad int8 U.
    chunk_rows: int = 0

    @property
    def num_features(self) -> int:
        return len(self.widths)


def make_u_spec(num_bins: int, num_features: int, per_feature=None) -> USpec:
    """``per_feature`` = BinMapper.num_bins (actual per-feature widths);
    None = uniform ``num_bins`` (no mapper — e.g. pre-binned input)."""
    if per_feature is None:
        widths = [num_bins] * num_features
    else:
        widths = [int(min(max(w, 1), num_bins)) for w in per_feature]
    offsets = np.concatenate([[0], np.cumsum(widths[:-1])]).astype(int)
    k = int(np.sum(widths))
    k_pad = ((k + _LANE - 1) // _LANE) * _LANE
    return USpec(
        widths=tuple(widths), offsets=tuple(int(o) for o in offsets),
        k=k, k_pad=k_pad, num_bins=num_bins,
    )


def u_bytes(n_rows: int, spec: USpec) -> int:
    """Resident HBM cost of the int8 U for ``n_rows`` (pre-padding)."""
    n_pad = ((n_rows + _N_ALIGN - 1) // _N_ALIGN) * _N_ALIGN
    return n_pad * spec.k_pad


def chunked_u_spec(n_rows: int, spec: USpec, budget: int) -> USpec:
    """Derive the row-chunked variant of ``spec`` sized to ``budget``
    (MMLSPARK_TPU_U_BUDGET): the per-chunk one-hot transient
    (chunk_rows x k_pad int8) is capped at HALF the budget — the scan
    keeps the current chunk plus the double-buffered next one in flight —
    and chunk_rows stays a multiple of the row-alignment block."""
    per_row = max(1, spec.k_pad)
    target = max(budget // 2, per_row * _N_ALIGN)
    chunk = max(_N_ALIGN, (target // per_row) // _N_ALIGN * _N_ALIGN)
    n_pad = ((n_rows + _N_ALIGN - 1) // _N_ALIGN) * _N_ALIGN
    chunk = min(chunk, n_pad)
    return dataclasses.replace(spec, chunk_rows=int(chunk))


def num_u_chunks(n_rows: int, spec: USpec) -> int:
    """Chunk count of one histogram pass for a chunked spec."""
    if not spec.chunk_rows:
        return 1
    return -(-n_rows // spec.chunk_rows)


def prepare_chunked_bins(bins: jax.Array, spec: USpec) -> jax.Array:
    """One-time per-fit layout for the chunked pass: (N, F) bins →
    (num_chunks, F, chunk_rows) uint8, feature-major within each chunk so
    the in-trace one-hot build gathers rows exactly like :func:`build_u`.
    Pad rows keep bin value 0 — a VALID one-hot column — and are silenced
    by the pass itself (their node key is padded to -1, so their panel
    columns are zero and they contribute nothing)."""
    n, f = bins.shape
    chunk = spec.chunk_rows
    if not chunk:
        raise ValueError("prepare_chunked_bins needs a chunked spec")
    m = -(-n // chunk)
    pad = m * chunk - n
    x = bins.astype(jnp.uint8)
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    return x.reshape(m, chunk, f).transpose(0, 2, 1)


@functools.lru_cache(maxsize=64)
def _col_maps_cached(spec: USpec) -> Tuple[np.ndarray, np.ndarray]:
    """Static per-spec column maps: ``feat_of_col[c]`` = feature owning
    packed row c, ``local_of_col[c]`` = c's bin id within that feature
    (-1 on the k..k_pad tail so tail rows match nothing). Cached as HOST
    numpy (the lru_cache host boundary graftlint understands): callers may
    hit this inside a trace, and a device array built there would be a
    trace-local constant the cache must not retain."""
    feat = np.zeros(spec.k_pad, np.int32)
    local = np.full(spec.k_pad, -1, np.int32)
    for j, (o, w) in enumerate(zip(spec.offsets, spec.widths)):
        feat[o : o + w] = j
        local[o : o + w] = np.arange(w)
    return feat, local


def build_u(bins: jax.Array, spec: USpec, dtype=jnp.int8) -> jax.Array:
    """(K_pad, N_pad) TRANSPOSED one-hot of the packed bin ids — ONE compare
    pass's worth of VPU work (~120 ms at 400k x 28 x 256), paid once per
    fit. The bin axis leads so the pass contraction is lane-on-lane.

    Built by a ``lax.scan`` over 128-row K blocks: trace size is O(1) in
    the feature count (a thousands-of-features dataset must not inflate
    trace/compile time — the original per-feature Python loop did), and
    the per-step gather transient is bounded at 128 x N_pad int32 — the
    single whole-K gather formulation made the TPU compiler itself crash
    at 1M rows (the (K_pad, N_pad) int32 intermediate is tens of GB).
    Pad rows carry bin id -1 and the k..k_pad tail carries local id -1,
    so both contribute nothing."""
    n, f = bins.shape
    pad = (-n) % _N_ALIGN
    ids = bins.astype(jnp.int32)
    if pad:
        ids = jnp.pad(ids, ((0, pad), (0, 0)), constant_values=-1)
    ids_t = ids.T  # (F, N_pad)
    feat_of_col, local_of_col = _col_maps_cached(spec)
    blk = _LANE  # k_pad is always a multiple of the lane block
    fo = feat_of_col.reshape(-1, blk)
    lo = local_of_col.reshape(-1, blk)

    def block(_, fl):
        fb, lb = fl
        rows = jnp.take(ids_t, fb, axis=0)  # (blk, N_pad)
        return None, (rows == lb[:, None]).astype(dtype)

    _, u = lax.scan(block, None, (fo, lo))
    return u.reshape(spec.k_pad, n + pad)


def _dense_maps(spec: USpec) -> Tuple[np.ndarray, np.ndarray]:
    """(F, B) packed-row gather map + validity mask for expanding the packed
    (K, D) result into the dense (F, B, D) histogram."""
    f, b = spec.num_features, spec.num_bins
    idx = np.zeros((f, b), np.int32)
    mask = np.zeros((f, b), np.float32)
    for j in range(f):
        w = spec.widths[j]
        idx[j, :w] = spec.offsets[j] + np.arange(w)
        mask[j, :w] = 1.0
    return idx, mask


@functools.lru_cache(maxsize=64)
def _dense_maps_cached(spec: USpec) -> Tuple[np.ndarray, np.ndarray]:
    # Cached as HOST numpy; see _col_maps_cached.
    return _dense_maps(spec)


def cat_row_maps(spec: USpec, cat_slots) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Static maps for the CATEGORICAL subset of U's packed rows:
    (row ids into U, feature id per row, local bin per row). Restricting
    the membership matmul to these rows streams only the categorical
    features' one-hot block per pass (~Σ cat widths instead of K_pad)."""
    rows, feats, locals_ = [], [], []
    for f_ in sorted(int(s) for s in cat_slots):
        w = spec.widths[f_]
        o = spec.offsets[f_]
        rows.extend(range(o, o + w))
        feats.extend([f_] * w)
        locals_.extend(range(w))
    return (
        np.asarray(rows, np.int32),
        np.asarray(feats, np.int32),
        np.asarray(locals_, np.int32),
    )


def membership_matmul(
    u_rows: jax.Array,  # (Kc, N_pad) int8 — the cat-feature rows of U
    feat_of_row: jax.Array,  # (Kc,) int32 feature id per row
    local_of_row: jax.Array,  # (Kc,) int32 feature-local bin per row
    sf: jax.Array,  # (k,) int32 split feature per leaf
    scm: jax.Array,  # (k, B) bool left-set mask per leaf (feature-local bins)
    n: int,
) -> jax.Array:
    """(k, n) bool: row in leaf jj's categorical left set — ONE standard
    (k, Kc) x (Kc, N) MXU matmul against the categorical rows of the
    fit-resident one-hot instead of per-leaf (N,) gathers (each tiny
    gather costs ~ms of layout round-trip in-context on TPU; measured
    ~35 ms/tree in the leafwise while_loop). Scatter each leaf's mask
    into packed-row space via the static row maps, dot, threshold.
    Numerically exact: the one-hot and the mask are 0/1 in bf16."""
    k = sf.shape[0]
    kc = feat_of_row.shape[0]
    sel = feat_of_row[None, :] == sf[:, None]
    masks = (
        jnp.take_along_axis(
            scm, jnp.broadcast_to(local_of_row[None, :], (k, kc)), axis=1
        )
        & sel
    )  # (k, Kc) — small (no N axis); bins hold feature-local ids
    in_set_f = lax.dot_general(
        masks.astype(jnp.bfloat16), u_rows.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (k, N_pad)
    return in_set_f[:, :n] > 0


def stat_rows(grad: jax.Array, hess: jax.Array, count: jax.Array) -> jax.Array:
    """(3, N) bf16 stat stack [g; h; c] in the row-on-lanes layout the panel
    wants. Node-independent — build it ONCE per tree and reuse across every
    pass of that tree (g/h/c are fixed within a tree)."""
    return jnp.stack(
        [grad, hess, count], axis=0
    ).astype(jnp.bfloat16)


def stat_rows_quant(
    grad: jax.Array, hess: jax.Array, count: jax.Array, key: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """8-bit stochastically-rounded stat rows + dequant scales — LightGBM's
    gradient-quantization training (``use_quantized_grad``: its engine
    discretizes g/h onto a small symmetric grid with stochastic rounding so
    histogram accumulation rides the integer SIMD/MXU path; here the whole
    U pass becomes one s8 x s8 MXU contraction at 2x the int ops/cycle of
    the bf16 path and a narrower panel stream). 127-level symmetric grid
    per tree: x_q = floor(x * 127/max|x| + u), u ~ U[0,1) — unbiased
    (E[x_q] = x * 127/max|x|), so per-bin SUMS are unbiased estimators and
    split gains converge to the exact ones at histogram row counts. Counts
    are 0/1 and stay exact. Returns ((3, N) int8 [g_q; h_q; c],
    (3,) f32 per-stat dequant scales [gs/127, hs/127, 1])."""
    g = grad.astype(jnp.float32)
    h = hess.astype(jnp.float32)
    gs = jnp.maximum(jnp.max(jnp.abs(g)), jnp.float32(1e-30))
    hs = jnp.maximum(jnp.max(jnp.abs(h)), jnp.float32(1e-30))
    kg, kh = jax.random.split(key)

    def q(x, s, kk):
        u = jax.random.uniform(kk, x.shape, dtype=jnp.float32)
        return jnp.clip(
            jnp.floor(x * (127.0 / s) + u), -127, 127
        ).astype(jnp.int8)

    stats = jnp.stack([q(g, gs, kg), q(h, hs, kh), count.astype(jnp.int8)])
    scales = jnp.stack([gs / 127.0, hs / 127.0, jnp.float32(1.0)])
    return stats, scales


def histogram_acc_dtype(n_rows: int, quant: bool):
    """Narrowest histogram-accumulator dtype that is provably overflow-free
    for ``n_rows`` — the deterministic promotion rule of the quantized
    path's packed accumulators (LightGBM's quantized training picks
    per-leaf hist bit widths the same way, from a bound on rows x grad
    range; here the bound is static per fit so the choice is part of the
    compiled program, never a runtime saturation check).

    Quantized stats are 127-level ints, so any per-bin partial sum is
    bounded by ``127 * n_rows`` (counts are 0/1 and bounded by ``n_rows``
    alone): int16 when that fits, else int32 — still exact integer sums
    either way, just wider. The f32 path keeps f32 (its sums are not
    integer, so narrowing would change results)."""
    if not quant:
        return jnp.float32
    if 127 * n_rows <= np.iinfo(np.int16).max:
        return jnp.int16
    return jnp.int32


def k_pad_fits_vmem(k_pad: int) -> bool:
    """Fused-pass VMEM gate: 2 U blocks (k_pad x 512 s8) + accumulator
    (k_pad x 128 s32) must sit comfortably in VMEM (~24 MB budget)."""
    return k_pad * (2 * _N_ALIGN + 4 * _LANE) <= (24 << 20)


def _fused_panel_dot(
    u: jax.Array,  # (K_pad, N_pad) int8
    aux: jax.Array,  # (8, N_pad) f32: rows [g, h, c, node, 0, 0, 0, 0]
    k: int,
    quant: bool,
    interpret: bool = False,
) -> jax.Array:
    """One Pallas pass fusing the panel build into the U contraction.

    The two-op XLA formulation materializes the (3k, N) panel to HBM
    behind an optimization barrier (without it XLA re-fuses the build into
    the dot's rhs load and recomputes it per K-tile — measured 2x slower).
    This kernel gets the best of both: each N-tile's panel is built ONCE
    in VMEM from the node keys + stat rows and consumed immediately by the
    MXU, so the pass streams exactly U + 32 f32 bytes/row of aux — no
    panel round-trip, no per-K-tile recompute. The output block
    (K_pad, 128) stays VMEM-resident across the whole N grid and
    accumulates (int32 exact for the quantized path, f32 otherwise).

    Panel row j carries stat j//k for rows whose node key equals j%k —
    the same (3k, N) layout the XLA path uses, padded to the full 128-lane
    group (rows 3k..127 are zero; callers slice)."""
    k_pad, n_pad = u.shape
    tn = _N_ALIGN
    out_dtype = jnp.int32 if quant else jnp.float32

    def kern(aux_ref, u_ref, out_ref):
        from jax.experimental import pallas as pl  # local: optional dep path

        j = lax.broadcasted_iota(jnp.int32, (_LANE, tn), 0)
        leaf = (j % k).astype(jnp.float32)
        sidx = j // k
        g, h, c = aux_ref[0:1, :], aux_ref[1:2, :], aux_ref[2:3, :]
        nodev = aux_ref[3:4, :]
        val = jnp.where(sidx == 0, g, jnp.where(sidx == 1, h, c))
        panel = jnp.where((nodev == leaf) & (j < 3 * k), val, 0.0)  # (128, tn)
        if quant:
            acc = lax.dot_general(
                u_ref[...], panel.astype(jnp.int8),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
        else:
            acc = lax.dot_general(
                u_ref[...].astype(jnp.bfloat16), panel.astype(jnp.bfloat16),
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        @pl.when(pl.program_id(0) == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)

        out_ref[...] += acc

    from jax.experimental import pallas as pl

    return pl.pallas_call(
        kern,
        grid=(n_pad // tn,),
        in_specs=[
            pl.BlockSpec((8, tn), lambda i: (0, i)),
            pl.BlockSpec((k_pad, tn), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((k_pad, _LANE), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((k_pad, _LANE), out_dtype),
        interpret=interpret,
    )(aux, u)


def build_histograms_u(
    u: jax.Array,  # (K_pad, N_pad) int8 from build_u
    grad: jax.Array,  # (N,) — ignored when stats is given
    hess: jax.Array,
    count: jax.Array,
    node: jax.Array,  # (N,) int32; out-of-range => row contributes nothing
    num_nodes: int,
    spec: USpec,
    *,
    stats=None,  # (3, N) bf16 from stat_rows(), or (stats_i8, scales) quant
    dequant: bool = True,
) -> jax.Array:
    """(num_nodes, F, B, 3) float32 — same contract as
    ``ops.histogram.build_histograms`` but with the one-hot precomputed.

    The per-pass work is: a (3k, N) transposed panel (node-key select over
    the stat rows, built entirely in the row-on-lanes layout) and one
    s8 x bf16 NT matmul. Precision model = the compare-built kernel's
    default MXU pass (bf16 inputs, f32 accumulation; counts exact).

    When ``stats`` is a ``stat_rows_quant`` tuple the pass runs entirely in
    int8 (s8 x s8 MXU, s32 accumulation — exact integer sums of the
    quantized per-row values) and the packed result is dequantized by the
    per-stat scales; counts stay bit-exact either way. ``dequant=False``
    keeps the quant result in the narrowest provably overflow-free integer
    dtype (:func:`histogram_acc_dtype`) so the caller can do exact integer
    sibling subtraction before applying the scales (:func:`dequant_hist`)."""
    scales = None
    if isinstance(stats, tuple):
        stats, scales = stats
    if 3 * num_nodes > _LANE:
        raise ValueError(f"panel width 3*{num_nodes} exceeds one lane group")
    k = num_nodes
    n = node.shape[0]
    n_pad = u.shape[1]

    if stats is None:
        stats = stat_rows(grad, hess, count)

    # VMEM residency: two double-buffered U blocks + the accumulator block
    # ≈ k_pad * 1.5 KB; gate well under v5e's VMEM so wide-K datasets
    # (thousands of packed bins) fall back to the two-op XLA pass.
    if (
        _FUSED
        and k_pad_fits_vmem(u.shape[0])
        and jax.default_backend() in ("tpu", "axon")
    ):
        # Fused Pallas pass: panel built per N-tile in VMEM, no HBM
        # round-trip (docstring of _fused_panel_dot).
        aux = jnp.concatenate(
            [
                stats.astype(jnp.float32),  # quantized values are small ints
                node.astype(jnp.float32)[None, :],
                jnp.zeros((4, n), jnp.float32),
            ]
        )
        if n_pad != n:
            # pad node lane with -1 (matches no leaf); stat lanes with 0
            aux = jnp.pad(aux, ((0, 0), (0, n_pad - n)))
            aux = aux.at[3, n:].set(-1.0)
        packed = _fused_panel_dot(u, aux, k, quant=scales is not None)
        packed = packed[:, : 3 * k]
    else:
        panel_t = _stat_panel_t(stats, node, k, n_pad)
        if scales is not None:
            packed = lax.dot_general(
                u, panel_t,
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.int32,
            )  # (K_pad, 3k) exact int sums of quantized stats
        else:
            packed = lax.dot_general(
                u.astype(jnp.bfloat16), panel_t,
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            )  # (K_pad, 3k)

    if scales is not None and not dequant:
        # narrow to the statically overflow-free accumulator width (exact:
        # MXU accumulation is s32; the downcast is lossless under the
        # 127 * n_rows bound histogram_acc_dtype derives from)
        packed = packed.astype(histogram_acc_dtype(n, quant=True))
    return _expand_packed(packed, scales, spec, k, dequant=dequant)


def _stat_panel_t(
    stats: jax.Array,  # (3, N) bf16 | int8
    node: jax.Array,  # (N,)
    k: int,
    n_pad: int,
) -> jax.Array:
    """(3k, N_pad) stat-major transposed panel: row s*k+j carries stat s
    for rows whose node key is j, 0 elsewhere. node broadcasts across
    SUBLANES (cheap); no lane-dim relayout anywhere. Materialized behind
    an optimization barrier: without it XLA re-fuses the panel build into
    the dot's rhs load and recomputes it per K-tile (~2x slower)."""
    n = node.shape[0]
    key = jnp.tile(jnp.arange(k, dtype=jnp.int32), 3)[:, None]  # (3k, 1)
    mask_t = key == node.astype(jnp.int32)[None, :]  # (3k, N)
    zero = jnp.int8(0) if stats.dtype == jnp.int8 else jnp.bfloat16(0)
    vals_t = jnp.repeat(stats, k, axis=0)  # (3k, N) bf16 | int8
    panel_t = jnp.where(mask_t, vals_t, zero)
    if n_pad != n:
        panel_t = jnp.pad(panel_t, ((0, 0), (0, n_pad - n)))
    return lax.optimization_barrier(panel_t)


def _expand_packed(
    packed: jax.Array, scales, spec: USpec, k: int, dequant: bool = True
) -> jax.Array:
    """Shared pass tail: dequantize (quant path — row s*k+j carries stat
    s, so the (3, k) reshape broadcasts each stat's scale over its k node
    columns) and expand the packed (K_pad, 3k) result to the dense
    (k, F, B, 3) histogram via the static gather maps.

    ``dequant=False`` DEFERS the scale multiply: the gather expansion runs
    in the packed integer domain and the result keeps the accumulator
    dtype, so callers (the sibling-subtraction cache in the leafwise
    grower) can subtract parent - child as exact integer sums and apply
    the scales once, after subtraction — the subtracted sibling is then
    bit-identical to a directly built one."""
    if scales is not None and dequant:
        packed = (
            packed.reshape(-1, 3, k).astype(jnp.float32)
            * scales[None, :, None]
        ).reshape(-1, 3 * k)
    f, b = spec.num_features, spec.num_bins
    idx, mask = _dense_maps_cached(spec)
    dense = packed[idx.reshape(-1)].reshape(f, b, 3 * k)
    dense = dense * jnp.asarray(mask).astype(dense.dtype)[:, :, None]
    return dense.reshape(f, b, 3, k).transpose(3, 0, 1, 2)


def dequant_hist(h: jax.Array, scales: jax.Array) -> jax.Array:
    """Apply the deferred per-stat dequant scales to a spec-space histogram
    built with ``dequant=False`` (last axis = [g, h, c] — matches the (3,)
    scale stack from :func:`stat_rows_quant`)."""
    return h.astype(jnp.float32) * scales


def build_histograms_u_chunked(
    bins_chunks: jax.Array,  # (m, F, chunk) uint8 from prepare_chunked_bins
    grad: jax.Array,  # (N,) — ignored when stats is given
    hess: jax.Array,
    count: jax.Array,
    node: jax.Array,  # (N,) int32; out-of-range => row contributes nothing
    num_nodes: int,
    spec: USpec,  # chunked (spec.chunk_rows > 0)
    *,
    stats=None,  # (3, N) bf16 from stat_rows(), or (stats_i8, scales) quant
    dequant: bool = True,
) -> jax.Array:
    """Row-chunked variant of :func:`build_histograms_u` — same contract,
    same precision model, but NO fit-resident U: a ``lax.scan`` walks the
    pre-laid-out bins chunks, rebuilds each chunk's one-hot in-trace (the
    same 128-row K-block gather loop as :func:`build_u`), contracts it
    against the chunk's stat panel, and accumulates the packed (K_pad, 3k)
    partial histograms — int32 (exact) on the quantized path, f32
    otherwise (partial-sum association differs from the resident pass only
    within f32 rounding, the precision the compare-built kernels already
    carry). The scan's sequential chunks let XLA double-buffer the next
    chunk's bins stream behind the current contraction, so past the
    residency cliff the pass stays MXU-bound instead of falling back to
    the compare-built slow path.

    Pad rows (the m*chunk - N tail) carry bin 0 — a valid one-hot column —
    but their node key is padded to -1, so their panel columns are zero
    and they contribute nothing, exactly like build_u's -1 pad rows."""
    scales = None
    if isinstance(stats, tuple):
        stats, scales = stats
    if 3 * num_nodes > _LANE:
        raise ValueError(f"panel width 3*{num_nodes} exceeds one lane group")
    k = num_nodes
    m, _, chunk = bins_chunks.shape
    n = node.shape[0]
    if stats is None:
        stats = stat_rows(grad, hess, count)
    quant = scales is not None

    total = m * chunk
    node_p = node.astype(jnp.int32)
    if total != n:
        node_p = jnp.pad(node_p, (0, total - n), constant_values=-1)
        stats = jnp.pad(stats, ((0, 0), (0, total - n)))
    node_c = node_p.reshape(m, chunk)
    stats_c = stats.reshape(3, m, chunk).transpose(1, 0, 2)  # (m, 3, chunk)

    feat_of_col, local_of_col = _col_maps_cached(spec)
    fo = feat_of_col.reshape(-1, _LANE)
    lo = local_of_col.reshape(-1, _LANE)

    def chunk_step(acc, xs):
        ids_t, nd, st = xs  # (F, chunk) u8, (chunk,) i32, (3, chunk)
        ids32 = ids_t.astype(jnp.int32)

        def block(_, fl):
            fb, lb = fl
            rows = jnp.take(ids32, fb, axis=0)  # (128, chunk)
            return None, (rows == lb[:, None]).astype(jnp.int8)

        _, u_c = lax.scan(block, None, (fo, lo))
        u_c = u_c.reshape(spec.k_pad, chunk)
        panel_t = _stat_panel_t(st, nd, k, chunk)
        if quant:
            part = lax.dot_general(
                u_c, panel_t,
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.int32,
            )
        else:
            part = lax.dot_general(
                u_c.astype(jnp.bfloat16), panel_t,
                (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            )
        return acc + part.astype(acc.dtype), None

    # The scan CARRY is the pass's HBM-resident accumulator — on the quant
    # path it narrows to the statically overflow-free integer width (the
    # per-chunk MXU partial is s32, downcast exact under the whole-pass
    # 127 * n_rows bound, which dominates every chunk partial).
    acc0 = jnp.zeros((spec.k_pad, 3 * k), histogram_acc_dtype(n, quant))
    packed, _ = lax.scan(chunk_step, acc0, (bins_chunks, node_c, stats_c))
    return _expand_packed(packed, scales, spec, k, dequant=dequant)
