"""TrainValidSweep — the many-models training plane's estimator surface.

A train/validation-split hyperparameter sweep that trains *many small
models in one compiled program*: candidates come from the existing
:mod:`mmlspark_tpu.automl.hyperparam` spaces (``GridSpace`` /
``RandomSpace`` / raw ``Dist`` dicts), partition into shape-buckets
(:mod:`mmlspark_tpu.sweep.bucketing`), and each bucket fits K-at-once
through the vmapped cores (:mod:`mmlspark_tpu.sweep.batched`). The best
candidate by validation metric is refit on the FULL table — so the
committed model is byte-identical to a standalone fit with the winning
params — and committed through
:class:`~mmlspark_tpu.runtime.journal.ModelStore` (versioned, CRC,
hot-swappable by the serving fleet).

With ``numProcesses`` > 1 the buckets shard across a supervised
:class:`~mmlspark_tpu.runtime.procgroup.ProcessGroup` gang
(:mod:`mmlspark_tpu.sweep.distributed`): task-per-bucket, per-bucket
journal resume, and a SIGKILL'd worker cannot change the selected model.

Observability: ``SweepStarted`` / ``CandidateBatchFitted`` /
``SweepCompleted`` events plus ``sweep_*`` registry metrics.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Tuple

import numpy as np

from mmlspark_tpu.automl.tune import _METRICS
from mmlspark_tpu.core.params import HasLabelCol, Param, gt, to_bool, to_float, to_int, to_str
from mmlspark_tpu.core.pipeline import Estimator, Model
from mmlspark_tpu.data.table import Table


def _model_text(model) -> str:
    """Serialized form committed to the ModelStore: booster text for tree
    models, a JSON weight record for linear ones."""
    if hasattr(model, "get_model_string"):
        return model.get_model_string()
    if hasattr(model, "getModelWeights"):
        w = np.asarray(model.getModelWeights(), dtype=np.float32)
        return json.dumps({
            "type": type(model).__name__,
            "weights": [float(x) for x in w],
        })
    raise TypeError(f"cannot serialize {type(model).__name__} for commit")


class TrainValidSweep(HasLabelCol, Estimator):
    """Batched train/validation hyperparameter sweep with best-model
    commit. The batch-of-models analogue of ``TuneHyperparameters``:
    one split instead of k folds, shape-bucketed vmapped fits instead of
    a candidate-at-a-time thread pool."""

    estimator = Param("Estimator to sweep", is_complex=True)
    paramSpace = Param(
        "GridSpace / RandomSpace / {param: Dist} candidate source",
        is_complex=True, default=None,
    )
    paramMaps = Param(
        "Explicit candidate param maps (overrides paramSpace)",
        is_complex=True, default=None,
    )
    evaluationMetric = Param(
        "Metric name", default="accuracy", converter=to_str,
        validator=lambda v: v in _METRICS,
    )
    trainRatio = Param(
        "Fraction of rows in the training split", default=0.75,
        converter=to_float, validator=lambda v: 0.0 < v < 1.0,
    )
    numRuns = Param("Sampled param maps (random spaces)", default=10,
                    converter=to_int, validator=gt(0))
    seed = Param("RNG seed (sampling + split)", default=0, converter=to_int)
    numProcesses = Param(
        "Shard buckets across a worker gang when > 1", default=0,
        converter=to_int,
    )
    commitModel = Param(
        "Commit the refit best model to the ModelStore", default=True,
        converter=to_bool,
    )

    def _candidates(self) -> List[Tuple[Estimator, Dict[str, Any]]]:
        est = self.getEstimator()
        if est is None:
            raise ValueError("no estimator to sweep")
        maps: List[Dict[str, Any]]
        explicit = self.getParamMaps()
        space = self.getParamSpace()
        if explicit:
            maps = [dict(m) for m in explicit]
        elif space is None:
            maps = [{}]
        elif hasattr(space, "param_maps"):
            from mmlspark_tpu.automl.hyperparam import GridSpace

            if isinstance(space, GridSpace):
                maps = list(space.param_maps())
            else:
                maps = list(space.param_maps(self.getNumRuns()))
        elif isinstance(space, dict) and space and all(
            hasattr(d, "get_next") for d in space.values()
        ):
            rng = np.random.default_rng(self.getSeed())
            maps = [
                {k: d.get_next(rng) for k, d in space.items()}
                for _ in range(self.getNumRuns())
            ]
        else:
            raise ValueError(
                "paramSpace must be a GridSpace/RandomSpace or a dict of "
                f"Dists, got {type(space).__name__}"
            )
        if not maps:
            raise ValueError("candidate space is empty")
        return [(est, m) for m in maps]

    def _split(self, n: int) -> np.ndarray:
        """Seeded boolean train mask (row order preserved; the complement
        is the validation split). Always leaves >=1 row on each side."""
        if n < 2:
            raise ValueError(f"{n} rows cannot split train/valid")
        rng = np.random.default_rng(self.getSeed())
        perm = rng.permutation(n)
        n_train = min(max(int(round(n * self.getTrainRatio())), 1), n - 1)
        mask = np.zeros(n, dtype=bool)
        mask[perm[:n_train]] = True
        return mask

    def _fit(self, table: Table) -> "TrainValidSweepModel":
        from mmlspark_tpu.automl.tune import _is_larger_better
        from mmlspark_tpu.observability import (
            SweepCompleted,
            SweepStarted,
            get_bus,
            get_registry,
        )
        from mmlspark_tpu.sweep.batched import fit_bucket
        from mmlspark_tpu.sweep.bucketing import bucket_candidates

        t0 = time.perf_counter()
        label_col = self.getLabelCol()
        metric = self.getEvaluationMetric()
        candidates = self._candidates()
        buckets = bucket_candidates(candidates)
        num_processes = self.getNumProcesses()
        mode = "gang" if num_processes > 1 else "inline"

        bus = get_bus()
        if bus.active:
            bus.publish(SweepStarted(
                candidates=len(candidates), buckets=len(buckets),
                estimator=type(self.getEstimator()).__name__, mode=mode,
            ))
        reg = get_registry()
        reg.counter(
            "sweep_candidates_total", "Candidates entering sweeps"
        ).inc(len(candidates))
        reg.gauge(
            "sweep_buckets", "Shape-buckets in the last sweep"
        ).set(len(buckets))

        mask = self._split(table.num_rows)
        train, valid = table.filter(mask), table.filter(~mask)

        metrics: List[float] = [float("nan")] * len(candidates)
        if mode == "gang":
            from mmlspark_tpu.sweep.distributed import run_sweep_process_group

            metrics = run_sweep_process_group(
                self.getEstimator(), buckets, table, mask, label_col,
                metric, num_processes,
                num_candidates=len(candidates),
                seed=self.getSeed(),
                group_options=getattr(self, "_group_options", None),
                owner=self,
            )
        else:
            for bi, bucket in enumerate(buckets):
                scored = fit_bucket(
                    bucket, train, valid, label_col, metric, bucket_index=bi,
                )
                for pos, idx in enumerate(bucket.indices):
                    metrics[idx] = scored[pos][0]

        higher = _is_larger_better(metric)
        metrics_arr = np.asarray(metrics, dtype=np.float64)
        if np.isnan(metrics_arr).all():
            raise ValueError(
                "all candidate metrics are NaN — check split/label distribution"
            )
        ranked = np.where(
            np.isnan(metrics_arr), -np.inf if higher else np.inf, metrics_arr
        )
        best_i = int(np.argmax(ranked) if higher else np.argmin(ranked))
        best_est, best_params = candidates[best_i]

        # refit on the FULL table: the committed model is what a standalone
        # fit with the winning params would produce, byte for byte
        best_model = best_est.copy(best_params).fit(table)

        version = -1
        if self.getCommitModel():
            from mmlspark_tpu.runtime.journal import (
                ModelStore,
                default_checkpoint_dir,
            )

            ckpt_root = default_checkpoint_dir()
            if ckpt_root is not None:
                import os

                store = ModelStore(os.path.join(ckpt_root, "models"))
                version = store.commit(
                    _model_text(best_model),
                    name=f"sweep-{type(best_model).__name__.lower()}",
                )

        elapsed = time.perf_counter() - t0
        reg.gauge(
            "sweep_best_metric", "Winning validation metric of the last sweep"
        ).set(float(metrics[best_i]))
        reg.counter("sweep_runs_total", "Completed sweeps").inc()
        if bus.active:
            bus.publish(SweepCompleted(
                candidates=len(candidates), best_index=best_i,
                best_metric=float(metrics[best_i]), version=version,
                seconds=elapsed,
            ))

        model = TrainValidSweepModel(
            bestModel=best_model,
            bestParams=dict(best_params),
            bestMetric=float(metrics[best_i]),
            allMetrics=[float(m) for m in metrics],
            modelVersion=version,
        )
        model.parent = self
        return model


class TrainValidSweepModel(Model):
    bestModel = Param("Winning refit model", is_complex=True, default=None)
    bestParams = Param("Winning param map", default=None)
    bestMetric = Param("Winning validation metric", default=float("nan"))
    allMetrics = Param("Validation metric per candidate", default=None)
    modelVersion = Param("ModelStore version of the committed best model "
                         "(-1 = not committed)", default=-1, converter=to_int)

    def transform(self, table: Table) -> Table:
        return self.getBestModel().transform(table)

    def leaderboard(self) -> Table:
        """Candidates ranked best-first: (rank, candidate index, metric)."""
        from mmlspark_tpu.automl.tune import _is_larger_better

        metrics = np.asarray(self.getAllMetrics() or [], dtype=np.float64)
        higher = (
            _is_larger_better(self.parent.getEvaluationMetric())
            if self.parent is not None else True
        )
        ranked = np.where(np.isnan(metrics), -np.inf if higher else np.inf,
                          metrics)
        order = np.argsort(-ranked if higher else ranked, kind="stable")
        return Table({
            "rank": np.arange(len(order), dtype=np.int64),
            "candidate": order.astype(np.int64),
            "metric": metrics[order],
        })
