"""mmlspark_tpu.sweep — the many-models training plane.

Where Spark parallelizes model search across executors, XLA can train
many small models in ONE compiled program: candidates that share static
shapes batch over a vmapped candidate axis, heterogeneous grids partition
into shape-buckets, and each bucket amortizes a single compile. See
``docs/automl_sweep.md`` for the bucketing rules and
:class:`TrainValidSweep` for the estimator surface.
"""

from mmlspark_tpu.sweep.batched import cv_metrics_batched, fit_bucket
from mmlspark_tpu.sweep.bucketing import (
    GBDT_VMAPPED,
    VW_VMAPPED,
    CandidateBucket,
    bucket_candidates,
)
from mmlspark_tpu.sweep.estimator import TrainValidSweep, TrainValidSweepModel

__all__ = [
    "CandidateBucket",
    "GBDT_VMAPPED",
    "TrainValidSweep",
    "TrainValidSweepModel",
    "VW_VMAPPED",
    "bucket_candidates",
    "cv_metrics_batched",
    "fit_bucket",
]
