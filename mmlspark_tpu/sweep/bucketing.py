"""Shape-bucketing: which sweep candidates can share ONE compiled program.

The many-models plane batches candidates over a vmapped candidate axis
(:func:`mmlspark_tpu.lightgbm.train.train_many`,
:func:`mmlspark_tpu.vw.base.train_linear_many`). Two candidates can ride
the same program only when every *program-shaping* option agrees —
``numLeaves`` changes tree-array shapes, ``numIterations`` changes the
scan length, the objective changes the kernel — while the *traced* lanes
(learning rate, bagging/feature fractions for GBDT; learning rate,
``powerT``, ``l1``, ``l2`` for VW) ride as per-candidate array inputs.

:func:`bucket_candidates` partitions a candidate list into
:class:`CandidateBucket` groups by that rule: candidates whose param maps
differ only in vmapped params share a bucket (one compile, K models);
everything else — heterogeneous statics, non-batchable estimators,
option surfaces the batched cores exclude — lands in singleton buckets
fitted through the ordinary ``estimator.fit`` path. Bucketing is
deterministic (first-seen order) so the gang scheduler can shard buckets
across processes by index.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from mmlspark_tpu.core.pipeline import Estimator

#: Estimator param names the GBDT batched core vmaps over (traced lanes).
#: Mirrors ``lightgbm.train.MANY_VMAPPED_FIELDS`` in estimator-param space.
GBDT_VMAPPED = frozenset({
    "learningRate",
    "featureFraction",
    "baggingFraction",
    "baggingFreq",
    "posBaggingFraction",
    "negBaggingFraction",
})

#: Estimator param names the VW batched core vmaps over.
VW_VMAPPED = frozenset({"learningRate", "powerT", "l1", "l2"})

#: VW pass-through flags that would override a vmapped lane with a static
#: (``--learning_rate 0.1`` wins over ``learningRate``), breaking the
#: per-candidate stacks. Candidates carrying them fall back to singleton.
_VW_ARG_CONFLICTS = frozenset({"learning_rate", "power_t", "l1", "l2"})


def _freeze(value: Any):
    """Hashable stand-in for a param value (bucket keys live in sets)."""
    if isinstance(value, (list, tuple)):
        return tuple(_freeze(v) for v in value)
    if isinstance(value, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in value.items()))
    try:
        hash(value)
        return value
    except TypeError:
        return repr(value)


@dataclasses.dataclass
class CandidateBucket:
    """One shape-bucket: candidates sharing a compiled program.

    ``kind`` is ``"gbdt"`` / ``"vw"`` for batchable buckets (fitted K-at-
    once by :func:`mmlspark_tpu.sweep.batched.fit_bucket`) or ``None`` for
    a singleton fallback fitted through ``estimator.copy(params).fit``.
    ``indices`` maps each bucket position back into the original candidate
    list, so leaderboards and journals stay in candidate order.
    """

    estimator: Estimator
    kind: Optional[str]
    param_maps: List[Dict[str, Any]]
    indices: List[int]

    @property
    def size(self) -> int:
        return len(self.param_maps)


def _gbdt_batchable(cand: Estimator) -> bool:
    """Option surface the GBDT batched core supports: plain gbdt/goss
    boosting, single-program fit (no batch/process splits), no warm start,
    no init scores, no validation sets or per-iteration metric plumbing
    (``train_many`` returns no eval history), no live callbacks."""
    if cand.getBoostingType() not in ("gbdt", "goss"):
        return False
    if cand.getNumIterations() <= 0:
        return False
    if cand.getNumBatches() > 1 or cand.getNumProcesses() > 1:
        return False
    if cand.getModelString():
        return False
    if cand.isSet("initScoreCol") or cand.isSet("validationIndicatorCol"):
        return False
    if cand.getIsProvideTrainingMetric() or cand.getEarlyStoppingRound() > 0:
        return False
    if cand.callbacks:
        return False
    return True


def _vw_batchable(cand: Estimator) -> bool:
    """VW candidates batch unless pass-through args pin a vmapped lane."""
    try:
        args = cand._parse_args()
    except ValueError:
        return False  # bad flags surface on the sequential path
    return not (_VW_ARG_CONFLICTS & set(args))


def _candidate_kind(cand: Estimator) -> Optional[str]:
    from mmlspark_tpu.lightgbm.base import LightGBMBase
    from mmlspark_tpu.vw.base import VowpalWabbitBase

    if isinstance(cand, LightGBMBase) and _gbdt_batchable(cand):
        return "gbdt"
    if isinstance(cand, VowpalWabbitBase) and _vw_batchable(cand):
        return "vw"
    return None


def _bucket_key(cand: Estimator, kind: str):
    """Statics that must agree for two candidates to share a program:
    every set param EXCEPT the vmapped lanes. Estimator class is part of
    the key (classifier vs regressor = different objective/kernel)."""
    vmapped = GBDT_VMAPPED if kind == "gbdt" else VW_VMAPPED
    statics = frozenset(
        (name, _freeze(value))
        for name, value in cand.extractParamMap().items()
        if name not in vmapped
    )
    return (kind, type(cand).__name__, statics)


def bucket_candidates(
    candidates: List[Tuple[Estimator, Dict[str, Any]]],
) -> List[CandidateBucket]:
    """Partition ``(estimator, param_map)`` candidates into shape-buckets.

    Returns buckets in first-seen deterministic order; the union of all
    ``indices`` is exactly ``range(len(candidates))``.
    """
    buckets: List[CandidateBucket] = []
    by_key: Dict[Any, CandidateBucket] = {}
    for i, (est, params) in enumerate(candidates):
        cand = est.copy(params)
        kind = _candidate_kind(cand)
        if kind is None:
            buckets.append(CandidateBucket(
                estimator=est, kind=None, param_maps=[dict(params)],
                indices=[i],
            ))
            continue
        key = _bucket_key(cand, kind)
        bucket = by_key.get(key)
        if bucket is None:
            bucket = CandidateBucket(
                estimator=est, kind=kind, param_maps=[], indices=[],
            )
            by_key[key] = bucket
            buckets.append(bucket)
        bucket.param_maps.append(dict(params))
        bucket.indices.append(i)
    return buckets
