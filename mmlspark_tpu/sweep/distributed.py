"""Gang-sharded sweeps: candidate buckets across a supervised ProcessGroup.

When a sweep outgrows one chip, :class:`TrainValidSweep` (``numProcesses``
> 1) hands its shape-buckets to a real worker gang — the same
:class:`~mmlspark_tpu.runtime.procgroup.ProcessGroup` machinery procfit
uses: heartbeats, gang recovery, fault injection. The unit of work is one
BUCKET (task-per-bucket): worker ``rank`` owns bucket ``bi`` iff
``bi % world == rank``, and each finished bucket commits its scores to a
per-bucket :class:`~mmlspark_tpu.runtime.journal.FitJournal` (one journal
per bucket — single writer, no cross-process append races).

Fault model: a worker SIGKILL'd mid-bucket takes down the epoch; the gang
re-forms and every already-journaled bucket is SKIPPED (``TaskRecovered``
per restored bucket, zero re-execution). Selection is driver-side and
reads ONLY the journals — worker return values never decide the model —
so the final leaderboard and committed ``ModelStore`` version are
identical to an undisturbed run.
"""

from __future__ import annotations

import pickle
from pathlib import Path
from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.profiling import get_logger

logger = get_logger("mmlspark_tpu.sweep.distributed")


def _bucket_journal_key(journal_key: str, bi: int) -> str:
    return f"{journal_key}-bucket{bi}"


# -- worker side --------------------------------------------------------------


def sweep_worker_entry(ctx) -> Dict[str, Any]:
    """Per-member sweep entry, invoked by ``procgroup.worker_main`` inside
    a formed epoch. Walks the bucket list in index order: journaled
    buckets are skipped (recovery), owned un-journaled buckets are fitted
    and their scores journaled. Returns a JSON-safe summary; scores ride
    the journals."""
    from mmlspark_tpu.observability import TaskRecovered, get_bus
    from mmlspark_tpu.runtime.journal import FitJournal
    from mmlspark_tpu.sweep.batched import fit_bucket
    from mmlspark_tpu.sweep.bucketing import CandidateBucket

    payload = ctx.payload
    with open(payload["spec"], "rb") as fh:
        spec = pickle.load(fh)
    journal_root = payload["journal_root"]
    journal_key = payload["journal_key"]
    table = spec["table"]
    mask = np.asarray(spec["train_mask"], dtype=bool)
    train, valid = table.filter(mask), table.filter(~mask)
    kinds = spec["kinds"]
    bus = get_bus()

    fitted: List[int] = []
    recovered: List[int] = []
    for bi in range(len(kinds)):
        # the designated death point for kill_process chaos: every member
        # walks every bucket index (rank assignment is rendezvous-order,
        # so a member-targeted directive must not depend on ownership),
        # and a directive keyed (member, iteration=bi, epoch) SIGKILLs
        # here — mid-sweep, with earlier buckets already journaled
        ctx.maybe_die(bi)
        owned = bi % ctx.world == ctx.rank
        journal = FitJournal(
            journal_root, key=_bucket_journal_key(journal_key, bi),
            num_tasks=1,
        )
        try:
            if 0 in journal.restore():
                # committed before this epoch — zero re-execution; the
                # owner books the scheduler's checkpoint-recovery event
                if owned:
                    recovered.append(bi)
                    if bus.active:
                        bus.publish(TaskRecovered(job_id=0, task_id=bi))
                continue
            if not owned:
                continue
            bucket = CandidateBucket(
                estimator=spec["estimator"], kind=kinds[bi],
                param_maps=spec["param_maps"][bi],
                indices=spec["indices"][bi],
            )
            scored = fit_bucket(
                bucket, train, valid, spec["label_col"], spec["metric"],
                bucket_index=bi,
            )
            journal.record(0, {
                "indices": [int(i) for i in bucket.indices],
                "scores": [float(s) for s, _ in scored],
            })
            fitted.append(bi)
        finally:
            journal.close()
    if fitted or recovered:
        logger.info(
            "sweep member %d (rank %d/%d, epoch %d): fit %s, recovered %s",
            ctx.member, ctx.rank, ctx.world, ctx.epoch, fitted, recovered,
        )
    return {
        "rank": ctx.rank, "world": ctx.world, "epoch": ctx.epoch,
        "fitted": fitted, "recovered": recovered,
    }


# -- driver side --------------------------------------------------------------


def run_sweep_process_group(
    estimator,
    buckets,
    table,
    train_mask: np.ndarray,
    label_col: str,
    metric: str,
    num_processes: int,
    *,
    num_candidates: int,
    seed: int = 0,
    workdir: Optional[str] = None,
    journal_root: Optional[str] = None,
    journal_key: str = "sweep",
    group_options: Optional[Dict[str, Any]] = None,
    owner=None,
) -> List[float]:
    """Shard ``buckets`` across ``num_processes`` worker processes and
    return the per-candidate validation metrics in candidate order.

    The driver parks the candidate spec (estimator + bucket descriptors +
    table + split mask) in the group workdir, pre-creates every bucket
    journal (so worker constructors stay read-only), runs the gang, then
    assembles scores from the journals — never from worker return values,
    so a chaotic run selects exactly like an undisturbed one.
    """
    from mmlspark_tpu.runtime.journal import FitJournal
    from mmlspark_tpu.runtime.procgroup import ProcessGroup

    if workdir is None:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="mmlspark-tpu-sweep-")
    wd = Path(workdir)
    wd.mkdir(parents=True, exist_ok=True)
    if journal_root is None:
        journal_root = str(wd / "journal")

    spec = {
        "estimator": estimator,
        "kinds": [b.kind for b in buckets],
        "param_maps": [b.param_maps for b in buckets],
        "indices": [b.indices for b in buckets],
        "table": table,
        "train_mask": np.asarray(train_mask, dtype=bool),
        "label_col": label_col,
        "metric": metric,
    }
    spec_path = wd / "spec.pkl"
    with open(spec_path, "wb") as fh:
        pickle.dump(spec, fh, protocol=4)
    for bi in range(len(buckets)):
        FitJournal(journal_root, key=_bucket_journal_key(journal_key, bi),
                   num_tasks=1).close()

    payload = {
        "spec": str(spec_path),
        "journal_root": journal_root,
        "journal_key": journal_key,
    }
    gkw = dict(group_options or {})
    gkw.setdefault("seed", seed)
    pg = ProcessGroup(
        num_processes, "mmlspark_tpu.sweep.distributed:sweep_worker_entry",
        payload=payload, workdir=str(wd / "group"), rendezvous="jax", **gkw,
    )
    try:
        worker_results = pg.run()
    finally:
        exit_statuses = pg.exit_statuses + pg.shutdown()

    metrics: List[float] = [float("nan")] * num_candidates
    for bi in range(len(buckets)):
        journal = FitJournal(
            journal_root, key=_bucket_journal_key(journal_key, bi),
            num_tasks=1,
        )
        rec = journal.restore().get(0)
        journal.close()
        if rec is None:
            raise RuntimeError(
                f"sweep bucket {bi} never committed; worker results: "
                f"{worker_results}"
            )
        for idx, score in zip(rec["indices"], rec["scores"]):
            metrics[int(idx)] = float(score)
    if owner is not None:
        owner._process_sweep = {
            "epochs": pg.epoch + 1,
            "worker_results": worker_results,
            "exit_statuses": exit_statuses,
        }
    return metrics
