"""Bucket execution: fit every candidate in a shape-bucket, score them.

One :class:`~mmlspark_tpu.sweep.bucketing.CandidateBucket` = one unit of
work. Batchable buckets run the whole preamble (feature extraction,
binning / row layout) ONCE and hand K candidates to the vmapped cores —
:func:`mmlspark_tpu.lightgbm.train.train_many` or
:func:`mmlspark_tpu.vw.base.train_linear_many` — so the bucket pays one
compile and one device dispatch for all K models. Singleton (``kind is
None``) buckets fall back to the ordinary ``estimator.copy(params).fit``.

The same executor serves the inline sweep
(:class:`~mmlspark_tpu.sweep.estimator.TrainValidSweep`), the batched CV
path inside :class:`~mmlspark_tpu.automl.tune.TuneHyperparameters`, and
the gang workers (:mod:`mmlspark_tpu.sweep.distributed`).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Sequence, Tuple

import numpy as np

from mmlspark_tpu.data.table import Table
from mmlspark_tpu.sweep.bucketing import CandidateBucket, bucket_candidates


def _score(model, valid_table: Table, label_col: str, metric: str) -> float:
    from mmlspark_tpu.automl.tune import _evaluate

    return _evaluate(model.transform(valid_table), label_col, metric)


def _fit_bucket_gbdt(bucket: CandidateBucket, table: Table) -> List[Any]:
    """K GBDT candidates through one binning pass + one vmapped scan.
    Mirrors ``LightGBMBase._fit`` up to the train call (the bucket key
    pins every data-shaping param, so candidate 0 speaks for the bucket),
    then unpacks per-candidate models."""
    from mmlspark_tpu.lightgbm.train import train_many

    cands = [bucket.estimator.copy(p) for p in bucket.param_maps]
    c0 = cands[0]
    X, y, w, _init = c0._prepare(table)
    w = c0._adjust_weights(y, w)
    num_class = c0._num_classes(y)
    opts_list = [c._make_options(num_class) for c in cands]

    num_features = X.shape[1] if hasattr(X, "shape") else X.num_features
    slot_names = c0.getSlotNames() or []
    if slot_names and len(slot_names) != num_features:
        raise ValueError(
            f"slotNames has {len(slot_names)} entries for "
            f"{num_features} features"
        )
    feature_names = list(slot_names) or [f"f{i}" for i in range(num_features)]
    cat_slots = set(c0.getCategoricalSlotIndexes() or [])
    names = c0.getCategoricalSlotNames() or []
    bad = sorted(i for i in cat_slots if not (0 <= i < num_features))
    if bad:
        raise ValueError(
            f"categoricalSlotIndexes out of range for {num_features} "
            f"features: {bad}"
        )
    if names:
        name_to_idx = {nm: i for i, nm in enumerate(feature_names)}
        for nm in names:
            if nm not in name_to_idx:
                raise ValueError(
                    f"categoricalSlotNames: unknown feature name {nm!r}"
                )
            cat_slots.add(name_to_idx[nm])

    bins, mapper = c0._bin_dataset(X, opts_list[0], cat_slots)
    results = train_many(
        bins, y, opts_list, w=w, mapper=mapper, feature_names=feature_names,
    )
    models = []
    for c, r in zip(cands, results):
        model = c._make_model(r)
        model.parent = c
        model._train_evals = r.evals
        models.append(model)
    return models


def _fit_bucket_vw(bucket: CandidateBucket, table: Table) -> List[Any]:
    """K VW candidates through one row layout + one vmapped SGD scan."""
    from mmlspark_tpu.vw.base import train_linear_many

    cands = [bucket.estimator.copy(p) for p in bucket.param_maps]
    c0 = cands[0]
    args, batch, y, w, const_idx, init = c0._train_setup(table)
    results = train_linear_many(
        batch, y, w,
        loss=args.get("loss", c0._default_loss),
        num_passes=args.get("passes", c0.getNumPasses()),
        learning_rates=[c.getLearningRate() for c in cands],
        power_ts=[c.getPowerT() for c in cands],
        l1s=[c.getL1() for c in cands],
        l2s=[c.getL2() for c in cands],
        batch_size=c0.getBatchSize(),
        constant_index=const_idx,
        initial_weights=init,
        quantile_tau=args.get("quantile_tau", 0.5),
        optimizer="ftrl" if args.get("ftrl") else "adagrad",
        ftrl_alpha=args.get("ftrl_alpha", 0.005),
        ftrl_beta=args.get("ftrl_beta", 0.1),
    )
    link = args.get("link", "identity")
    models = []
    for c, r in zip(cands, results):
        c._link = link
        model = c._make_model(r, batch.dim, const_idx)
        model.set("linkFunction", link)
        model.parent = c
        models.append(model)
    return models


def fit_bucket(
    bucket: CandidateBucket,
    train_table: Table,
    valid_table: Table,
    label_col: str,
    metric: str,
    bucket_index: int = -1,
) -> List[Tuple[float, Any]]:
    """Fit + score every candidate in one bucket.

    Returns ``(metric, model)`` pairs aligned with ``bucket.param_maps``
    order. Publishes one ``CandidateBatchFitted`` event per call so the
    compile-amortization evidence lands on the bus regardless of which
    plane (inline sweep, batched CV, gang worker) ran the bucket.
    """
    from mmlspark_tpu.observability import CandidateBatchFitted, get_bus

    t0 = time.perf_counter()
    if bucket.kind == "gbdt":
        models = _fit_bucket_gbdt(bucket, train_table)
    elif bucket.kind == "vw":
        models = _fit_bucket_vw(bucket, train_table)
    else:
        models = [
            bucket.estimator.copy(p).fit(train_table)
            for p in bucket.param_maps
        ]
    scored = [
        (_score(m, valid_table, label_col, metric), m) for m in models
    ]
    bus = get_bus()
    if bus.active:
        bus.publish(CandidateBatchFitted(
            bucket=int(bucket_index), size=bucket.size,
            kind=bucket.kind or "sequential",
            batched=bucket.kind is not None,
            seconds=time.perf_counter() - t0,
        ))
    return scored


def cv_metrics_batched(
    candidates: List[Tuple[Any, Dict[str, Any]]],
    table: Table,
    folds: Sequence[np.ndarray],
    label_col: str,
    metric: str,
) -> List[float]:
    """K-fold CV over all candidates through shape-buckets: per fold, each
    bucket fits K-at-once instead of candidate-at-a-time. Returns the
    per-candidate mean metric in candidate order — the drop-in replacement
    for ``TuneHyperparameters``'s thread-pool metric loop."""
    buckets = bucket_candidates(candidates)
    n = table.num_rows
    sums = np.zeros(len(candidates), dtype=np.float64)
    for fold in folds:
        mask = np.zeros(n, dtype=bool)
        mask[fold] = True
        train, valid = table.filter(~mask), table.filter(mask)
        for bi, bucket in enumerate(buckets):
            scored = fit_bucket(bucket, train, valid, label_col, metric,
                                bucket_index=bi)
            for pos, idx in enumerate(bucket.indices):
                sums[idx] += scored[pos][0]
    return [float(s / len(folds)) for s in sums]
