"""Front-end fleet router: one address for a fleet of serving replicas.

The reference's Spark Serving deployment puts a load balancer in front of
the per-worker HTTP servers it registers with the driver
(``HTTPSourceV2.scala:318-410`` ServiceInfo + ``DriverServiceUtils``);
:class:`FleetRouter` is that front end, built only on the public control
plane — it discovers live replicas from RegistrationService ``GET
/services`` and steers by the load metadata replicas heartbeat into their
leases (``inflight``/``shed_total``/``p99_ms``). No private handle into
any replica process exists: a replica that dies simply vanishes from
``/services`` at the next lease prune, and until then costs one failed
hop per request, never a user-visible error.

Per request the router:

- picks a replica by ``policy`` — ``"least_loaded"`` (ascending heartbeat
  ``inflight``, round-robin rotation breaking ties) or
  ``"consistent_hash"`` (a crc32 vnode ring over the ``X-Routing-Key``
  header or the request body, so one key sticks to one replica while the
  fleet resizes with minimal reshuffling);
- forwards the body with the *remaining* deadline re-computed into
  ``X-Deadline-Ms`` and the hop's socket timeout capped to it;
- on a transport error or retryable status, records the failure on that
  replica's :class:`~mmlspark_tpu.resilience.breaker.CircuitBreaker` and
  retries on a *different* replica under the shared
  :class:`~mmlspark_tpu.resilience.policy.RetryPolicy` — attempts bounded,
  sleeps jittered and clipped to the deadline, retries rationed by the
  :class:`~mmlspark_tpu.resilience.budget.RetryBudget`;
- passes a replica's 429 shed through (with its ``Retry-After``) once
  retries are exhausted — a shed is the fleet protecting itself, not an
  error — and answers 503 only when every live replica was tried or
  breaker-skipped.

Hops go through :func:`mmlspark_tpu.io.http.clients._do_request`, so the
ambient :class:`~mmlspark_tpu.runtime.faults.FaultPlan` HTTP directives
(``http_storm``/``http_delay``/``http_reset``) inject at the router->replica
edge exactly as they do for any outbound client — the chaos campaign
trips real breakers with no cooperating server.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
import uuid
import zlib
from contextlib import nullcontext
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, List, Optional, Tuple

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.io.http.clients import BREAKER_FAILURE_STATUSES, _do_request
from mmlspark_tpu.io.http.schema import EntityData, HTTPRequestData
from mmlspark_tpu.observability.events import (
    RegistryRecovered,
    RegistryUnavailable,
    RequestRouted,
    get_bus,
)
from mmlspark_tpu.observability.registry import get_registry
from mmlspark_tpu.observability.tracing import (
    TRACE_HEADER,
    Span,
    TraceContext,
    get_tracer,
)
from mmlspark_tpu.resilience.breaker import BreakerRegistry
from mmlspark_tpu.resilience.budget import (
    DEADLINE_HEADER,
    Deadline,
    RetryBudget,
    deadline_scope,
)
from mmlspark_tpu.resilience.policy import RetryPolicy
from mmlspark_tpu.serving.server import RegistrationService, ServiceInfo, _Server

logger = get_logger("mmlspark_tpu.serving.router")

#: routing policies
LEAST_LOADED = "least_loaded"
CONSISTENT_HASH = "consistent_hash"

#: header carrying the affinity key for consistent-hash routing
ROUTING_KEY_HEADER = "X-Routing-Key"

#: vnodes per replica on the hash ring — enough that adding/removing one
#: replica moves ~1/N of the key space, small enough to rebuild per request
_VNODES = 64

_SERVICE_FIELDS = ("name", "host", "port", "model_version", "inflight",
                   "shed_total", "p99_ms")

#: a synthetic-502 hop that failed faster than this did no work anywhere
#: (connection refused/reset on a dead port — a socket timeout takes the
#: full hop timeout), so failing over is free and skips the retry budget
_FAST_FAIL_S = 0.1

#: fraction of the remaining deadline one hop may wait while other
#: replicas remain untried — a stalled replica costs a capped slice of
#: the budget, and the saved remainder pays for the failover hop
_HEDGE_FRACTION = 0.5


def _parse_services(raw: List[Dict[str, Any]]) -> List[ServiceInfo]:
    """``GET /services`` JSON -> ServiceInfo list, tolerant of extra keys
    (an older router must survive a newer registry's metadata)."""
    out = []
    for rec in raw:
        try:
            out.append(ServiceInfo(
                **{k: rec[k] for k in _SERVICE_FIELDS if k in rec}
            ))
        except (KeyError, TypeError):
            continue
    return out


class FleetRouter:
    """Deadline-aware, breaker-guarded HTTP front end over a replica fleet.

    Discovery is either in-process (``registry=`` a
    :class:`RegistrationService`) or over the wire (``registry_url=`` its
    base URL); a background thread re-reads ``/services`` every
    ``discovery_interval_s`` so retired/expired replicas drop out of
    rotation within one interval.
    """

    def __init__(
        self,
        registry: Optional[RegistrationService] = None,
        registry_url: Optional[str] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        policy: str = LEAST_LOADED,
        retry_policy: Optional[RetryPolicy] = None,
        retry_budget: Optional[RetryBudget] = None,
        breakers: Optional[BreakerRegistry] = None,
        discovery_interval_s: float = 0.25,
        hop_timeout_s: float = 5.0,
        default_deadline_s: Optional[float] = None,
        name: str = "router",
    ):
        if registry is None and registry_url is None:
            raise ValueError("need registry= or registry_url=")
        if policy not in (LEAST_LOADED, CONSISTENT_HASH):
            raise ValueError(f"unknown routing policy {policy!r}")
        self._registry = registry
        self._registry_url = registry_url.rstrip("/") if registry_url else None
        self.policy = policy
        self.name = name
        self.hop_timeout_s = float(hop_timeout_s)
        self.default_deadline_s = default_deadline_s
        self.discovery_interval_s = float(discovery_interval_s)
        #: retries rationed fleet-wide: each first attempt deposits, each
        #: failover spends — a dead replica can't multiply traffic
        if retry_policy is None:
            retry_budget = retry_budget or RetryBudget(ratio=0.2)
            retry_policy = RetryPolicy(
                max_attempts=3, base=0.02, cap=0.25, seed=0,
                budget=retry_budget,
            )
        self.retry_policy = retry_policy
        #: tighter than the shared client defaults: a serving replica that
        #: fails 3 hops in 5 s is out of rotation for a second
        self.breakers = breakers or BreakerRegistry(
            failure_threshold=3, window_s=5.0, reset_timeout_s=1.0,
        )
        self._replicas: List[ServiceInfo] = []
        self._rr = 0  # least-loaded tiebreak rotation (benign races ok)
        self._started_at = time.monotonic()
        self._discover_stop = threading.Event()
        self._discover_thread: Optional[threading.Thread] = None

        reg = get_registry()
        self._m_requests = reg.counter(
            "router_requests_total", "Requests answered by the fleet router"
        )
        self._m_hops = reg.counter(
            "router_hops_total", "Replica attempts made by the router"
        )
        self._m_failovers = reg.counter(
            "router_failovers_total",
            "Requests that needed more than one replica attempt",
        )
        self._m_skipped = reg.counter(
            "router_breaker_skips_total",
            "Replica picks skipped because their breaker was open",
        )
        self._m_no_replica = reg.counter(
            "router_no_replica_total",
            "Requests failed because no live replica could be tried",
        )
        self._m_replicas = reg.gauge(
            "router_replicas", "Live replicas in the routing table"
        )
        self._m_stale = reg.gauge(
            "router_stale_table",
            "1 while the routing table is last-known-good because the "
            "registry is unreachable",
        )
        #: registry-outage latch: set on the first failed discovery so the
        #: RegistryUnavailable event fires once per outage, not per poll
        self._stale = False
        self._m_latency = reg.histogram(
            "router_latency_seconds", "Router end-to-end request latency"
        )
        self._httpd = _Server((host, port), self._make_handler())
        self.info = ServiceInfo(name, host, self._httpd.server_address[1])

    @property
    def url(self) -> str:
        return self.info.url

    # -- discovery -----------------------------------------------------------

    def refresh(self) -> List[ServiceInfo]:
        """Re-read ``/services`` into the routing table (also called by
        the background discovery thread). Returns the new table.

        Registry-outage tolerant: any discovery failure — connection
        refused, timeout, malformed or truncated ``/services`` JSON —
        keeps the last-known-good table and stamps it stale
        (``router_stale_table`` gauge, one
        :class:`~mmlspark_tpu.observability.events.RegistryUnavailable`
        event per outage onset). The discovery thread never crashes; the
        router keeps answering from the stale table until the registry
        comes back."""
        try:
            if self._registry is not None:
                replicas = list(self._registry.services)
            else:
                url = self._registry_url + "/services"
                # net chaos on the discovery edge: partitions/drops raise
                # here (caught below -> stale table), corrupt garbles the
                # body so json.loads fails the same way a truncated read
                # off a dying registry would
                from mmlspark_tpu.runtime.faults import check_net

                net = check_net(url)
                with urllib.request.urlopen(url, timeout=5) as resp:
                    raw = resp.read()
                if net is not None and net.get("kind") == "corrupt":
                    from mmlspark_tpu.runtime.netchaos import corrupt_bytes

                    raw = corrupt_bytes(raw)
                replicas = _parse_services(json.loads(raw))
        except Exception as e:  # noqa: BLE001 - keep the last good table
            # warn once per outage onset; repeat polls log at DEBUG so a
            # long outage doesn't flood the log at discovery frequency
            log = logger.debug if self._stale else logger.warning
            if not self._stale:
                self._stale = True
                self._m_stale.set(1)
                bus = get_bus()
                if bus.active:
                    bus.publish(RegistryUnavailable(
                        source="router",
                        error=f"{type(e).__name__}: {e}",
                        stale_replicas=len(self._replicas),
                    ))
            log(
                "service discovery failed (%s); serving from stale table "
                "of %d replica(s)", e, len(self._replicas),
            )
            return self._replicas
        if self._stale:
            self._stale = False
            self._m_stale.set(0)
            bus = get_bus()
            if bus.active:
                bus.publish(RegistryRecovered(
                    source="router", replicas=len(replicas),
                ))
            logger.info("registry reachable again; routing table is fresh")
        # never route to ourselves (a router registered for visibility)
        replicas = [r for r in replicas if r.name != self.name]
        replicas.sort(key=lambda s: s.name)
        self._replicas = replicas  # atomic swap; readers snapshot
        self._m_replicas.set(len(replicas))
        return replicas

    def _discover_loop(self) -> None:
        while not self._discover_stop.wait(self.discovery_interval_s):
            try:
                self.refresh()
            except Exception:  # noqa: BLE001 - the poll thread must survive
                logger.warning("discovery poll failed", exc_info=True)

    # -- replica choice ------------------------------------------------------

    def _order(self, replicas: List[ServiceInfo],
               routing_key: bytes) -> List[ServiceInfo]:
        """Replica preference order for one request. The first entry is
        the pick; the rest are the failover sequence (always distinct
        replicas — a retry never re-hits the endpoint that just failed)."""
        if self.policy == CONSISTENT_HASH:
            ring: List[Tuple[int, ServiceInfo]] = []
            for svc in replicas:
                for v in range(_VNODES):
                    point = zlib.crc32(f"{svc.name}#{v}".encode())
                    ring.append((point, svc))
            ring.sort(key=lambda p: (p[0], p[1].name))
            key_point = zlib.crc32(routing_key)
            start = 0
            for i, (point, _) in enumerate(ring):
                if point >= key_point:
                    start = i
                    break
            ordered: List[ServiceInfo] = []
            seen = set()
            for i in range(len(ring)):
                svc = ring[(start + i) % len(ring)][1]
                if svc.name not in seen:
                    seen.add(svc.name)
                    ordered.append(svc)
            return ordered
        # least-loaded: ascending heartbeat inflight; rotation breaks ties
        # so equally idle replicas share first picks instead of the
        # alphabetically first one taking every request
        shift = self._rr % len(replicas)
        self._rr += 1
        rotated = replicas[shift:] + replicas[:shift]
        return sorted(rotated, key=lambda s: s.inflight or 0)

    # -- request path --------------------------------------------------------

    def _route(
        self, body: bytes, headers: Dict[str, str],
        span: Optional[Span] = None,
    ) -> Tuple[int, bytes, Dict[str, str], str, int]:
        """One client request through the fleet. Returns
        ``(status, body, extra_headers, final_replica, hops)``.
        ``span`` is the request's root span: each replica attempt opens a
        ``router.hop`` child whose :class:`TraceContext` rides the hop
        headers, so the replica's request->batch->apply spans parent
        under this hop in the merged fleet trace."""
        deadline = Deadline.from_header(headers.get(DEADLINE_HEADER))
        if deadline is None and self.default_deadline_s:
            deadline = Deadline.after(self.default_deadline_s)
        routing_key = (
            headers.get(ROUTING_KEY_HEADER, "").encode() or body
        )
        budget = self.retry_policy.budget
        if budget is not None:
            budget.record_request()

        replicas = self._replicas or self.refresh()
        if not replicas:
            self._m_no_replica.inc()
            return 503, b'{"error": "no live replicas"}', {}, "", 0

        scope = deadline_scope(deadline) if deadline else nullcontext()
        order = self._order(replicas, routing_key)
        tried: set = set()
        hops = 0
        attempt = 0  # retry index for the policy's backoff schedule
        last: Tuple[int, bytes, Dict[str, str], str] = (
            503, b'{"error": "all replicas unavailable"}', {}, "",
        )
        with scope:
            while True:
                candidate = None
                for svc in order:
                    if svc.name in tried:
                        continue
                    if not self.breakers.for_url(svc.url).allow():
                        self._m_skipped.inc()
                        continue
                    candidate = svc
                    break
                if candidate is None:
                    break  # every replica tried or breaker-skipped
                if deadline is not None and deadline.expired:
                    return (
                        504, b'{"error": "deadline exceeded"}', {},
                        last[3], hops,
                    )
                tried.add(candidate.name)
                hops += 1
                self._m_hops.inc()
                # hedge: while other replicas remain untried, one slow
                # hop may not burn the whole remaining deadline — reserve
                # headroom to fail over instead of timing out with
                # nothing left (one stalled replica != a dead request).
                # No breaker peek here: allow() claims half-open probes.
                more = any(s.name not in tried for s in order)
                hop_started = time.monotonic()
                tracer = get_tracer()
                hop_span = (
                    tracer.start_span(
                        "router.hop", parent=span, replica=candidate.name,
                    )
                    if span is not None else None
                )
                status, data, resp_headers = self._hop(
                    candidate, body, headers, deadline, hedge=more,
                    trace=(
                        TraceContext.from_span(hop_span)
                        if hop_span is not None else None
                    ),
                )
                if hop_span is not None:
                    tracer.finish(
                        hop_span,
                        status="ok" if status < 500 else f"http_{status}",
                        http_status=status,
                    )
                last = (status, data, resp_headers, candidate.name)
                if not self.retry_policy.retryable(status):
                    return status, data, resp_headers, candidate.name, hops
                if (
                    status == 502
                    and time.monotonic() - hop_started < _FAST_FAIL_S
                ):
                    # connection-level fast-fail (refused/reset before the
                    # replica did any work — a dead port, not a slow one):
                    # failing over costs the fleet nothing, so it is NOT
                    # rationed by the retry budget, which exists to cap
                    # load amplification on replicas that processed the
                    # attempt. Hops stay bounded by the tried-set and the
                    # deadline; no backoff — the next hop is a different
                    # replica. This is what makes a SIGKILL'd replica one
                    # failed hop instead of a user-visible 502 while its
                    # stale lease rides out the registry TTL.
                    continue
                if not self.retry_policy.allow_retry(attempt):
                    break
                time.sleep(self.retry_policy.next_wait(
                    attempt, status=status, headers=resp_headers,
                ))
                attempt += 1
        status, data, resp_headers, replica = last
        return status, data, resp_headers, replica, hops

    def _hop(
        self,
        svc: ServiceInfo,
        body: bytes,
        headers: Dict[str, str],
        deadline: Optional[Deadline],
        hedge: bool = False,
        trace: Optional[TraceContext] = None,
    ) -> Tuple[int, bytes, Dict[str, str]]:
        """One attempt against one replica, with breaker bookkeeping.
        Transport errors come back as a synthetic 502 so the retry loop
        has one shape to reason about. With ``hedge`` (other replicas
        remain untried) the socket wait is capped to a fraction of the
        remaining deadline so a timeout still leaves room to fail over.
        ``trace`` is the hop span's wire context — the replica adopts it
        so its spans land in the router's trace."""
        breaker = self.breakers.for_url(svc.url)
        timeout = self.hop_timeout_s
        extra: Dict[str, str] = {"Content-Type": "application/json"}
        if trace is not None:
            extra.update(trace.to_headers())
        elif headers.get(TRACE_HEADER):
            extra[TRACE_HEADER] = headers[TRACE_HEADER]
        # the per-client identity must survive the proxy hop: the replica's
        # malformed-rate breaker keys on X-Client-Id, and without it every
        # routed request would collapse onto the router's address — one
        # poison client would shed the whole fleet's healthy traffic
        for k, v in headers.items():
            if k.lower() == "x-client-id":
                extra["X-Client-Id"] = v
                break
        if deadline is not None:
            # forward the REMAINING budget; never wait on the socket
            # longer than the caller will wait for us
            extra[DEADLINE_HEADER] = deadline.to_header()
            budget_s = max(0.001, deadline.remaining())
            if hedge:
                budget_s = max(0.001, budget_s * _HEDGE_FRACTION)
            timeout = min(timeout, budget_s)
        request = HTTPRequestData(
            url=svc.url, method="POST",
            entity=EntityData(content=body, contentType="application/json"),
        )
        try:
            resp = _do_request(request, timeout, extra_headers=extra)
        except Exception as e:  # noqa: BLE001 - refused/reset/timeout
            breaker.record_failure()
            logger.debug("hop to %s failed: %s", svc.name, e)
            return 502, json.dumps(
                {"error": f"replica unreachable: {type(e).__name__}"}
            ).encode(), {}
        if resp.status_code in BREAKER_FAILURE_STATUSES:
            breaker.record_failure()
        else:
            # includes 429: a shedding replica is UP and protecting itself
            breaker.record_success()
        keep = {
            k: v for k, v in resp.header_map().items()
            if k.lower() == "retry-after"
        }
        return resp.status_code, (
            resp.entity.content if resp.entity else b""
        ), keep

    # -- HTTP edge -----------------------------------------------------------

    def health(self) -> Dict[str, Any]:
        return {
            "status": "ok",
            "name": self.name,
            "uptime_seconds": round(time.monotonic() - self._started_at, 3),
            "policy": self.policy,
            "replicas": len(self._replicas),
        }

    def _make_handler(self):
        router = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def _reply_bytes(
                self, status: int, data: bytes,
                content_type: str = "application/json",
                extra_headers: Optional[Dict[str, str]] = None,
            ) -> None:
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path == "/metrics":
                    body = get_registry().exposition().encode("utf-8")
                    self._reply_bytes(
                        200, body,
                        content_type="text/plain; version=0.0.4; charset=utf-8",
                    )
                elif self.path == "/healthz":
                    self._reply_bytes(200, json.dumps(router.health()).encode())
                else:
                    self._reply_bytes(404, b'{"error": "not found"}')

            def do_POST(self):  # noqa: N802 (http.server API)
                t0 = time.monotonic()
                rid = uuid.uuid4().hex
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                headers = dict(self.headers.items())
                # root span for the fleet-wide trace; a client-supplied
                # X-Trace-Id is adopted so an upstream hop stays parent
                tracer = get_tracer()
                span = tracer.start_span(
                    "router.request", rid=rid,
                    context=TraceContext.from_headers(self.headers),
                )
                status, data, extra, replica, hops = router._route(
                    body, headers, span=span
                )
                router._m_requests.inc()
                if hops > 1:
                    router._m_failovers.inc()
                latency = time.monotonic() - t0
                router._m_latency.observe(latency)
                # the trace id rides EVERY reply — 429/503/504 included —
                # so a user-quoted incident id joins against the event log
                extra = dict(extra)
                extra[TRACE_HEADER] = span.trace_id
                try:
                    self._reply_bytes(status, data, extra_headers=extra)
                except OSError:
                    pass  # client hung up; the fold still sees the event
                tracer.finish(
                    span,
                    status="ok" if status < 500 else f"http_{status}",
                    http_status=status, hops=hops, replica=replica,
                )
                bus = get_bus()
                if bus.active:
                    bus.publish(RequestRouted(
                        rid=rid, replica=replica, hops=hops,
                        status=status, latency=latency,
                        trace_id=span.trace_id,
                    ))

            def log_message(self, *args):  # silence default stderr logging
                pass

        return Handler

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetRouter":
        self.refresh()
        threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"router-{self.name}",
        ).start()
        self._discover_stop.clear()
        self._discover_thread = threading.Thread(
            target=self._discover_loop, daemon=True,
            name=f"router-discovery-{self.name}",
        )
        self._discover_thread.start()
        return self

    def stop(self) -> None:
        self._discover_stop.set()
        if self._discover_thread is not None:
            self._discover_thread.join(timeout=2.0)
            self._discover_thread = None
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
