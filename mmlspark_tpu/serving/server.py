"""Model serving — embedded HTTP servers answering with TPU inference.

Reference: Spark Serving (SURVEY.md §2.16;
``org/apache/spark/sql/execution/streaming/HTTPSourceV2.scala``): per-worker
HTTP servers with epoch-indexed request queues, reply-by-request-id, driver
registration service, commit-based GC, task-retry re-hydration.

TPU-native redesign: the streaming-engine indirection disappears — a
:class:`ServingServer` owns an HTTP listener, a micro-batching loop and a
persistent *pre-compiled* model (the "ThreadLocal buffer" trick for
single-row latency becomes: keep the jitted program + donated device
buffers warm and pad requests into fixed batch shapes so XLA never
recompiles). Epoch bookkeeping (``requestQueues(epoch)``,
``getNextRequest`` timeout-driven epoch advance, ``HTTPSourceV2.scala:
588-623``) survives as the micro-batch loop; replies are routed by request
id exactly as ``replyTo`` does (``continuous/HTTPSinkV2.scala:81-89``).

Modes (``io/IOImplicits.scala:20-74``):
- ``ServingServer`` — head-node mode (one listener, the ``HTTPSource`` V1).
- ``DistributedServingServer`` — N listeners sharing one model, the
  ``DistributedHTTPSource`` shape for multi-host TPU pods; a registration
  callback exposes every endpoint like ``HTTPSourceStateHolder.serviceInfo``.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table


class _Server(ThreadingHTTPServer):
    # many concurrent clients: deep accept backlog, daemon worker threads
    request_queue_size = 128
    daemon_threads = True


@dataclass
class _PendingRequest:
    rid: str
    payload: Any
    event: threading.Event = field(default_factory=threading.Event)
    response: Optional[bytes] = None
    status: int = 200
    epoch: int = -1


@dataclass
class ServiceInfo:
    """One worker endpoint (``HTTPSourceV2.scala:318-410`` ServiceInfo)."""

    name: str
    host: str
    port: int

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/"


class ServingServer:
    """Serve a ``Transformer`` (or a raw table->table callable) over HTTP.

    POST body: JSON ``{"<inputCol>": value}`` or a bare value; reply is the
    JSON of the output column for that row. Requests are micro-batched up to
    ``maxBatchSize`` or ``maxLatencyMs`` — the ``DynamicMiniBatchTransformer``
    idea applied at the serving edge so single-row latency stays low while
    the chip still sees batches.
    """

    def __init__(
        self,
        model: Transformer | Callable[[Table], Table],
        input_col: str = "input",
        output_col: str = "prediction",
        host: str = "127.0.0.1",
        port: int = 0,
        max_batch_size: int = 64,
        max_latency_ms: float = 2.0,
        name: str = "serving",
    ):
        self.model = model
        self.input_col = input_col
        self.output_col = output_col
        self.max_batch_size = int(max_batch_size)
        self.max_latency_ms = float(max_latency_ms)
        self.name = name
        self._queue: "queue.Queue[_PendingRequest]" = queue.Queue()
        self._epoch = 0
        self._history: Dict[int, List[_PendingRequest]] = {}  # epoch -> reqs
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._httpd = _Server((host, port), self._make_handler())
        self.info = ServiceInfo(name, host, self._httpd.server_address[1])
        self._threads: List[threading.Thread] = []

    # -- HTTP edge -----------------------------------------------------------

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (http.server API)
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                try:
                    payload = json.loads(body) if body else None
                except json.JSONDecodeError:
                    self.send_response(400)
                    self.end_headers()
                    self.wfile.write(b'{"error": "invalid json"}')
                    return
                if isinstance(payload, dict) and server.input_col in payload:
                    payload = payload[server.input_col]
                req = _PendingRequest(rid=uuid.uuid4().hex, payload=payload)
                server._queue.put(req)
                req.event.wait(timeout=30.0)
                if req.response is None:
                    self.send_response(504)
                    self.end_headers()
                    return
                self.send_response(req.status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(req.response)))
                self.end_headers()
                self.wfile.write(req.response)

            def log_message(self, *args):  # silence default stderr logging
                pass

        return Handler

    # -- micro-batch loop ----------------------------------------------------

    def _gather_batch(self) -> List[_PendingRequest]:
        """Collect up to max_batch_size requests, waiting at most
        max_latency_ms past the first (``getNextRequest`` epoch-advance
        timeout, ``HTTPSourceV2.scala:588-623``)."""
        batch: List[_PendingRequest] = []
        try:
            first = self._queue.get(timeout=0.05)
        except queue.Empty:
            return batch
        batch.append(first)
        deadline = time.perf_counter() + self.max_latency_ms / 1000.0
        while len(batch) < self.max_batch_size:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                batch.append(self._queue.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _apply_model(self, table: Table) -> Table:
        if isinstance(self.model, Transformer):
            return self.model.transform(table)
        return self.model(table)

    def _reply(self, req: _PendingRequest, value: Any, status: int = 200) -> None:
        """replyTo(requestId) (``HTTPSinkV2.scala:81-89``)."""
        if isinstance(value, np.ndarray):
            value = value.tolist()
        elif isinstance(value, np.generic):
            value = value.item()
        req.response = json.dumps({self.output_col: value}).encode("utf-8")
        req.status = status
        req.event.set()

    def _serve_loop(self) -> None:
        while not self._stopping.is_set():
            batch = self._gather_batch()
            if not batch:
                continue
            epoch = self._epoch
            self._epoch += 1
            for r in batch:
                r.epoch = epoch
            with self._lock:
                self._history[epoch] = batch  # re-hydration bookkeeping
            try:
                payloads = np.empty(len(batch), dtype=object)
                for i, r in enumerate(batch):
                    p = r.payload
                    payloads[i] = np.asarray(p) if isinstance(p, list) else p
                try:
                    col = np.stack(payloads)  # rectangular -> fast path
                except Exception:
                    col = payloads
                out = self._apply_model(Table({self.input_col: col}))
                values = out.column(self.output_col)
                for r, v in zip(batch, values):
                    self._reply(r, v)
            except Exception as e:
                err = json.dumps({"error": str(e)[:500]}).encode("utf-8")
                for r in batch:
                    r.response = err
                    r.status = 500
                    r.event.set()
            finally:
                self.commit(epoch)

    def commit(self, epoch: int) -> None:
        """Commit-based GC of answered epochs (``HTTPSourceV2.scala:535-552``)."""
        with self._lock:
            self._history.pop(epoch, None)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "ServingServer":
        t1 = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        t2 = threading.Thread(target=self._serve_loop, daemon=True)
        t1.start()
        t2.start()
        self._threads = [t1, t2]
        return self

    def stop(self) -> None:
        self._stopping.set()
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self) -> "ServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class DistributedServingServer:
    """N listeners sharing one model — the ``DistributedHTTPSource`` shape.
    Endpoints register into ``service_info`` the way worker servers report
    to the driver registration service (``HTTPSourceV2.scala:113-173``)."""

    def __init__(self, model, num_servers: int = 2, host: str = "127.0.0.1",
                 name: str = "serving", **kwargs):
        self.servers = [
            ServingServer(model, host=host, name=f"{name}-{i}", **kwargs)
            for i in range(num_servers)
        ]

    @property
    def service_info(self) -> List[ServiceInfo]:
        return [s.info for s in self.servers]

    def start(self) -> "DistributedServingServer":
        for s in self.servers:
            s.start()
        return self

    def stop(self) -> None:
        for s in self.servers:
            s.stop()

    def __enter__(self) -> "DistributedServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
