"""Model serving — embedded HTTP servers answering with TPU inference.

Reference: Spark Serving (SURVEY.md §2.16;
``org/apache/spark/sql/execution/streaming/HTTPSourceV2.scala``): per-worker
HTTP servers with epoch-indexed request queues, reply-by-request-id, driver
registration service, commit-based GC, task-retry re-hydration.

TPU-native redesign: the streaming-engine indirection disappears — a
:class:`ServingServer` owns an HTTP listener and a micro-batching
:class:`_BatchLoop` with a persistent *pre-compiled* model (the
"ThreadLocal buffer" trick for single-row latency becomes: keep the jitted
program warm and pad requests into fixed batch shapes so XLA never
recompiles). The reference machinery maps as:

- epoch-indexed queues + ``getNextRequest`` timeout-driven epoch advance
  (``HTTPSourceV2.scala:588-623``) → the micro-batch gather loop;
- ``replyTo(machineIp, requestId, response)`` (``HTTPSinkV2.scala:81-89``)
  → the rid-keyed reply registry: ANY listener's request can be answered
  by the shared loop (the cross-worker reply the reference left as
  ``NotImplementedError`` at ``HTTPSourceV2.scala:509-533``);
- ``registerPartition`` re-hydration + ``recoveredPartitions``
  (``HTTPSourceV2.scala:470-487``) → failed batches re-enqueue up to
  ``max_retries`` (task retry), and :meth:`_BatchLoop.recover` replays
  every uncommitted epoch after a worker death;
- commit-based GC (``:535-552``) → :meth:`_BatchLoop.commit`;
- the driver registration HTTP service (``DriverServiceUtils:113-173``,
  ``HTTPSourceStateHolder.serviceInfo``) → :class:`RegistrationService`.
"""

from __future__ import annotations

import json
import logging
import os
import queue
import random
import threading
import time
import uuid
import zlib
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.observability.events import (
    BatchFormed,
    LeaseRecovered,
    ModelSwapped,
    RequestServed,
    RequestShed,
    get_bus,
)
from mmlspark_tpu.observability.profiler import get_profiler
from mmlspark_tpu.observability.registry import get_registry
from mmlspark_tpu.observability.tracing import (
    TRACE_HEADER,
    Span,
    TraceContext,
    get_tracer,
)
from mmlspark_tpu.resilience.admission import AdmissionController
from mmlspark_tpu.resilience.budget import DEADLINE_HEADER, Deadline

logger = logging.getLogger("mmlspark_tpu.serving")

#: micro-batch sizes are small integers; latency-style buckets would put
#: every batch in the first bucket
_BATCH_SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)

_GET_QMONITOR = None


def _quality_monitor():
    # ambient quality gate, cached like core.pipeline._tracer: the batch
    # loop is the serving hot path, so an unconfigured process pays one
    # env lookup per batch and never imports the quality plane
    global _GET_QMONITOR
    if _GET_QMONITOR is None:
        from mmlspark_tpu.observability.quality import get_monitor

        _GET_QMONITOR = get_monitor
    return _GET_QMONITOR()


class _Server(ThreadingHTTPServer):
    # many concurrent clients: deep accept backlog, daemon worker threads
    request_queue_size = 128
    daemon_threads = True


@dataclass
class _PendingRequest:
    rid: str
    payload: Any
    event: threading.Event = field(default_factory=threading.Event)
    response: Optional[bytes] = None
    status: int = 200
    epoch: int = -1
    retries: int = 0
    # observability: contextvars don't cross the listener->loop thread hop,
    # so the request's root span rides the request object itself
    t_submit: float = 0.0
    span: Optional[Span] = None
    trace_id: str = ""
    # resilience: the request's wall-clock budget (X-Deadline-Ms or the
    # server default) and the listener-gave-up flag — both checked by the
    # batch loop so timed-out work is purged BEFORE the TPU apply
    deadline: Optional[Deadline] = None
    cancelled: bool = False


@dataclass
class ServiceInfo:
    """One worker endpoint (``HTTPSourceV2.scala:318-410`` ServiceInfo).

    ``model_version`` is lease metadata: the ModelStore version this
    replica currently serves (None = untracked). Hot swaps and warm
    restarts refresh it, so ``GET /services`` shows which version each
    replica answers with.

    ``inflight``/``shed_total``/``p99_ms`` are *load* metadata, refreshed
    by heartbeats: the signals the fleet router (least-loaded balancing)
    and autoscaler (scale-up/down decisions) steer by without any private
    handle into the replica process — ``/services`` is the whole
    control-plane contract (docs/serving_fleet.md)."""

    name: str
    host: str
    port: int
    model_version: Optional[int] = None
    #: admitted-and-unanswered requests at last heartbeat (None = unreported)
    inflight: Optional[int] = None
    #: cumulative 429 sheds at last heartbeat
    shed_total: Optional[int] = None
    #: queue-wait p99 in milliseconds at last heartbeat
    p99_ms: Optional[float] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/"


#: ServiceInfo fields omitted from the ``/services`` wire format while
#: unreported (None) — a lease that never heartbeat load metadata keeps
#: the pre-fleet wire shape.
_LOAD_FIELDS = frozenset({"inflight", "shed_total", "p99_ms"})


class _BatchLoop:
    """Micro-batching evaluation loop shared by one or many listeners.

    Requests enter through :meth:`submit` (any listener thread) and are
    answered by rid through their own events — reply routing is therefore
    independent of which listener accepted the request. Uncommitted epochs
    are retained for re-hydration; a batch that fails evaluation re-enqueues
    its requests up to ``max_retries`` before failing them with 500."""

    def __init__(
        self,
        model: Transformer | Callable[[Table], Table],
        input_col: str,
        output_col: str,
        max_batch_size: int,
        max_latency_ms: float,
        max_retries: int = 1,
        scheduler=None,
        registry=None,
        admission: Optional[AdmissionController] = None,
    ):
        self.model = model
        self.input_col = input_col
        self.output_col = output_col
        #: ModelStore version of ``model`` (0 = untracked); hot swaps and
        #: warm restarts refresh it so drift sketches carry the version
        #: of the model that actually scored each batch
        self.model_version = 0
        self.max_batch_size = int(max_batch_size)
        self.max_latency_ms = float(max_latency_ms)
        self.max_retries = int(max_retries)
        #: shed-or-admit gate shared by every listener on this loop
        self.admission = admission
        #: optional mmlspark_tpu.runtime.Scheduler — when set, each
        #: micro-batch is applied as partitioned tasks with retry /
        #: heartbeat re-dispatch (the Spark-executor dispatch analog)
        self.scheduler = scheduler
        self.queue: "queue.Queue[_PendingRequest]" = queue.Queue()
        self._epoch = 0
        self._history: Dict[int, List[_PendingRequest]] = {}  # uncommitted epochs
        #: rid -> request reply registry; entries leave on reply OR via
        #: :meth:`forget` when the listener gives up (504), so timed-out
        #: rids never accumulate
        self._pending: Dict[str, _PendingRequest] = {}
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: monotonic time of the last processed batch (healthz freshness)
        self.last_batch_at: Optional[float] = None
        # metrics plane (docs/observability.md); pass a registry for isolation
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        self._reg_requests = reg.counter(
            "serving_requests_total", "Requests answered by the batch loop"
        )
        self._reg_replies_failed = reg.counter(
            "serving_replies_failed_total",
            "Replies lost because the client disconnected before the write",
        )
        self._reg_batches = reg.counter(
            "serving_batches_total", "Micro-batches evaluated"
        )
        self._reg_queue_wait = reg.histogram(
            "serving_queue_wait_seconds",
            "Submit-to-batch wait per request",
        )
        self._reg_batch_size = reg.histogram(
            "serving_batch_size", "Requests per micro-batch",
            buckets=_BATCH_SIZE_BUCKETS,
        )
        self._reg_apply = reg.histogram(
            "serving_apply_latency_seconds",
            "Model apply time per micro-batch",
        )
        self._reg_expired = reg.counter(
            "serving_expired_total",
            "Requests dropped before model apply (deadline expired or "
            "listener gave up)",
        )

    # -- intake / reply ------------------------------------------------------

    def submit(self, req: _PendingRequest) -> None:
        if not req.t_submit:
            req.t_submit = time.monotonic()
        with self._lock:
            self._pending[req.rid] = req
        self.queue.put(req)

    def forget(self, rid: str) -> None:
        """The listener answered 504 and moved on: drop the rid from the
        reply registry and mark the request cancelled so the batch loop
        purges it instead of computing an answer nobody is waiting for."""
        with self._lock:
            req = self._pending.pop(rid, None)
        if req is not None:
            req.cancelled = True

    def _finish(self, req: _PendingRequest, data: bytes, status: int) -> None:
        """Resolve a request: deregister its rid, store the reply, wake
        the listener."""
        with self._lock:
            self._pending.pop(req.rid, None)
        req.response = data
        req.status = status
        req.event.set()

    def _reply(self, req: _PendingRequest, value: Any, status: int = 200) -> None:
        """replyTo(requestId) (``HTTPSinkV2.scala:81-89``)."""
        if isinstance(value, np.ndarray):
            value = value.tolist()
        elif isinstance(value, np.generic):
            value = value.item()
        self._finish(
            req, json.dumps({self.output_col: value}).encode("utf-8"), status
        )

    def note_reply_failure(self, rid: str, exc: BaseException) -> None:
        """The answer existed but the client hung up before the write — a
        visibility gap in the reference (a dropped keep-alive connection
        surfaced only as a stack trace). Status 499 follows nginx's
        'client closed request' convention."""
        self._reg_replies_failed.inc()
        bus = get_bus()
        if bus.active:
            bus.publish(RequestServed(rid=rid, status=499, latency=0.0))
        logger.debug(
            "reply to %s lost, client disconnected (%s: %s)",
            rid, type(exc).__name__, exc,
        )

    # -- batching ------------------------------------------------------------

    def effective_max_batch_size(self) -> int:
        """``max_batch_size`` after ambient memory pressure: half at
        WARN, a quarter (floor 1) at CRITICAL — smaller device batches
        under pressure, full size again the moment the level clears."""
        from mmlspark_tpu.runtime.pressure import (
            PressureLevel, current_pressure_level,
        )

        level = current_pressure_level("memory")
        if level >= PressureLevel.CRITICAL:
            return max(1, self.max_batch_size // 4)
        if level >= PressureLevel.WARN:
            return max(1, self.max_batch_size // 2)
        return self.max_batch_size

    def _gather_batch(self) -> List[_PendingRequest]:
        """Collect up to the (pressure-adjusted) max batch size, waiting
        at most max_latency_ms past the first (``getNextRequest``
        epoch-advance timeout, ``HTTPSourceV2.scala:588-623``)."""
        batch: List[_PendingRequest] = []
        try:
            first = self.queue.get(timeout=0.05)
        except queue.Empty:
            return batch
        batch.append(first)
        bound = self.effective_max_batch_size()
        deadline = time.perf_counter() + self.max_latency_ms / 1000.0
        while len(batch) < bound:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                batch.append(self.queue.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    def _apply_model(self, table: Table) -> Table:
        apply = (
            self.model.transform if isinstance(self.model, Transformer)
            else self.model
        )
        if self.scheduler is None:
            return apply(table)
        # Scheduler-backed dispatch: split the micro-batch across executor
        # tasks; an executor dying mid-batch retries its partition, and
        # results reassemble in request order, so the caller sees one
        # ordinary (fault-absorbed) response set.
        col = table.column(self.input_col)
        k = max(1, min(self.scheduler.policy.max_workers, len(col)))
        bounds = np.linspace(0, len(col), k + 1).astype(int)
        shards = [
            Table({self.input_col: col[lo:hi]})
            for lo, hi in zip(bounds[:-1], bounds[1:])
            if hi > lo
        ]
        parts = self.scheduler.run(apply, shards)
        out = np.concatenate(
            [np.asarray(p.column(self.output_col)) for p in parts]
        )
        return Table({self.output_col: out})

    def _purge_expired(
        self, batch: List[_PendingRequest]
    ) -> List[_PendingRequest]:
        """Drop cancelled/deadline-expired requests BEFORE the TPU apply —
        computing an answer whose requester already got a 504 only adds
        latency for the live requests behind it (the load-shedding half of
        deadline propagation)."""
        live: List[_PendingRequest] = []
        for r in batch:
            if r.cancelled or (r.deadline is not None and r.deadline.expired):
                self._reg_expired.inc()
                if not r.event.is_set():
                    self._finish(
                        r, b'{"error": "deadline exceeded"}', status=504
                    )
                else:
                    with self._lock:
                        self._pending.pop(r.rid, None)
            else:
                live.append(r)
        return live

    def _process(self, batch: List[_PendingRequest]) -> None:
        batch = self._purge_expired(batch)
        if not batch:
            return
        epoch = self._epoch
        self._epoch += 1
        for r in batch:
            r.epoch = epoch
        with self._lock:
            self._history[epoch] = batch  # re-hydration bookkeeping
        now = time.monotonic()
        self.last_batch_at = now
        self._reg_batches.inc()
        self._reg_batch_size.observe(len(batch))
        for r in batch:
            if r.t_submit:
                self._reg_queue_wait.observe(now - r.t_submit)
        # The batch joins the FIRST request's trace (a batch has one parent;
        # the remaining requests keep their own root spans), so at least one
        # request's trace id threads request -> batch -> apply -> reply.
        tracer = get_tracer()
        parent = next((r.span for r in batch if r.span is not None), None)
        bus = get_bus()
        if bus.active:
            bus.publish(BatchFormed(
                epoch=epoch, size=len(batch),
                trace_id=parent.trace_id if parent else "",
            ))
        try:
            payloads = np.empty(len(batch), dtype=object)
            for i, r in enumerate(batch):
                p = r.payload
                payloads[i] = np.asarray(p) if isinstance(p, list) else p
            try:
                col = np.stack(payloads)  # rectangular -> fast path
            except (ValueError, TypeError):
                col = payloads  # ragged payloads stay an object column
            # drift sketching (quality plane): the loop observes the
            # batch itself — inputs before apply, scores after — and
            # suppresses the PipelineModel.transform hook underneath so
            # a request is never sketched twice
            monitor = _quality_monitor()
            t0 = time.perf_counter()
            with tracer.span(
                "serving.batch", parent=parent, epoch=epoch, size=len(batch)
            ):
                with tracer.span("serving.apply"):
                    if monitor is not None:
                        with monitor.suppress_transform():
                            out = self._apply_model(
                                Table({self.input_col: col})
                            )
                    else:
                        out = self._apply_model(Table({self.input_col: col}))
            apply_dt = time.perf_counter() - t0
            self._reg_apply.observe(apply_dt)
            values = out.column(self.output_col)
            if monitor is not None:
                monitor.observe_columns(
                    {self.input_col: col, self.output_col: values},
                    version=self.model_version,
                )
            prof = get_profiler()
            if prof.active:
                prof.note_execute("serving.apply", apply_dt)
                prof.note_transfer(
                    getattr(col, "nbytes", 0), "h2d", name="serving.apply"
                )
                prof.note_transfer(
                    getattr(np.asarray(values), "nbytes", 0),
                    "d2h", name="serving.apply",
                )
            for r, v in zip(batch, values):
                self._reply(r, v)
                self._reg_requests.inc()
            self.commit(epoch)
        except Exception as e:
            logger.warning(
                "batch epoch %d failed (%s: %s); re-enqueueing retryable "
                "requests", epoch, type(e).__name__, e,
            )
            self.commit(epoch)
            # Task-retry re-hydration: the failed batch goes back on the
            # queue (``registerPartition``/``recoveredPartitions``,
            # HTTPSourceV2.scala:470-487) until retries are exhausted.
            unanswered = [r for r in batch if not r.event.is_set()]
            retryable = [r for r in unanswered if r.retries < self.max_retries]
            failed = [r for r in unanswered if r.retries >= self.max_retries]
            for r in retryable:
                r.retries += 1
                self.queue.put(r)
            err = json.dumps({"error": str(e)[:500]}).encode("utf-8")
            for r in failed:
                self._finish(r, err, status=500)
                self._reg_requests.inc()

    def _serve_loop(self) -> None:
        while not self._stopping.is_set():
            batch = self._gather_batch()
            if batch:
                self._process(batch)

    # -- fault tolerance -----------------------------------------------------

    def commit(self, epoch: int) -> None:
        """Commit-based GC of answered epochs (``HTTPSourceV2.scala:535-552``)."""
        with self._lock:
            self._history.pop(epoch, None)

    @property
    def uncommitted_epochs(self) -> List[int]:
        with self._lock:
            return sorted(self._history)

    def recover(self) -> int:
        """Re-hydrate every uncommitted epoch after a worker death: its
        unanswered requests re-enter the queue for the next (restarted)
        loop. Returns how many requests were replayed."""
        with self._lock:
            pending = [
                r
                for reqs in self._history.values()
                for r in reqs
                if not r.event.is_set()
            ]
            self._history.clear()
        for r in pending:
            self.queue.put(r)
        return len(pending)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "_BatchLoop":
        self._stopping.clear()
        self._thread = threading.Thread(target=self._serve_loop, daemon=True)
        self._thread.start()
        return self

    def drain(self, timeout: float = 5.0) -> bool:
        """Graceful-shutdown helper: wait (bounded) for the already-queued
        requests to be answered by the still-running loop. Callers stop
        accepting first, drain second, stop the loop last — admitted
        requests get answers, not connection resets. Returns True when the
        queue fully drained."""
        if self._thread is None or not self._thread.is_alive():
            return self.queue.empty()
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.queue.empty() and not self.uncommitted_epochs:
                return True
            time.sleep(0.005)
        return self.queue.empty()

    def stop(self) -> None:
        self._stopping.set()


class _ListenerMixin:
    """HTTP edge shared by the serving classes: parse, submit, await."""

    def health(self) -> Dict[str, Any]:
        """Liveness snapshot served at ``GET /healthz``."""
        loop: _BatchLoop = self.loop  # type: ignore[attr-defined]
        last = loop.last_batch_at
        now = time.monotonic()
        return {
            "status": "ok",
            "name": getattr(self, "name", "serving"),
            "uptime_seconds": round(now - self._started_at, 3),
            "model_epoch": loop._epoch,
            "model_version": getattr(self, "model_version", None),
            "last_batch_age_seconds": (
                round(now - last, 3) if last is not None else None
            ),
            "uncommitted_epochs": len(loop.uncommitted_epochs),
            "inflight": (
                loop.admission.inflight if loop.admission is not None else None
            ),
        }

    def _make_handler(self, loop: _BatchLoop, input_col: str):
        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: connections persist across requests, so steady-state
            # clients skip TCP setup per call — the "sub-millisecond" serving
            # posture of the reference (mmlspark-serving.md) needs keep-alive.
            # Every response path MUST therefore carry Content-Length, or a
            # keep-alive client would block waiting for a close that never
            # comes. Nagle must be off: coalescing the status line with the
            # body write otherwise interacts with delayed ACKs into ~40 ms
            # stalls per keep-alive request.
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True

            def _reply_bytes(
                self, status: int, data: bytes,
                content_type: str = "application/json",
                extra_headers: Optional[Dict[str, str]] = None,
            ) -> None:
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                if extra_headers:
                    for k, v in extra_headers.items():
                        self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path == "/metrics":
                    body = loop.registry.exposition().encode("utf-8")
                    self._reply_bytes(
                        200, body,
                        content_type="text/plain; version=0.0.4; charset=utf-8",
                    )
                elif self.path == "/healthz":
                    self._reply_bytes(200, json.dumps(server.health()).encode())
                else:
                    self._reply_bytes(404, b'{"error": "not found"}')

            def do_POST(self):  # noqa: N802 (http.server API)
                # admit-or-shed BEFORE reading the body: an overloaded
                # server answers 429 + Retry-After in microseconds instead
                # of queueing work it will time out on (docs/resilience.md)
                admission = loop.admission
                if admission is not None and not admission.try_acquire():
                    self._reply_bytes(
                        429, b'{"error": "server overloaded"}',
                        extra_headers={
                            "Retry-After": f"{admission.retry_after_s:g}"
                        },
                    )
                    return
                try:
                    self._handle_admitted()
                finally:
                    if admission is not None:
                        admission.release()

            def _client_id(self) -> str:
                """Poison-breaker key: an explicit X-Client-Id beats the
                peer address (routers/proxies collapse many clients onto
                one address; the header keeps the breaker per-tenant)."""
                return (
                    self.headers.get("X-Client-Id")
                    or self.client_address[0]
                )

            def _reject(self, span, rid: str, client: str,
                        kind: str, detail: str) -> None:
                """Answer a malformed request with a structured 400 that
                still carries the trace id, book it against the client's
                malformed-rate budget, and keep it OUT of the batch loop
                (a bad payload must never poison co-batched requests)."""
                tracer = get_tracer()
                breaker = server.malformed_breaker
                if breaker is not None:
                    breaker.record_malformed(client, kind=kind)
                data = json.dumps({
                    "error": {"kind": kind, "detail": detail, "rid": rid},
                }).encode()
                try:
                    self._reply_bytes(
                        400, data,
                        extra_headers={TRACE_HEADER: span.trace_id},
                    )
                except OSError:
                    tracer.finish(span, status="disconnect")
                    return
                tracer.finish(span, status="400")
                bus = get_bus()
                if bus.active:
                    bus.publish(RequestServed(
                        rid=rid, status=400, latency=0.0,
                        trace_id=span.trace_id,
                    ))

            def _handle_admitted(self) -> None:
                rid = uuid.uuid4().hex
                tracer = get_tracer()
                # the span opens BEFORE the body is parsed: every answer —
                # including a malformed-payload 400 — carries X-Trace-Id,
                # so a client can always hand support a correlatable id
                #
                # listener threads carry no ambient span; a wire-propagated
                # TraceContext (the router's hop) is adopted so this
                # request->batch->apply chain parents under the router's
                # span in the merged fleet trace — otherwise the request
                # mints the trace root itself
                span = tracer.start_span(
                    "serving.request", rid=rid,
                    context=TraceContext.from_headers(self.headers),
                )
                client = self._client_id()
                # body is ALWAYS read before any reply — a keep-alive
                # connection with an unconsumed body desyncs on the next
                # request — so even the poison-shed path drains it first
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                breaker = server.malformed_breaker
                if breaker is not None and breaker.blocked(client):
                    breaker.note_shed(client)
                    retry_after = f"{breaker.reset_s:g}"
                    self._reply_bytes(
                        429, json.dumps({
                            "error": {"kind": "malformed-rate",
                                      "detail": "client shed by the poison "
                                                "breaker", "rid": rid},
                        }).encode(),
                        extra_headers={
                            "Retry-After": retry_after,
                            TRACE_HEADER: span.trace_id,
                        },
                    )
                    tracer.finish(span, status="429")
                    bus = get_bus()
                    if bus.active:
                        bus.publish(RequestShed(
                            reason="malformed_rate", queue_depth=0,
                            retry_after=breaker.reset_s, rid=rid,
                        ))
                    return
                try:
                    payload = json.loads(body) if body else None
                except json.JSONDecodeError as e:
                    self._reject(span, rid, client, "invalid-json", str(e))
                    return
                validator = server.request_validator
                if validator is not None:
                    rejection = validator.check_payload(payload)
                    if rejection is not None:
                        self._reject(span, rid, client, *rejection)
                        return
                if isinstance(payload, dict) and input_col in payload:
                    payload = payload[input_col]
                req = _PendingRequest(rid=rid, payload=payload)
                # deadline propagation: a caller-supplied X-Deadline-Ms wins;
                # otherwise the server's default request budget (if any)
                req.deadline = Deadline.from_header(
                    self.headers.get(DEADLINE_HEADER)
                )
                if req.deadline is None and server.request_deadline_s:
                    req.deadline = Deadline.after(server.request_deadline_s)
                req.span, req.trace_id = span, span.trace_id
                loop.submit(req)
                wait_s = server.reply_timeout_s
                if req.deadline is not None:
                    # never hold the connection past the caller's budget
                    wait_s = min(wait_s, max(0.0, req.deadline.remaining()))
                req.event.wait(timeout=wait_s)
                if req.response is None:
                    # the listener gives up: deregister the rid so the loop
                    # purges the request instead of computing into the void
                    loop.forget(req.rid)
                    status, data = 504, b'{"error": "timeout"}'
                else:
                    status, data = req.status, req.response
                try:
                    self._reply_bytes(
                        status, data,
                        extra_headers={TRACE_HEADER: span.trace_id},
                    )
                except OSError as e:
                    # client disconnect on the reply path: answer computed
                    # but unwritable — count it, don't stack-trace (the
                    # satellite fix; see docs/observability.md)
                    loop.note_reply_failure(req.rid, e)
                    tracer.finish(span, status="disconnect")
                    return
                tracer.finish(span, status=str(status))
                bus = get_bus()
                if bus.active:
                    bus.publish(RequestServed(
                        rid=req.rid, status=status,
                        latency=time.monotonic() - req.t_submit,
                        trace_id=req.trace_id,
                    ))

            def log_message(self, *args):  # silence default stderr logging
                pass

        return Handler


class ServingServer(_ListenerMixin):
    """Serve a ``Transformer`` (or a raw table->table callable) over HTTP.

    POST body: JSON ``{"<inputCol>": value}`` or a bare value; reply is the
    JSON of the output column for that row. Requests are micro-batched up to
    ``maxBatchSize`` or ``maxLatencyMs`` — the ``DynamicMiniBatchTransformer``
    idea applied at the serving edge so single-row latency stays low while
    the chip still sees batches.
    """

    def __init__(
        self,
        model: Transformer | Callable[[Table], Table],
        input_col: str = "input",
        output_col: str = "prediction",
        host: str = "127.0.0.1",
        port: int = 0,
        max_batch_size: int = 64,
        max_latency_ms: float = 2.0,
        max_retries: int = 1,
        name: str = "serving",
        loop: Optional[_BatchLoop] = None,
        registry=None,
        reply_timeout_s: float = 30.0,
        max_pending: int = 1024,
        shed_retry_after_s: float = 1.0,
        request_deadline_s: Optional[float] = None,
        drain_timeout_s: float = 5.0,
        request_validator: Any = None,
        malformed_breaker: Any = None,
        malformed_threshold: int = 16,
        malformed_window_s: float = 5.0,
        malformed_reset_s: float = 2.0,
    ):
        from mmlspark_tpu.dataguard.requestguard import (
            MalformedRateBreaker,
            RequestValidator,
        )

        self.input_col = input_col
        self.output_col = output_col
        self.name = name
        self._owns_loop = loop is None
        self._started_at = time.monotonic()
        #: how long a listener thread holds the connection waiting for the
        #: loop's reply (was a hardcoded 30 s)
        self.reply_timeout_s = float(reply_timeout_s)
        #: default per-request budget when the caller sends no X-Deadline-Ms
        self.request_deadline_s = request_deadline_s
        self.drain_timeout_s = float(drain_timeout_s)
        # pre-admission hardening (dataguard): payloads are validated
        # against the model's input contract before they can reach the
        # batch loop, and clients flooding malformed requests are shed
        # per-client — pass request_validator="off" to disable, or an
        # explicit RequestValidator to pin the contract
        if request_validator == "off":
            self.request_validator = None
        elif request_validator is None:
            self.request_validator = RequestValidator.for_model(
                model, input_col=input_col
            )
        else:
            self.request_validator = request_validator
        self.malformed_breaker = malformed_breaker or MalformedRateBreaker(
            threshold=malformed_threshold, window_s=malformed_window_s,
            reset_s=malformed_reset_s, registry=registry,
        )
        self.loop = loop or _BatchLoop(
            model, input_col, output_col, max_batch_size, max_latency_ms,
            max_retries, registry=registry,
            admission=AdmissionController(
                max_pending=max_pending, retry_after_s=shed_retry_after_s,
                registry=registry, name=name,
            ),
        )
        self._httpd = _Server((host, port), self._make_handler(self.loop, input_col))
        self.info = ServiceInfo(name, host, self._httpd.server_address[1])
        #: ModelStore version currently served (None = untracked); set by
        #: warm_restart_server and advanced by the hot-swap watcher
        self.model_version: Optional[int] = None
        self._swap_stop: Optional[threading.Event] = None
        self._swap_thread: Optional[threading.Thread] = None

    @property
    def model(self):
        return self.loop.model

    def heartbeat_stats(self) -> Dict[str, Any]:
        """The register/heartbeat payload this replica reports about
        itself: identity plus the live load metadata
        (``inflight``/``shed_total``/``p99_ms``) the fleet router and
        autoscaler steer by. Everything here is self-observed — the
        control plane never needs a handle into the replica process."""
        admission = self.loop.admission
        return {
            "name": self.info.name,
            "host": self.info.host,
            "port": self.info.port,
            "model_version": self.model_version,
            "inflight": admission.inflight if admission is not None else 0,
            "shed_total": (
                int(admission._shed.value) if admission is not None else 0
            ),
            "p99_ms": self.loop._reg_queue_wait.percentile(0.99) * 1e3,
        }

    # -- hot swap (live model replacement, zero downtime) --------------------

    def enable_hot_swap(
        self,
        loader: Callable[[str], Any],
        root: Optional[str] = None,
        name: str = "model",
        poll_s: float = 0.25,
    ) -> "ServingServer":
        """Watch the ModelStore ``CURRENT`` pointer under ``root`` and swap
        the live model the moment a new version commits — between requests,
        with no listener restart: the batch loop reads ``loop.model`` per
        micro-batch, so one attribute assignment is the whole cutover.
        Polling reads only the small CURRENT pointer
        (:meth:`~mmlspark_tpu.runtime.journal.ModelStore.current_version`);
        the model text is loaded and CRC-verified only when the version
        actually moved. A version that fails to load is skipped (the old
        model keeps serving) and retried next poll."""
        import os as _os

        from mmlspark_tpu.runtime.journal import ModelStore, default_checkpoint_dir

        root = root or default_checkpoint_dir()
        if root is None:
            raise ValueError(
                "hot swap needs a ModelStore root: pass root= or set "
                "MMLSPARK_TPU_CHECKPOINT_DIR"
            )
        store = ModelStore(_os.path.join(root, "models"))
        reg = self.loop.registry
        swaps = reg.counter(
            "serving_model_swaps_total", "Live model hot swaps"
        ).labels(server=self.name)
        version_g = reg.gauge(
            "serving_model_version", "ModelStore version currently served"
        ).labels(server=self.name)
        if self.model_version is not None:
            version_g.set(self.model_version)
        stop = threading.Event()

        def _watch() -> None:
            while not stop.wait(poll_s):
                try:
                    v = store.current_version(name)
                    if v is None or v == self.model_version:
                        continue
                    latest = store.latest(name)
                    if latest is None:
                        continue
                    version, text = latest
                    if version == self.model_version:
                        continue
                    model = loader(text)
                except Exception as e:  # noqa: BLE001 - keep serving old model
                    logger.warning(
                        "hot swap of %r failed (%s: %s); keeping v%s",
                        name, type(e).__name__, e, self.model_version,
                    )
                    continue
                # single attribute store = the atomic cutover: in-flight
                # batches finish on the old model, the next batch reads new
                self.loop.model = model
                self.model_version = version
                self.info.model_version = version
                self.loop.model_version = version
                monitor = _quality_monitor()
                if monitor is not None:
                    monitor.note_version(version)
                swaps.inc()
                version_g.set(version)
                logger.info(
                    "hot-swapped %r to v%06d on %s", name, version, self.name
                )
                bus = get_bus()
                if bus.active:
                    bus.publish(ModelSwapped(
                        name=name, version=version, server=self.name,
                    ))

        self._swap_stop = stop
        self._swap_thread = threading.Thread(
            target=_watch, daemon=True, name=f"hot-swap-{self.name}"
        )
        self._swap_thread.start()
        return self

    def start(self) -> "ServingServer":
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        if self._owns_loop:
            self.loop.start()
        return self

    def stop(self) -> None:
        if self._swap_stop is not None:
            self._swap_stop.set()
            if self._swap_thread is not None:
                self._swap_thread.join(timeout=5.0)
            self._swap_stop = self._swap_thread = None
        # graceful drain: stop accepting, answer what was admitted, THEN
        # stop the loop — reversing the old order, which could kill the
        # loop while listeners still held admitted-but-unanswered requests
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._owns_loop:
            self.loop.drain(timeout=self.drain_timeout_s)
            self.loop.stop()

    def __enter__(self) -> "ServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def _parse_load_metadata(info: Dict[str, Any]) -> Dict[str, Any]:
    """The optional load fields of a register/heartbeat payload, validated.
    Raises ``TypeError``/``ValueError`` on garbage (the caller answers 400)."""
    out: Dict[str, Any] = {}
    if info.get("inflight") is not None:
        out["inflight"] = int(info["inflight"])
    if info.get("shed_total") is not None:
        out["shed_total"] = int(info["shed_total"])
    if info.get("p99_ms") is not None:
        out["p99_ms"] = float(info["p99_ms"])
    return out


class RegistrationService:
    """Driver-side endpoint registry (``DriverServiceUtils:113-173``):
    workers POST their ServiceInfo to ``/register``; clients GET
    ``/services`` to discover every worker endpoint
    (``HTTPSourceStateHolder.serviceInfo``, ``HTTPSourceV2.scala:318-410``).

    With ``ttl_s`` set, every registration is a lease: replicas refresh it
    by POSTing ``/heartbeat`` (or calling :meth:`heartbeat` in-process),
    and a replica whose lease expires silently drops out of
    :attr:`services` — a crashed worker stops being discoverable without
    anyone deregistering it. ``ttl_s=None`` keeps the old everlasting
    registrations.

    With ``journal_dir`` set, the lease table is journaled to disk
    (tmp+rename with a CRC sidecar — the
    :class:`~mmlspark_tpu.runtime.journal.ModelStore` idiom) on every
    register/deregister, and a restarted registry recovers the journaled
    leases on construction with a fresh grace period — replicas keep
    heartbeating as if nothing happened instead of re-registering from
    scratch. Each recovered lease publishes a
    :class:`~mmlspark_tpu.observability.events.LeaseRecovered` event."""

    JOURNAL_NAME = "leases.json"

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        ttl_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        journal_dir: Optional[str] = None,
    ):
        self._services: Dict[str, ServiceInfo] = {}
        #: service name -> last register/heartbeat time (the lease stamp)
        self._last_seen: Dict[str, float] = {}
        self.ttl_s = ttl_s
        self._clock = clock
        self._journal_dir = journal_dir
        self._lock = threading.Lock()
        self._started_at = time.monotonic()
        if journal_dir is not None:
            self._recover_leases()
        registry = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802
                if self.path not in ("/register", "/heartbeat", "/deregister"):
                    self.send_response(404)
                    self.end_headers()
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    info = json.loads(self.rfile.read(length))
                    name = str(info["name"])
                except (KeyError, TypeError, ValueError) as e:
                    logger.debug("rejected malformed %s payload: %s", self.path, e)
                    self.send_response(400)
                    self.end_headers()
                    return
                if self.path == "/deregister":
                    # explicit retire: the lease is released NOW, not at
                    # TTL expiry — routers drop the replica on next poll
                    self.send_response(
                        200 if registry.deregister(name) else 404
                    )
                    self.end_headers()
                    return
                try:
                    raw_version = info.get("model_version")
                    model_version = (
                        int(raw_version) if raw_version is not None else None
                    )
                    load = _parse_load_metadata(info)
                except (TypeError, ValueError):
                    self.send_response(400)
                    self.end_headers()
                    return
                if self.path == "/heartbeat":
                    # lease refresh only: an unknown (expired/never-seen)
                    # name gets 404 so the replica knows to re-register
                    if not registry.heartbeat(name, model_version, **load):
                        self.send_response(404)
                        self.end_headers()
                        return
                    self.send_response(200)
                    self.end_headers()
                    return
                try:
                    svc = ServiceInfo(
                        name, info["host"], int(info["port"]),
                        model_version=model_version, **load,
                    )
                except (KeyError, TypeError, ValueError) as e:
                    logger.debug("rejected malformed /register payload: %s", e)
                    self.send_response(400)
                    self.end_headers()
                    return
                registry.register(svc)
                self.send_response(200)
                self.end_headers()

            def do_GET(self):  # noqa: N802
                ctype = "application/json"
                if self.path == "/services":
                    # load metadata is optional per lease: a replica that
                    # never heartbeat it gets the pre-fleet wire shape
                    body = json.dumps([
                        {k: v for k, v in vars(s).items()
                         if v is not None or k not in _LOAD_FIELDS}
                        for s in registry.services
                    ]).encode()
                elif self.path == "/metrics":
                    body = get_registry().exposition().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path == "/healthz":
                    n = len(registry.services)
                    body = json.dumps({
                        "status": "ok",
                        "uptime_seconds": round(
                            time.monotonic() - registry._started_at, 3
                        ),
                        "registered_services": n,
                    }).encode()
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        self._httpd = _Server((host, port), Handler)
        self.info = ServiceInfo("registry", host, self._httpd.server_address[1])

    @property
    def services(self) -> List[ServiceInfo]:
        """Live endpoints: lease-expired replicas are pruned on read."""
        with self._lock:
            self._prune_expired()
            return list(self._services.values())

    def _prune_expired(self) -> None:
        """Drop services whose lease lapsed. Caller holds ``self._lock``."""
        if self.ttl_s is None:
            return
        now = self._clock()
        pruned = False
        for name, seen in list(self._last_seen.items()):
            if now - seen > self.ttl_s:
                self._services.pop(name, None)
                del self._last_seen[name]
                pruned = True
                logger.warning(
                    "service %r lease expired (no heartbeat for > %.1fs); "
                    "dropped from discovery", name, self.ttl_s,
                )
        if pruned:
            self._journal_leases()

    # -- lease journal (registry restart survival) ---------------------------

    @property
    def _journal_path(self) -> Optional[str]:
        if self._journal_dir is None:
            return None
        return os.path.join(self._journal_dir, self.JOURNAL_NAME)

    def _journal_leases(self) -> None:
        """Snapshot the lease table to disk. Caller holds ``self._lock``.
        Written on register/deregister (membership changes), not on every
        heartbeat: recovery re-stamps each lease with a fresh grace
        period anyway, so journaling the refresh times would buy nothing
        but an fsync per heartbeat."""
        path = self._journal_path
        if path is None:
            return
        from mmlspark_tpu.runtime.journal import _atomic_write

        payload = json.dumps({
            "saved_at": time.time(),
            "leases": [vars(s) for s in self._services.values()],
        }).encode()
        try:
            os.makedirs(self._journal_dir, exist_ok=True)
            _atomic_write(path, payload)
            _atomic_write(path + ".crc", f"{zlib.crc32(payload):08x}".encode())
        except OSError:
            logger.warning("lease journal write failed", exc_info=True)

    def _recover_leases(self) -> None:
        """Reload journaled leases after a registry restart. Every
        recovered lease gets a fresh ``_last_seen`` stamp — the grace
        period restarts, giving live replicas one full TTL to land their
        next heartbeat before the lease can expire."""
        path = self._journal_path
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path, "rb") as f:
                payload = f.read()
            with open(path + ".crc", "rb") as f:
                want = f.read().decode().strip()
            if f"{zlib.crc32(payload):08x}" != want:
                logger.warning(
                    "lease journal CRC mismatch; discarding %s", path
                )
                return
            doc = json.loads(payload)
        except (OSError, ValueError) as e:
            logger.warning("lease journal unreadable (%s); starting empty", e)
            return
        age_s = max(0.0, time.time() - float(doc.get("saved_at", 0.0)))
        bus = get_bus()
        for rec in doc.get("leases", []):
            try:
                svc = ServiceInfo(
                    str(rec["name"]), str(rec["host"]), int(rec["port"]),
                    model_version=rec.get("model_version"),
                    **{k: rec[k] for k in _LOAD_FIELDS if rec.get(k) is not None},
                )
            except (KeyError, TypeError, ValueError):
                continue
            self._services[svc.name] = svc
            self._last_seen[svc.name] = self._clock()
            if bus.active:
                bus.publish(LeaseRecovered(
                    name=svc.name, url=svc.url, age_s=age_s,
                ))
        if self._services:
            logger.info(
                "recovered %d journaled lease(s) (%.1fs old) from %s",
                len(self._services), age_s, path,
            )

    def register(self, svc: ServiceInfo) -> None:
        with self._lock:
            self._services[svc.name] = svc
            self._last_seen[svc.name] = self._clock()
            self._journal_leases()

    def heartbeat(
        self,
        name: str,
        model_version: Optional[int] = None,
        inflight: Optional[int] = None,
        shed_total: Optional[int] = None,
        p99_ms: Optional[float] = None,
    ) -> bool:
        """Refresh ``name``'s lease; False when the service is unknown
        (expired or never registered) — the replica must re-register.
        ``model_version`` updates the lease metadata so ``/services``
        tracks which model version the replica currently serves (a hot
        swap shows up on the next heartbeat without re-registration);
        ``inflight``/``shed_total``/``p99_ms`` refresh the load metadata
        the fleet router and autoscaler read off ``/services``."""
        with self._lock:
            self._prune_expired()
            if name not in self._services:
                return False
            self._last_seen[name] = self._clock()
            svc = self._services[name]
            if model_version is not None:
                svc.model_version = int(model_version)
            if inflight is not None:
                svc.inflight = int(inflight)
            if shed_total is not None:
                svc.shed_total = int(shed_total)
            if p99_ms is not None:
                svc.p99_ms = float(p99_ms)
            return True

    def deregister(self, name: str) -> bool:
        """Drop ``name`` immediately (the autoscaler's retire path): the
        next ``/services`` read no longer lists it, so no router sends it
        another request. False when the name was not registered."""
        with self._lock:
            self._last_seen.pop(name, None)
            dropped = self._services.pop(name, None) is not None
            if dropped:
                self._journal_leases()
            return dropped

    def start(self) -> "RegistrationService":
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def __enter__(self) -> "RegistrationService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class DistributedServingServer:
    """N listeners sharing ONE micro-batch loop — the ``DistributedHTTPSource``
    shape: requests from every listener funnel into the shared queue, replies
    route back by request id regardless of the accepting listener (the
    cross-worker reply), and endpoints register with the driver's
    :class:`RegistrationService` the way worker servers report in
    (``reportServerToDriver``, ``HTTPSourceV2.scala:649-655``)."""

    def __init__(
        self,
        model,
        num_servers: int = 2,
        host: str = "127.0.0.1",
        name: str = "serving",
        registry: Optional[RegistrationService] = None,
        registry_url: Optional[str] = None,
        input_col: str = "input",
        output_col: str = "prediction",
        max_batch_size: int = 64,
        max_latency_ms: float = 2.0,
        max_retries: int = 1,
        base_port: int = 0,
        num_executors: int = 0,
        executor_policy=None,
        max_pending: int = 1024,
        shed_retry_after_s: float = 1.0,
        drain_timeout_s: float = 5.0,
        registry_heartbeat_s: Optional[float] = None,
        **kwargs,
    ):
        self.drain_timeout_s = float(drain_timeout_s)
        self._name = name
        #: lease-refresh cadence against a TTL'd RegistrationService;
        #: None disables the heartbeat thread
        self.registry_heartbeat_s = registry_heartbeat_s
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        # num_executors > 0 (or an ambient runtime.policy() / explicit
        # executor_policy) routes every micro-batch through the
        # fault-tolerant partition scheduler: the Spark-cluster posture
        # where batch evaluation runs on executors the driver can lose.
        self.scheduler = None
        from mmlspark_tpu import runtime

        pol = executor_policy or runtime.current_policy()
        if num_executors > 0 or pol is not None:
            pol = pol or runtime.SchedulerPolicy(max_workers=num_executors)
            self.scheduler = runtime.Scheduler(policy=pol)
        # ONE admission gate across all listeners: the shared loop is the
        # shared bottleneck, so the pending bound must be global too
        self.loop = _BatchLoop(
            model, input_col, output_col, max_batch_size, max_latency_ms,
            max_retries, scheduler=self.scheduler,
            admission=AdmissionController(
                max_pending=max_pending, retry_after_s=shed_retry_after_s,
                name=name,
            ),
        )
        # ONE poison breaker too: a flooding client spraying its malformed
        # requests across listeners must still accumulate into one budget
        if "malformed_breaker" not in kwargs:
            from mmlspark_tpu.dataguard.requestguard import MalformedRateBreaker

            kwargs["malformed_breaker"] = MalformedRateBreaker()
        # base_port > 0: listeners bind base_port, base_port+1, ... (the
        # deployable layout — k8s Services need declared ports); 0 keeps
        # OS-assigned ephemeral ports for tests.
        self.servers = [
            ServingServer(
                model, host=host, name=f"{name}-{i}", loop=self.loop,
                port=(base_port + i) if base_port else 0,
                input_col=input_col, output_col=output_col, **kwargs,
            )
            for i in range(num_servers)
        ]
        self._registry = registry
        self._registry_url = registry_url

    @property
    def service_info(self) -> List[ServiceInfo]:
        return [s.info for s in self.servers]

    def _register_endpoints(self) -> None:
        if self._registry is not None:
            for info in self.service_info:
                self._registry.register(info)
        if self._registry_url:
            import urllib.request

            for info in self.service_info:
                req = urllib.request.Request(
                    self._registry_url.rstrip("/") + "/register",
                    data=json.dumps(vars(info)).encode(),
                    method="POST",
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=5).read()

    # -- registry lease refresh ----------------------------------------------

    def _heartbeat_once(self) -> None:
        """Refresh every listener's lease; a rejected heartbeat (lease
        already expired) falls back to a full re-registration."""
        # all listeners share ONE loop/admission gate, so each lease
        # reports the same (global) load metadata — the router divides
        # traffic by replica, not by listener
        admission = self.loop.admission
        inflight = admission.inflight if admission is not None else None
        if self._registry is not None:
            for info in self.service_info:
                if not self._registry.heartbeat(
                    info.name, info.model_version, inflight=inflight
                ):
                    self._registry.register(info)
        if self._registry_url:
            import urllib.request

            base = self._registry_url.rstrip("/")
            for info in self.service_info:
                req = urllib.request.Request(
                    base + "/heartbeat",
                    data=json.dumps({
                        "name": info.name,
                        "model_version": info.model_version,
                        "inflight": inflight,
                    }).encode(),
                    method="POST",
                    headers={"Content-Type": "application/json"},
                )
                try:
                    urllib.request.urlopen(req, timeout=5).read()
                except Exception:
                    # expired or registry restarted: re-register from scratch
                    try:
                        self._register_endpoints()
                    except Exception:
                        logger.warning(
                            "registry heartbeat + re-register failed",
                            exc_info=True,
                        )
                    return

    def _heartbeat_loop(self) -> None:
        # seeded per-replica jitter (±20% of the period) de-synchronizes a
        # fleet's lease refreshes: after a registry restart every replica
        # would otherwise heartbeat in the same instant, and the recovered
        # registry would eat the whole fleet's refresh as one burst
        seed = int(os.environ.get("MMLSPARK_TPU_FAULT_SEED", "0") or 0)
        rng = random.Random(seed * 1_000_003 + zlib.crc32(self._name.encode()))
        while True:
            period = self.registry_heartbeat_s
            wait = period * (1.0 + 0.2 * (2.0 * rng.random() - 1.0))
            if self._hb_stop.wait(wait):
                return
            try:
                self._heartbeat_once()
            except Exception:
                logger.warning("registry heartbeat failed", exc_info=True)

    def start(self) -> "DistributedServingServer":
        self.loop.start()
        for s in self.servers:
            s.start()
        try:
            self._register_endpoints()
        except Exception:
            # a failed registration must not leak running listeners/ports
            logger.exception("endpoint registration failed; stopping servers")
            self.stop()
            raise
        if self.registry_heartbeat_s is not None:
            self._hb_stop.clear()
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True,
                name="registry-heartbeat",
            )
            self._hb_thread.start()
        return self

    def stop(self) -> None:
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=1.0)
            self._hb_thread = None
        # listeners first (stop accepting), drain the shared queue, then
        # stop the loop — admitted requests get answered, not dropped
        for s in self.servers:
            s.stop()
        self.loop.drain(timeout=self.drain_timeout_s)
        self.loop.stop()
        if self.scheduler is not None:
            # graceful executor drain, then teardown (Spark's
            # decommission-before-stop)
            self.scheduler.pool.drain(timeout=5.0)
            self.scheduler.close()

    def __enter__(self) -> "DistributedServingServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- warm restart (durable model recovery) -----------------------------------


def recover_model(
    loader: Callable[[str], Any],
    root: Optional[str] = None,
    name: str = "model",
):
    """Warm-restart recovery scan: load the last atomically committed
    model from the :class:`~mmlspark_tpu.runtime.journal.ModelStore`
    under ``root`` (default: the ambient ``MMLSPARK_TPU_CHECKPOINT_DIR``,
    where a durable ``fit`` commits) and rebuild it via ``loader(text)``
    — e.g. ``LightGBMClassificationModel.from_model_string``. Returns
    ``(version, model)`` or ``None`` when nothing was ever committed.
    A torn/corrupt CURRENT pointer falls back to the newest checksummed
    version, so a crash mid-commit can never resurrect a broken model."""
    import os

    from mmlspark_tpu.runtime.journal import ModelStore, default_checkpoint_dir

    root = root or default_checkpoint_dir()
    if root is None:
        return None
    store = ModelStore(os.path.join(root, "models"))
    latest = store.latest(name)
    if latest is None:
        return None
    version, text = latest
    return version, loader(text)


def warm_restart_server(
    loader: Callable[[str], Any],
    root: Optional[str] = None,
    name: str = "model",
    watch: bool = False,
    poll_s: float = 0.25,
    **server_kwargs,
) -> ServingServer:
    """Build a :class:`ServingServer` from the last committed model —
    the process-kill recovery path: the server that died mid-serve comes
    back serving exactly the model version that was last atomically
    committed. The recovered version is stamped into the server's
    :class:`ServiceInfo` lease metadata, so registering/heartbeating it
    against a :class:`RegistrationService` reports which version this
    replica serves. ``watch=True`` additionally starts the CURRENT-pointer
    watcher (:meth:`ServingServer.enable_hot_swap`), so later commits
    hot-swap in with no further restarts. Raises ``FileNotFoundError``
    when no committed model exists (nothing safe to serve)."""
    recovered = recover_model(loader, root=root, name=name)
    if recovered is None:
        raise FileNotFoundError(
            f"no committed model {name!r} found under "
            f"{root or 'MMLSPARK_TPU_CHECKPOINT_DIR'}; cannot warm-restart"
        )
    version, model = recovered
    logger.info("warm restart: serving committed model %s v%06d", name, version)
    server = ServingServer(model, **server_kwargs)
    server.model_version = version
    server.info.model_version = version
    server.loop.model_version = version
    monitor = _quality_monitor()
    if monitor is not None:
        monitor.note_version(version)
    if watch:
        server.enable_hot_swap(loader, root=root, name=name, poll_s=poll_s)
    return server
