"""Replica supervision: serving endpoints as real, restartable processes.

``DistributedServingServer`` multiplies LISTENERS inside one process; the
reference deployment multiplies PROCESSES — each Spark worker hosts its
own serving endpoint, and the platform restarts a worker whose JVM dies.
:class:`ReplicaSupervisor` is that layer, built on the same primitives as
the training-side process gang (:mod:`mmlspark_tpu.runtime.procgroup`):
scrubbed spawn environment, seeded port picking, heartbeat files,
structured :class:`~mmlspark_tpu.runtime.procgroup.ExitStatus` records,
``ProcessStarted``/``ProcessLost`` events, and
:class:`~mmlspark_tpu.runtime.health.HealthTracker` quarantine so a
crash-looping replica stops being restarted.

Unlike a fit gang, serving never "completes" and replicas never need a
collective: there is no rendezvous, no epochs, and loss of one replica
does not interrupt the others — ``poll()`` simply books the death and
respawns on a fresh port. A supervised replica process loads its model
itself (the ``factory`` entry point, typically wrapping
:func:`~mmlspark_tpu.serving.server.recover_model` against the shared
checkpoint root), so a replica that died mid-serve comes back serving the
last atomically committed model version.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import zlib
from pathlib import Path
from typing import Any, Dict, List, Optional

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.runtime.procgroup import (
    ExitStatus,
    _Heartbeat,
    _resolve_entry,
    _write_json,
    pick_port,
    scrub_env,
)

logger = get_logger("mmlspark_tpu.serving.replicas")


def demo_model_factory(spec: Dict[str, Any]):
    """A self-contained payload model for smoke tests and the chaos tool:
    ``prediction = 2 * input`` as a raw table->table callable."""
    import numpy as np

    from mmlspark_tpu.data.table import Table

    in_col = spec.get("server_options", {}).get("input_col", "input")
    out_col = spec.get("server_options", {}).get("output_col", "prediction")

    def model(table: Table) -> Table:
        x = np.asarray(table.column(in_col), dtype=np.float64)
        return Table({out_col: 2.0 * x})

    return model


def _registry_post(base: str, path: str, payload: Dict[str, Any]) -> None:
    """One POST to the registration service (raises on HTTP error)."""
    url = base.rstrip("/") + path
    # net chaos on the replica->registry edge: a partition raises
    # EHOSTUNREACH, a drop times out — the reporter's backoff path
    from mmlspark_tpu.runtime.faults import check_net

    check_net(url)
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        method="POST",
        headers={"Content-Type": "application/json"},
    )
    urllib.request.urlopen(req, timeout=5).read()


#: backoff ceiling, as a multiple of the heartbeat interval — a long
#: registry outage settles into a slow, jittered probe, not a tight loop
_BACKOFF_CAP_INTERVALS = 10.0


def _registry_reporter(server, registry_url: str, interval_s: float,
                       stop_evt: threading.Event) -> None:
    """Replica-side lease loop: register once, then heartbeat the live
    load metadata (``heartbeat_stats``) every ``interval_s``. A 404 means
    the lease expired (registry restart without a lease journal / TTL
    lapse while this process was stalled) — re-register from scratch. A
    down registry never stops the replica serving; the loop retries with
    seeded, jittered exponential backoff so a restarted registry gets the
    fleet's re-registrations spread out, not as one burst, and publishes
    :class:`~mmlspark_tpu.observability.events.RegistryUnavailable` once
    per outage onset. Heartbeat periods themselves carry seeded ±20%
    jitter (``MMLSPARK_TPU_FAULT_SEED`` + the replica name), so the fleet
    never phase-locks."""
    from mmlspark_tpu.observability.events import (
        RegistryRecovered,
        RegistryUnavailable,
        get_bus,
    )

    seed = int(os.environ.get("MMLSPARK_TPU_FAULT_SEED", "0") or 0)
    rng = random.Random(
        seed * 1_000_003 + zlib.crc32(server.info.name.encode())
    )
    registered = False
    down = False
    backoff = interval_s
    while not stop_evt.is_set():
        stats = server.heartbeat_stats()
        wait = interval_s * (1.0 + 0.2 * (2.0 * rng.random() - 1.0))
        try:
            if not registered:
                _registry_post(registry_url, "/register", stats)
                registered = True
            else:
                _registry_post(registry_url, "/heartbeat", stats)
            if down:
                down = False
                bus = get_bus()
                if bus.active:
                    bus.publish(RegistryRecovered(source="replica"))
                logger.info("replica %s regained the registry",
                            server.info.name)
            backoff = interval_s
        except urllib.error.HTTPError as e:
            if e.code == 404:
                # expired lease: re-register next tick, after a jittered
                # backoff (the whole fleet may have expired at once)
                registered = False
                wait = backoff * (0.5 + rng.random())
                backoff = min(backoff * 2.0,
                              _BACKOFF_CAP_INTERVALS * interval_s)
        except Exception as e:  # noqa: BLE001 - registry down; keep serving
            if not down:
                down = True
                bus = get_bus()
                if bus.active:
                    bus.publish(RegistryUnavailable(
                        source="replica", error=f"{type(e).__name__}: {e}",
                    ))
                logger.warning("replica %s lost the registry: %s",
                               server.info.name, e)
            wait = backoff * (0.5 + rng.random())
            backoff = min(backoff * 2.0, _BACKOFF_CAP_INTERVALS * interval_s)
        stop_evt.wait(wait)


def _replica_main(workdir: str, index: int) -> int:
    """One replica process: load the model via the factory entry, serve on
    the assigned port, heartbeat until the supervisor's stop file (global
    ``stop`` or the per-replica ``stop-<index>`` the retire path writes)."""
    from mmlspark_tpu.serving.server import ServingServer

    wd = Path(workdir)
    spec = json.loads((wd / f"replica-{index}.json").read_text())
    hb = _Heartbeat(wd / f"hb-{index}", interval=spec.get("hb_interval_s", 0.5))
    hb.start()
    reg_stop = threading.Event()
    registry_url = spec.get("registry_url")
    try:
        model = _resolve_entry(spec["factory"])(spec)
        server = ServingServer(
            model,
            host=spec.get("host", "127.0.0.1"),
            port=int(spec["port"]),
            name=f"{spec.get('name', 'replica')}-{index}",
            **spec.get("server_options", {}),
        )
        with server:
            swap = spec.get("hot_swap")
            if swap:
                # the replica watches ModelStore CURRENT itself, so a
                # mid-campaign commit swaps every replica with no restart
                server.enable_hot_swap(
                    _resolve_entry(swap["loader"]),
                    root=swap.get("root"),
                    name=swap.get("name", "model"),
                    poll_s=float(swap.get("poll_s", 0.25)),
                )
            if registry_url:
                threading.Thread(
                    target=_registry_reporter,
                    args=(server, registry_url,
                          float(spec.get("registry_heartbeat_s", 0.5)),
                          reg_stop),
                    daemon=True, name=f"replica-registry-{index}",
                ).start()
            _write_json(wd / f"ready-{index}.json",
                        {"url": server.info.url, "pid": os.getpid(),
                         "port": server.info.port})
            while not (wd / "stop").exists() \
                    and not (wd / f"stop-{index}").exists():
                time.sleep(0.1)
            if registry_url:
                # graceful exit: release the lease now instead of letting
                # it ride out the TTL (the retire path also deregisters
                # supervisor-side; a second deregister is a harmless 404)
                reg_stop.set()
                try:
                    _registry_post(
                        registry_url, "/deregister",
                        {"name": server.info.name},
                    )
                except Exception:  # noqa: BLE001 - registry already gone
                    pass
        return 0
    except Exception as e:  # noqa: BLE001 - report, then die visibly
        import traceback

        _write_json(wd / f"failed-{index}.json",
                    {"error": f"{type(e).__name__}: {e}",
                     "traceback": traceback.format_exc()})
        return 1
    finally:
        reg_stop.set()
        hb.stop()


class ReplicaSupervisor:
    """Spawn and babysit N serving-replica processes.

    ``factory`` is a ``"module:function"`` entry resolved INSIDE each
    replica process; it receives the replica spec dict and returns the
    model (a ``Transformer`` or table->table callable) to serve. Call
    :meth:`poll` periodically (or :meth:`watch` for a bounded loop):
    dead or heartbeat-silent replicas are booked as
    :class:`ExitStatus` + ``ProcessLost`` and respawned on a fresh port
    unless the health tracker has quarantined them.
    """

    def __init__(
        self,
        factory: str,
        num_replicas: int = 2,
        workdir: Optional[str] = None,
        host: str = "127.0.0.1",
        name: str = "replica",
        server_options: Optional[Dict[str, Any]] = None,
        env: Optional[Dict[str, str]] = None,
        seed: int = 0,
        heartbeat_timeout_s: float = 10.0,
        ready_timeout_s: float = 30.0,
        health=None,
        registry_url: Optional[str] = None,
        registry_heartbeat_s: float = 0.5,
        hot_swap: Optional[Dict[str, Any]] = None,
    ):
        from mmlspark_tpu.observability.registry import get_registry
        from mmlspark_tpu.runtime.health import HealthTracker

        if num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        self.factory = factory
        self.num_replicas = int(num_replicas)
        if workdir is None:
            import tempfile

            workdir = tempfile.mkdtemp(prefix="mmlspark-tpu-replicas-")
        self.workdir = Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)
        self.host = host
        self.name = name
        self.server_options = dict(server_options or {})
        self.env = scrub_env(env)
        self.seed = int(seed)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.ready_timeout_s = float(ready_timeout_s)
        # serving default: 2 quick deaths quarantine the slot (the replica
        # is crash-looping; restarting it a third time serves nobody)
        self.health = health or HealthTracker(
            threshold=2.0, window_s=600.0, parole_s=600.0
        )
        #: replicas POST /register + /heartbeat (with load metadata) here;
        #: retire_replica POSTs /deregister — the fleet control plane
        self.registry_url = registry_url
        self.registry_heartbeat_s = float(registry_heartbeat_s)
        #: optional ModelStore hot-swap spec passed through to every
        #: replica: {"loader": "module:fn", "root": ..., "name": ...}
        self.hot_swap = dict(hot_swap) if hot_swap else None
        self.exit_statuses: List[ExitStatus] = []
        self._procs: Dict[int, subprocess.Popen] = {}
        self._generations: Dict[int, int] = {}
        self._ports: Dict[int, int] = {}
        #: indices retired by the autoscaler: never respawned, never reused
        self._retired: set = set()
        self._next_index = int(num_replicas)
        reg = get_registry()
        self._metrics = {
            "started": reg.counter(
                "serving_replicas_started_total", "Replica processes spawned"
            ),
            "lost": reg.counter(
                "serving_replicas_lost_total", "Replica processes lost"
            ),
            "up": reg.gauge("serving_replicas_up", "Live serving replicas"),
        }

    # -- spawn ---------------------------------------------------------------

    def _spawn(self, index: int) -> None:
        from mmlspark_tpu.observability import ProcessStarted
        from mmlspark_tpu.observability.events import get_bus

        gen = self._generations.get(index, -1) + 1
        self._generations[index] = gen
        port = pick_port(
            seed=self.seed * 1000 + index * 100 + gen,
            exclude=set(self._ports.values()),
        )
        self._ports[index] = port
        for stale in (f"ready-{index}.json", f"failed-{index}.json",
                      f"stop-{index}"):
            try:
                (self.workdir / stale).unlink()
            except OSError:
                pass
        spec: Dict[str, Any] = {
            "factory": self.factory, "host": self.host, "port": port,
            "name": self.name, "server_options": self.server_options,
        }
        if self.registry_url:
            spec["registry_url"] = self.registry_url
            spec["registry_heartbeat_s"] = self.registry_heartbeat_s
        if self.hot_swap:
            spec["hot_swap"] = self.hot_swap
        _write_json(self.workdir / f"replica-{index}.json", spec)
        log_fh = open(self.workdir / f"log-{index}-{gen}.txt", "wb")
        # per-process event-log federation: the child inherits the shared
        # MMLSPARK_TPU_EVENT_LOG base but writes its own
        # ``<base>@replica-<index>`` segment, so two replicas never clobber
        # one live file / rotation sequence (observability.events.collect
        # folds the segments back together)
        env = dict(self.env)
        env["MMLSPARK_TPU_EVENT_LOG_PROCESS"] = f"replica-{index}"
        try:
            proc = subprocess.Popen(
                [sys.executable, "-m", "mmlspark_tpu.serving.replicas",
                 "--replica", str(self.workdir), str(index)],
                env=env, stdout=log_fh, stderr=subprocess.STDOUT,
                cwd=str(self.workdir),
            )
        finally:
            log_fh.close()
        self._procs[index] = proc
        self._metrics["started"].inc()
        bus = get_bus()
        if bus.active:
            bus.publish(ProcessStarted(member=index, pid=proc.pid, epoch=gen))
        logger.info("replica %d spawned pid %d port %d (gen %d)",
                    index, proc.pid, port, gen)

    def start(self) -> "ReplicaSupervisor":
        for index in range(self.num_replicas):
            self._spawn(index)
        self.wait_ready()
        return self

    def wait_ready(self, timeout_s: Optional[float] = None) -> None:
        deadline = time.monotonic() + (timeout_s or self.ready_timeout_s)
        while time.monotonic() < deadline:
            if all(
                (self.workdir / f"ready-{i}.json").exists()
                or i not in self._procs
                for i in range(self.num_replicas)
            ):
                self._metrics["up"].set(len(self._procs))
                return
            for i, proc in list(self._procs.items()):
                if proc.poll() is not None:
                    failed = self.workdir / f"failed-{i}.json"
                    detail = failed.read_text() if failed.exists() else ""
                    raise RuntimeError(
                        f"replica {i} died during startup "
                        f"(rc={proc.returncode}): {detail[:500]}"
                    )
            time.sleep(0.05)
        raise TimeoutError(
            f"replicas not ready within {timeout_s or self.ready_timeout_s}s"
        )

    # -- liveness ------------------------------------------------------------

    def urls(self) -> Dict[int, str]:
        out = {}
        for i in list(self._procs):
            path = self.workdir / f"ready-{i}.json"
            if path.exists():
                out[i] = json.loads(path.read_text())["url"]
        return out

    def _hb_stale(self, index: int) -> bool:
        path = self.workdir / f"hb-{index}"
        try:
            age = time.time() - path.stat().st_mtime
        except OSError:
            return False  # not yet written; startup is wait_ready's job
        return age > self.heartbeat_timeout_s

    def poll(self) -> List[ExitStatus]:
        """One supervision pass: book losses, respawn eligible replicas.
        Returns the losses observed in THIS pass."""
        from mmlspark_tpu.observability import ProcessLost
        from mmlspark_tpu.observability.events import get_bus

        losses: List[ExitStatus] = []
        for index, proc in list(self._procs.items()):
            if index in self._retired:
                continue  # retire_replica owns this slot's teardown
            rc = proc.poll()
            if rc is None and not self._hb_stale(index):
                continue
            if rc is None:
                proc.kill()
                proc.wait(timeout=5.0)
                reason = "heartbeat"
                rc = proc.returncode
            else:
                reason = f"signal:{-rc}" if rc < 0 else f"exit:{rc}"
            loss = ExitStatus(index, proc.pid, rc, reason,
                              self._generations[index])
            losses.append(loss)
            self.exit_statuses.append(loss)
            self._metrics["lost"].inc()
            bus = get_bus()
            if bus.active:
                bus.publish(ProcessLost(
                    member=index, pid=proc.pid, reason=reason,
                    epoch=self._generations[index],
                ))
            self.health.note_failure(index, reason=reason)
            del self._procs[index]
            if self.health.is_quarantined(index):
                logger.warning("replica %d quarantined; not restarting", index)
            else:
                self._spawn(index)
        self._metrics["up"].set(len(self._procs))
        return losses

    def watch(self, duration_s: float, interval_s: float = 0.5) -> None:
        """Poll for ``duration_s`` — the smoke-test supervision loop."""
        deadline = time.monotonic() + duration_s
        while time.monotonic() < deadline:
            self.poll()
            time.sleep(interval_s)

    # -- fleet scaling (driven by FleetController) ---------------------------

    @property
    def live_count(self) -> int:
        return len(self._procs)

    def add_replica(self, ready_timeout_s: Optional[float] = None) -> int:
        """Scale up by one: spawn a replica on a fresh index and block
        until its ready file appears (or it dies trying). Returns the new
        index. Retired indices are never reused, so the registry name
        ``<name>-<index>`` stays unambiguous across the fleet's life."""
        index = self._next_index
        self._next_index += 1
        self._spawn(index)
        deadline = time.monotonic() + (ready_timeout_s or self.ready_timeout_s)
        ready = self.workdir / f"ready-{index}.json"
        while time.monotonic() < deadline:
            if ready.exists():
                self._metrics["up"].set(len(self._procs))
                return index
            proc = self._procs.get(index)
            if proc is not None and proc.poll() is not None:
                failed = self.workdir / f"failed-{index}.json"
                detail = failed.read_text() if failed.exists() else ""
                raise RuntimeError(
                    f"replica {index} died during scale-up "
                    f"(rc={proc.returncode}): {detail[:500]}"
                )
            time.sleep(0.05)
        raise TimeoutError(f"replica {index} not ready during scale-up")

    def retire_replica(self, index: int, grace_s: float = 5.0) -> ExitStatus:
        """Scale down by one: deregister ``<name>-<index>`` from the
        registration service FIRST (no router sends it another request),
        then signal the per-replica stop file and wait for a graceful
        exit. The index is marked retired so :meth:`poll` never respawns
        it — an intentional retire is not a loss."""
        if index not in self._procs:
            raise KeyError(f"replica {index} is not running")
        self._retired.add(index)
        if self.registry_url:
            try:
                _registry_post(
                    self.registry_url, "/deregister",
                    {"name": f"{self.name}-{index}"},
                )
            except Exception:  # noqa: BLE001 - registry down; retire anyway
                logger.warning("deregister of replica %d failed", index,
                               exc_info=True)
        _write_json(self.workdir / f"stop-{index}", {"at": time.time()})
        proc = self._procs.pop(index)
        deadline = time.monotonic() + grace_s
        while proc.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=2.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)
        rc = proc.returncode
        status = ExitStatus(index, proc.pid, rc, "retired",
                            self._generations[index])
        self.exit_statuses.append(status)
        self._metrics["up"].set(len(self._procs))
        logger.info("replica %d retired (rc=%s)", index, rc)
        return status

    # -- teardown ------------------------------------------------------------

    def stop(self, grace_s: float = 5.0) -> List[ExitStatus]:
        _write_json(self.workdir / "stop", {"at": time.time()})
        deadline = time.monotonic() + grace_s
        final: List[ExitStatus] = []
        for index, proc in self._procs.items():
            while proc.poll() is None and time.monotonic() < deadline:
                time.sleep(0.05)
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=2.0)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10.0)
            rc = proc.returncode
            reason = f"signal:{-rc}" if rc and rc < 0 else f"exit:{rc}"
            final.append(ExitStatus(index, proc.pid, rc, reason,
                                    self._generations[index]))
        self._procs.clear()
        self._metrics["up"].set(0)
        return final

    def __enter__(self) -> "ReplicaSupervisor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def _main(argv: List[str]) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="mmlspark_tpu.serving.replicas")
    parser.add_argument("--replica", required=True, metavar="WORKDIR")
    parser.add_argument("index", type=int)
    args = parser.parse_args(argv)
    return _replica_main(args.replica, args.index)


if __name__ == "__main__":  # pragma: no cover - subprocess entry
    # canonical-module re-dispatch (same runpy identity trap as procgroup)
    from mmlspark_tpu.serving import replicas as _canonical

    sys.exit(_canonical._main(sys.argv[1:]))
