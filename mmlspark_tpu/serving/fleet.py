"""Metrics-driven autoscaler over a supervised replica fleet.

The pieces existed separately — :class:`ReplicaSupervisor` process gangs,
:class:`RegistrationService` TTL leases, hot swap on ModelStore CURRENT,
admission control + breakers — and :class:`FleetController` is what turns
them into the reference's "load-balanced continuous serving" posture: a
control loop that reads ONLY the public registry (``/services`` plus the
load metadata replicas heartbeat into their leases) and resizes the fleet
within ``[min_replicas, max_replicas]``:

- **scale up** when the mean heartbeat ``inflight`` per replica crosses
  ``scale_up_inflight``, when sheds start flowing (``scale_up_shed_rate``
  429s/second fleet-wide), or when any replica's queue-wait p99 crosses
  ``p99_up_ms``;
- **scale down** when the fleet has been idle (mean inflight below
  ``scale_down_inflight`` and zero sheds) for ``down_sustain_s`` —
  a single quiet sample never retires capacity;
- every action waits out ``cooldown_s`` before the next (no flapping),
  retires via :meth:`ReplicaSupervisor.retire_replica` (explicit
  ``/deregister`` first, so no router sends the victim another request),
  and publishes :class:`~mmlspark_tpu.observability.events.FleetScaled`.

The module also hosts the campaign payload factories (resolved by name
INSIDE replica processes, so they must live in an importable module):
:func:`store_model_factory` serves whatever the shared ModelStore's
CURRENT pointer names — the mid-storm hot-swap payload — and
:func:`sar_demo_factory` serves SAR top-k recommendation, the
recommendation workload as a fleet payload.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.observability.events import (
    FleetScaled,
    RegistryRecovered,
    RegistryUnavailable,
    get_bus,
)
from mmlspark_tpu.observability.registry import get_registry
from mmlspark_tpu.serving.replicas import ReplicaSupervisor
from mmlspark_tpu.serving.router import _parse_services
from mmlspark_tpu.serving.server import RegistrationService, ServiceInfo

logger = get_logger("mmlspark_tpu.serving.fleet")


class FleetController:
    """Autoscaler: registry load metadata in, spawn/retire decisions out.

    The controller holds the supervisor (the process plane) and a view of
    the registry (the control plane) but NEVER a handle into a replica:
    every signal it steers by arrived via a replica's own heartbeat."""

    def __init__(
        self,
        supervisor: ReplicaSupervisor,
        registry: Optional[RegistrationService] = None,
        registry_url: Optional[str] = None,
        federator: Optional[Any] = None,
        min_replicas: int = 1,
        max_replicas: int = 4,
        scale_up_inflight: float = 4.0,
        scale_down_inflight: float = 1.0,
        scale_up_shed_rate: float = 0.5,
        p99_up_ms: Optional[float] = None,
        cooldown_s: float = 3.0,
        down_sustain_s: float = 2.0,
        interval_s: float = 0.5,
        clock: Callable[[], float] = time.monotonic,
        alert_advisor: Optional[Callable[[], Any]] = None,
    ):
        if registry is None and registry_url is None:
            raise ValueError("need registry= or registry_url=")
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        self.supervisor = supervisor
        self._registry = registry
        self._registry_url = registry_url.rstrip("/") if registry_url else None
        #: optional MetricsFederator — when set, every control pass swaps
        #: the heartbeat load metadata for live /metrics scrapes, so the
        #: autoscaler steers on fleet-wide truth instead of lease lag
        self.federator = federator
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_inflight = float(scale_up_inflight)
        self.scale_down_inflight = float(scale_down_inflight)
        self.scale_up_shed_rate = float(scale_up_shed_rate)
        self.p99_up_ms = p99_up_ms
        self.cooldown_s = float(cooldown_s)
        self.down_sustain_s = float(down_sustain_s)
        self.interval_s = float(interval_s)
        self.clock = clock
        #: advisory hook (e.g. ``AlertEvaluator.active_alerts``): while it
        #: returns a truthy value the fleet is pinned non-idle, so an
        #: actively-burning SLO defers scale-down until the alert resolves
        self.alert_advisor = alert_advisor
        self._last_action_at: Optional[float] = None
        self._low_since: Optional[float] = None
        #: (total shed counter, at) from the previous pass — the shed RATE
        #: is a delta, cumulative counters never come back down
        self._last_shed: Optional[Tuple[int, float]] = None
        #: last-known-good ``/services`` snapshot, used (stamped stale)
        #: while the registry is unreachable so the control loop keeps
        #: supervising instead of going blind
        self._last_services: List[ServiceInfo] = []
        self._stale = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        reg = get_registry()
        self._m_replicas = reg.gauge(
            "fleet_replicas", "Supervised serving replicas in the fleet"
        )
        self._m_ups = reg.counter(
            "fleet_scale_ups_total", "Autoscaler scale-up actions"
        )
        self._m_downs = reg.counter(
            "fleet_scale_downs_total", "Autoscaler scale-down actions"
        )
        self._m_replicas.set(supervisor.live_count)

    # -- signals -------------------------------------------------------------

    def _services(self) -> List[ServiceInfo]:
        if self._registry is not None:
            return list(self._registry.services)
        url = self._registry_url + "/services"
        # same net-chaos edge as the router's discovery fetch
        from mmlspark_tpu.runtime.faults import check_net

        net = check_net(url)
        with urllib.request.urlopen(url, timeout=5) as resp:
            raw = resp.read()
        if net is not None and net.get("kind") == "corrupt":
            from mmlspark_tpu.runtime.netchaos import corrupt_bytes

            raw = corrupt_bytes(raw)
        return _parse_services(json.loads(raw))

    def _federated(self, services: List[ServiceInfo]) -> List[ServiceInfo]:
        """Swap heartbeat load metadata for scraped signals where the
        federator has them; a failed scrape round keeps the heartbeat
        values (federation must never blind the control loop)."""
        import dataclasses

        try:
            signals = self.federator.fleet_signals(services=[
                {"name": s.name, "host": s.host, "port": s.port}
                for s in services
            ])
        except Exception as e:  # noqa: BLE001 - replicas mid-churn
            logger.debug("fleet signals scrape failed: %s", e)
            return services
        out: List[ServiceInfo] = []
        for svc in services:
            sig = signals.get(svc.name)
            if not sig:
                out.append(svc)
                continue
            out.append(dataclasses.replace(
                svc,
                inflight=int(sig["inflight"]),
                shed_total=int(sig["shed_total"]),
                p99_ms=float(sig["p99_ms"]),
            ))
        return out

    def decide(
        self, services: List[ServiceInfo], now: Optional[float] = None
    ) -> Optional[Tuple[str, str]]:
        """One scaling decision from one ``/services`` snapshot:
        ``("up"|"down", reason)`` or None. Pure in the signals (the
        snapshot is the only input) but stateful in the pacing — cooldown,
        shed-rate deltas, and the sustained-idle window live here."""
        now = self.clock() if now is None else now
        live = self.supervisor.live_count
        inflights = [s.inflight or 0 for s in services]
        mean_inflight = sum(inflights) / len(inflights) if inflights else 0.0
        shed_total = sum(s.shed_total or 0 for s in services)
        shed_rate = 0.0
        if self._last_shed is not None:
            prev, at = self._last_shed
            dt = now - at
            if dt > 0:
                # max(0, ·): a retired replica leaving /services can step
                # the summed counter down; that is not negative shedding
                shed_rate = max(0, shed_total - prev) / dt
        self._last_shed = (shed_total, now)
        p99 = max((s.p99_ms or 0.0 for s in services), default=0.0)

        alerting = False
        if self.alert_advisor is not None:
            try:
                alerting = bool(self.alert_advisor())
            except Exception as e:  # noqa: BLE001 - advisory must not blind
                logger.debug("alert advisor failed: %s", e)
        busy = (
            mean_inflight >= self.scale_up_inflight
            or shed_rate >= self.scale_up_shed_rate
            or (self.p99_up_ms is not None and p99 >= self.p99_up_ms)
        )
        # a firing SLO alert pins the fleet non-idle: retiring capacity
        # mid-incident can only deepen the burn
        idle = (
            mean_inflight <= self.scale_down_inflight
            and shed_rate == 0.0
            and not alerting
        )
        if not idle:
            self._low_since = None
        elif self._low_since is None:
            self._low_since = now

        in_cooldown = (
            self._last_action_at is not None
            and now - self._last_action_at < self.cooldown_s
        )
        if live < self.min_replicas and not in_cooldown:
            return "up", f"below min ({live} < {self.min_replicas})"
        if in_cooldown:
            return None
        if busy and live < self.max_replicas:
            if shed_rate >= self.scale_up_shed_rate:
                reason = f"shed rate {shed_rate:.1f}/s"
            elif mean_inflight >= self.scale_up_inflight:
                reason = (
                    f"inflight {mean_inflight:.1f} >= "
                    f"{self.scale_up_inflight:g}"
                )
            else:
                reason = f"p99 {p99:.1f}ms >= {self.p99_up_ms:g}ms"
            return "up", reason
        if (
            live > self.min_replicas
            and self._low_since is not None
            and now - self._low_since >= self.down_sustain_s
        ):
            return "down", (
                f"idle {now - self._low_since:.1f}s "
                f"(inflight {mean_inflight:.1f})"
            )
        return None

    # -- actions -------------------------------------------------------------

    def _pick_victim(self, services: List[ServiceInfo]) -> Optional[int]:
        """The replica index to retire: the least-loaded registered
        replica that maps back to a live supervised slot; highest index
        breaks ties (newest capacity goes first)."""
        prefix = f"{self.supervisor.name}-"
        candidates: List[Tuple[int, int]] = []
        for svc in services:
            if not svc.name.startswith(prefix):
                continue
            try:
                index = int(svc.name[len(prefix):])
            except ValueError:
                continue
            if index in self.supervisor._procs:
                candidates.append((svc.inflight or 0, index))
        if not candidates:
            # registry view is stale/empty; fall back to the process plane
            live = list(self.supervisor._procs)
            return max(live) if len(live) > 1 else None
        candidates.sort(key=lambda c: (c[0], -c[1]))
        return candidates[0][1]

    def step(self) -> Optional[Tuple[str, str]]:
        """One control pass: supervise (respawn the dead), read the
        registry, maybe scale. Returns the action taken, if any."""
        self.supervisor.poll()
        stale = False
        try:
            services = self._services()
            self._last_services = services
            if self._stale:
                self._stale = False
                bus = get_bus()
                if bus.active:
                    bus.publish(RegistryRecovered(
                        source="controller", replicas=len(services),
                    ))
                logger.info("fleet controller regained the registry")
        except Exception as e:  # noqa: BLE001 - registry down/unreachable
            # registry outage tolerance: keep steering on the last-known-
            # good snapshot (stamped stale) — supervision and below-min
            # respawn must not stop because discovery did
            stale = True
            if not self._stale:
                self._stale = True
                bus = get_bus()
                if bus.active:
                    bus.publish(RegistryUnavailable(
                        source="controller",
                        error=f"{type(e).__name__}: {e}",
                        stale_replicas=len(self._last_services),
                    ))
            logger.warning(
                "fleet controller lost the registry (%s); using stale "
                "snapshot of %d lease(s)", e, len(self._last_services),
            )
            services = self._last_services
        if self.federator is not None:
            services = self._federated(services)
        decision = self.decide(services)
        if stale and decision is not None and decision[0] == "down":
            # stale load metadata can only look idle (nobody refreshed
            # it); never retire live capacity on an outage artifact
            logger.info("suppressing scale-down on stale registry snapshot")
            decision = None
        if decision is None:
            self._m_replicas.set(self.supervisor.live_count)
            return None
        direction, reason = decision
        if direction == "up":
            try:
                index = self.supervisor.add_replica()
            except (RuntimeError, TimeoutError) as e:
                # the spawn IS the scale-up; a slow (or once-crashed)
                # replica is now the supervisor poll loop's to finish
                logger.warning("scale-up replica not ready yet: %s", e)
                index = self.supervisor._next_index - 1
            self._m_ups.inc()
        else:
            victim = self._pick_victim(services)
            if victim is None:
                return None
            # retire_replica deregisters over registry_url; an in-process
            # registry (tests) needs the explicit call
            if self._registry is not None:
                self._registry.deregister(f"{self.supervisor.name}-{victim}")
            self.supervisor.retire_replica(victim)
            index = victim
            self._m_downs.inc()
        self._last_action_at = self.clock()
        self._low_since = None
        replicas = self.supervisor.live_count
        self._m_replicas.set(replicas)
        logger.info("fleet scaled %s to %d replicas (%s)",
                    direction, replicas, reason)
        bus = get_bus()
        if bus.active:
            bus.publish(FleetScaled(
                direction=direction, replicas=replicas,
                replica=index, reason=reason,
            ))
        return decision

    # -- lifecycle -----------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.step()
            except Exception:  # noqa: BLE001 - the loop must survive a bad pass
                logger.warning("fleet controller step failed", exc_info=True)

    def start(self) -> "FleetController":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="fleet-controller"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "FleetController":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- campaign payload factories (resolved inside replica processes) ----------


def store_model_loader(text: str):
    """ModelStore text -> affine model: the committed JSON
    ``{"scale": s, "bias": b}`` becomes ``prediction = s*input + b``.
    Distinguishable versions make the hot swap *observable*: the load
    generator knows which model version answered from the value alone.
    An optional ``work_ms`` stalls each micro-batch that long — the
    campaign's knob for making a replica saturable at small client
    counts without a heavyweight payload."""
    import numpy as np

    from mmlspark_tpu.data.table import Table

    spec = json.loads(text)
    scale = float(spec.get("scale", 1.0))
    bias = float(spec.get("bias", 0.0))
    work_ms = float(spec.get("work_ms", 0.0))

    def model(table: Table) -> Table:
        if work_ms > 0:
            time.sleep(work_ms / 1e3)
        x = np.asarray(table.column("input"), dtype=np.float64)
        return Table({"prediction": scale * x + bias})

    return model


def store_model_factory(spec: Dict[str, Any]):
    """Replica factory: serve the ModelStore CURRENT named by the replica
    spec's ``hot_swap`` block. A replica respawned mid-campaign comes
    back already on the latest committed version — the same recovery
    contract as :func:`~mmlspark_tpu.serving.server.recover_model`."""
    import os

    from mmlspark_tpu.runtime.journal import ModelStore

    swap = spec["hot_swap"]
    store = ModelStore(os.path.join(swap["root"], "models"))
    latest = store.latest(swap.get("name", "model"))
    if latest is None:
        return store_model_loader("{}")  # identity until the first commit
    _, text = latest
    return store_model_loader(text)


def sar_topk_model(model, num_items: int = 5):
    """Wrap a fitted :class:`~mmlspark_tpu.recommendation.sar.SARModel`
    as a serving callable: each request posts a user id, the reply is
    that user's top-``num_items`` item ids (unknown users get ``[-1...]``
    — cold start is an answer, not an error)."""
    import numpy as np

    from mmlspark_tpu.data.table import Table

    def serve(table: Table) -> Table:
        users = np.asarray(table.column("input"), dtype=np.int64)
        A = model.getUserAffinity()
        known = (users >= 0) & (users < A.shape[0])
        idx, _ = model._recommend(A[np.where(known, users, 0)], num_items)
        idx = np.where(known[:, None], idx, -1)
        return Table({"prediction": idx.astype(np.int64)})

    return serve


def sar_demo_factory(spec: Dict[str, Any]):
    """Replica factory for the recommendation payload: fit a small,
    seeded SAR inside the replica process and serve top-k retrieval.
    Every replica fits the identical model (same seed), so any replica
    answers any user — the stateless-replica property routing needs."""
    import numpy as np

    from mmlspark_tpu.data.table import Table
    from mmlspark_tpu.recommendation.sar import SAR

    opts = spec.get("sar", {})
    n_users = int(opts.get("n_users", 64))
    n_items = int(opts.get("n_items", 32))
    events = int(opts.get("events", 1024))
    rng = np.random.default_rng(int(opts.get("seed", 0)))
    table = Table({
        "user": rng.integers(0, n_users, events).astype(np.int64),
        "item": rng.integers(0, n_items, events).astype(np.int64),
        "rating": rng.uniform(0.5, 5.0, events),
    })
    model = SAR(userCol="user", itemCol="item", ratingCol="rating").fit(table)
    return sar_topk_model(model, num_items=int(opts.get("num_items", 5)))
