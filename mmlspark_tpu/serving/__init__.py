"""Model serving (reference Spark Serving, SURVEY.md §2.16)."""

from mmlspark_tpu.serving.fleet import FleetController
from mmlspark_tpu.serving.replicas import ReplicaSupervisor
from mmlspark_tpu.serving.router import FleetRouter
from mmlspark_tpu.serving.server import (
    DistributedServingServer,
    RegistrationService,
    ServiceInfo,
    ServingServer,
    recover_model,
    warm_restart_server,
)

__all__ = [
    "DistributedServingServer",
    "FleetController",
    "FleetRouter",
    "RegistrationService",
    "ReplicaSupervisor",
    "ServiceInfo",
    "ServingServer",
    "recover_model",
    "warm_restart_server",
]
