"""SLO reporting: fold the metrics registry + event log into one verdict.

``docs/serving_latency.md`` claims sub-5 ms p50 applies, but nothing in
the system folded the measured registry into a statement against targets
(ROADMAP item 4). :class:`SLOReport` is that fold — the Spark
structured-streaming "progress report" analogue for the serving plane:

    report = SLOReport.fold(get_registry(), events=replay(log_path))
    print(report.to_markdown())     # the docs/serving_latency.md table
    open("slo.json", "w").write(report.to_json())

Everything in the report is *derived*, never sampled twice: latency
quantiles come from the same ``serving_*`` histograms the Prometheus
endpoint exposes (so the report equals the registry fold exactly — the
determinism test asserts it), request/shed/error counts come from the
counters, and the end-to-end quantiles plus per-stage breakdown
(queue -> batch -> apply -> reply) come from replaying
:class:`~mmlspark_tpu.observability.events.RequestServed` latencies
against the stage histograms.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Iterable, List, Optional, Union

from mmlspark_tpu.observability.events import (
    AlertFired,
    AlertResolved,
    DriftCleared,
    DriftDetected,
    Event,
    RequestServed,
    RequestShed,
)
from mmlspark_tpu.observability.registry import MetricsRegistry


@dataclasses.dataclass(frozen=True)
class SLOTargets:
    """The serving objectives the report judges against (defaults are the
    docs/serving_latency.md claims: 5 ms median apply, 50 ms tail,
    three-nines availability)."""

    p50_ms: float = 5.0
    p99_ms: float = 50.0
    availability: float = 0.999

    def to_dict(self) -> Dict[str, float]:
        return dataclasses.asdict(self)


def _quantile(sorted_vals: List[float], q: float) -> float:
    """Linear-interpolated quantile of a sorted sample (0 when empty)."""
    if not sorted_vals:
        return 0.0
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * frac


def _scalar(summary: Dict[str, Any], name: str) -> float:
    """Counter/gauge value from a registry ``summary()`` dict; labeled
    series sum across children."""
    v = summary.get(name)
    if v is None:
        return 0.0
    if isinstance(v, dict):
        return float(sum(v.values()))
    return float(v)


def _hist(summary: Dict[str, Any], name: str) -> Dict[str, float]:
    v = summary.get(name)
    if isinstance(v, dict) and "count" in v:
        return {k: float(v[k]) for k in ("count", "sum", "p50", "p95", "p99")}
    return {"count": 0.0, "sum": 0.0, "p50": 0.0, "p95": 0.0, "p99": 0.0}


def fleet_summary(registry: MetricsRegistry) -> Dict[str, Any]:
    """Collapse a federated (``replica``-labeled) registry into the
    unlabeled summary shape :meth:`SLOReport.fold` reads. Counters and
    gauges already sum across children in the fold's ``_scalar``; the
    work here is histograms — a parent whose observations all live in
    labeled children reports ``count=0``, so merge the children
    bucket-for-bucket into one series before interpolating quantiles.
    Deterministic: the merge is pure addition over sorted metric names."""
    from mmlspark_tpu.observability.registry import Histogram

    summary = registry.summary()
    with registry._lock:
        metrics = dict(registry._metrics)
    for name, metric in sorted(metrics.items()):
        if not isinstance(metric, Histogram) or not metric._children:
            continue
        merged = Histogram(name, buckets=metric.buckets)
        for _, series in metric._series():
            with series._lock:
                counts = list(series._counts)  # type: ignore[attr-defined]
                total = series._count  # type: ignore[attr-defined]
                ssum = series._sum  # type: ignore[attr-defined]
            if len(counts) != len(merged._counts):
                continue  # child scraped with mismatched buckets
            merged._counts = [a + b for a, b in zip(merged._counts, counts)]
            merged._count += total
            merged._sum += ssum
        summary[name] = merged.summary()
    return summary


@dataclasses.dataclass
class SLOReport:
    """One serving-SLO verdict, derived from the registry + event log."""

    targets: SLOTargets
    requests: float
    shed: float
    expired: float
    reply_failures: float
    errors: float
    #: end-to-end request latency quantiles (seconds), from RequestServed
    e2e: Dict[str, float]
    #: per-stage summaries (count/sum/p50/p95/p99, seconds)
    stages: Dict[str, Dict[str, float]]
    batches: float = 0.0
    #: model-quality section (ISSUE 18): the per-feature drift table
    #: rebuilt from the ``quality_*`` gauges, the live ``alerts_active``
    #: gauge, and the drift/alert transition history from the event log
    quality: Dict[str, Any] = dataclasses.field(default_factory=dict)

    # -- derived -------------------------------------------------------------

    @property
    def shed_pct(self) -> float:
        offered = self.requests + self.shed
        return 100.0 * self.shed / offered if offered else 0.0

    @property
    def error_rate(self) -> float:
        return self.errors / self.requests if self.requests else 0.0

    @property
    def error_budget_consumed(self) -> float:
        """Fraction of the availability error budget spent (>1 = blown)."""
        budget = 1.0 - self.targets.availability
        return self.error_rate / budget if budget > 0 else 0.0

    @property
    def apply_p50_ms(self) -> float:
        return self.stages.get("apply", {}).get("p50", 0.0) * 1e3

    @property
    def apply_p99_ms(self) -> float:
        return self.stages.get("apply", {}).get("p99", 0.0) * 1e3

    def ok(self) -> bool:
        return (
            self.apply_p50_ms <= self.targets.p50_ms
            and self.apply_p99_ms <= self.targets.p99_ms
            and self.error_budget_consumed <= 1.0
        )

    # -- construction --------------------------------------------------------

    @classmethod
    def fold(
        cls,
        registry: Union[MetricsRegistry, Dict[str, Any], None],
        events: Optional[Iterable[Event]] = None,
        targets: Optional[SLOTargets] = None,
    ) -> "SLOReport":
        """Fold a registry (or a ``registry.summary()`` dict — the history
        server feeds a JSON snapshot) and an optional event stream into a
        report. Counters and stage quantiles come straight from the
        registry; the event stream adds end-to-end quantiles, HTTP-error
        counts, and fills shed/served counts when no registry is given."""
        targets = targets or SLOTargets()
        if registry is None:
            summary: Dict[str, Any] = {}
        elif isinstance(registry, MetricsRegistry):
            summary = registry.summary()
        else:
            summary = dict(registry)

        stages = {
            "queue": _hist(summary, "serving_queue_wait_seconds"),
            "apply": _hist(summary, "serving_apply_latency_seconds"),
        }
        requests = _scalar(summary, "serving_requests_total")
        shed = _scalar(summary, "serving_shed_total")
        expired = _scalar(summary, "serving_expired_total")
        reply_failures = _scalar(summary, "serving_replies_failed_total")
        batches = _scalar(summary, "serving_batches_total")

        latencies: List[float] = []
        errors = 0.0
        ev_served = 0.0
        ev_shed = 0.0
        drift_events: List[Dict[str, Any]] = []
        alert_history: List[Dict[str, Any]] = []
        for ev in events or ():
            if isinstance(ev, RequestServed):
                ev_served += 1
                latencies.append(float(ev.latency))
                if ev.status >= 500:
                    errors += 1
            elif isinstance(ev, RequestShed):
                ev_shed += 1
            elif isinstance(ev, (DriftDetected, DriftCleared)):
                drift_events.append({
                    "event": type(ev).__name__,
                    "feature": ev.feature,
                    "stat": ev.stat,
                    "value": float(ev.value),
                    "threshold": float(ev.threshold),
                })
            elif isinstance(ev, (AlertFired, AlertResolved)):
                alert_history.append({
                    "event": type(ev).__name__,
                    "alert": ev.alert,
                    "slo": ev.slo,
                    "burn_short": float(ev.burn_short),
                    "burn_long": float(ev.burn_long),
                })
        if requests == 0.0:
            requests = ev_served
        if shed == 0.0:
            shed = ev_shed

        from mmlspark_tpu.observability.quality import drift_table_from_summary

        quality = {
            "drift": drift_table_from_summary(summary),
            "alerts_active": _scalar(summary, "alerts_active"),
            "drift_events": drift_events,
            "alert_history": alert_history,
        }

        latencies.sort()
        e2e = {
            "count": float(len(latencies)),
            "p50": _quantile(latencies, 0.50),
            "p95": _quantile(latencies, 0.95),
            "p99": _quantile(latencies, 0.99),
        }
        # reply overhead: whatever end-to-end time queue+apply don't explain
        reply = max(
            0.0,
            e2e["p50"] - stages["queue"]["p50"] - stages["apply"]["p50"],
        )
        stages["reply"] = {
            "count": e2e["count"], "sum": 0.0,
            "p50": reply, "p95": 0.0, "p99": 0.0,
        }
        return cls(
            targets=targets,
            requests=requests,
            shed=shed,
            expired=expired,
            reply_failures=reply_failures,
            errors=errors,
            e2e=e2e,
            stages=stages,
            batches=batches,
            quality=quality,
        )

    @classmethod
    def fold_fleet(
        cls,
        registry: Union[MetricsRegistry, Dict[str, Any], None],
        events: Optional[Iterable[Event]] = None,
        targets: Optional[SLOTargets] = None,
    ) -> "SLOReport":
        """The fleet-wide verdict: fold a **federated** registry (every
        series ``replica``-labeled, from
        :meth:`~mmlspark_tpu.observability.federation.MetricsFederator.scrape`)
        plus a **merged** multi-process event stream (from
        :func:`~mmlspark_tpu.observability.events.merge`) into one report.
        Histogram children merge bucket-for-bucket first, so the fleet p99
        is interpolated over the union of observations, not the mean of
        per-replica quantiles."""
        if isinstance(registry, MetricsRegistry):
            registry = fleet_summary(registry)
        return cls.fold(registry, events=events, targets=targets)

    # -- renderers -----------------------------------------------------------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "targets": self.targets.to_dict(),
            "requests": self.requests,
            "shed": self.shed,
            "shed_pct": self.shed_pct,
            "expired": self.expired,
            "reply_failures": self.reply_failures,
            "errors": self.errors,
            "error_rate": self.error_rate,
            "error_budget_consumed": self.error_budget_consumed,
            "batches": self.batches,
            "e2e": self.e2e,
            "stages": self.stages,
            "quality": self.quality,
            "ok": self.ok(),
        }

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    def to_markdown(self) -> str:
        """The measured-SLO table docs/serving_latency.md embeds."""

        def _status(ok: bool) -> str:
            return "met" if ok else "**missed**"

        t = self.targets
        lines = [
            "| objective | target | measured | status |",
            "|---|---|---|---|",
            (
                f"| apply p50 | <= {t.p50_ms:g} ms | "
                f"{self.apply_p50_ms:.2f} ms | "
                f"{_status(self.apply_p50_ms <= t.p50_ms)} |"
            ),
            (
                f"| apply p99 | <= {t.p99_ms:g} ms | "
                f"{self.apply_p99_ms:.2f} ms | "
                f"{_status(self.apply_p99_ms <= t.p99_ms)} |"
            ),
            (
                f"| availability | >= {t.availability:.3%} | "
                f"{1.0 - self.error_rate:.3%} | "
                f"{_status(self.error_budget_consumed <= 1.0)} |"
            ),
            "",
            (
                f"Requests: {self.requests:.0f} served, {self.shed:.0f} shed "
                f"({self.shed_pct:.1f}%), {self.expired:.0f} expired, "
                f"{self.errors:.0f} server errors "
                f"(error budget consumed: "
                f"{self.error_budget_consumed:.1%})."
            ),
            "",
            "| stage | count | p50 | p95 | p99 |",
            "|---|---|---|---|---|",
        ]
        order = ["queue", "apply", "reply"]
        for stage in order + sorted(set(self.stages) - set(order)):
            s = self.stages.get(stage)
            if s is None:
                continue
            lines.append(
                f"| {stage} | {s['count']:.0f} | {s['p50'] * 1e3:.2f} ms "
                f"| {s['p95'] * 1e3:.2f} ms | {s['p99'] * 1e3:.2f} ms |"
            )
        if self.e2e["count"]:
            lines.append(
                f"| end-to-end | {self.e2e['count']:.0f} "
                f"| {self.e2e['p50'] * 1e3:.2f} ms "
                f"| {self.e2e['p95'] * 1e3:.2f} ms "
                f"| {self.e2e['p99'] * 1e3:.2f} ms |"
            )
        drift = self.quality.get("drift") or []
        if drift:
            lines += [
                "",
                "Model quality (vs reference profile):",
                "",
                "| feature | model | version | PSI | KS | drifted |",
                "|---|---|---|---|---|---|",
            ]
            for row in drift:
                lines.append(
                    f"| {row.get('feature', '')} | {row.get('model', '')} "
                    f"| {row.get('version', '')} | {row.get('psi', 0.0):.3f} "
                    f"| {row.get('ks', 0.0):.3f} "
                    f"| {'yes' if row.get('drifted') else 'no'} |"
                )
        history = self.quality.get("alert_history") or []
        if history:
            lines += [
                "",
                "| alert | slo | transition | burn short | burn long |",
                "|---|---|---|---|---|",
            ]
            for rec in history:
                lines.append(
                    f"| {rec.get('alert', '')} | {rec.get('slo', '')} "
                    f"| {rec.get('event', '')} "
                    f"| {rec.get('burn_short', 0.0):.2f}x "
                    f"| {rec.get('burn_long', 0.0):.2f}x |"
                )
        return "\n".join(lines)
