"""Typed event bus — the ListenerBus/event-log analogue (SURVEY.md §5).

Spark answers "what happened during this job" with its ListenerBus: every
subsystem posts typed events, listeners subscribe, and the event log
persists the stream for post-hoc replay in the UI. This module is that
plane for the TPU framework:

- typed events (:class:`StageStarted` .. :class:`ModelCommitted`) carry
  monotonic timestamps plus job/stage/task ids;
- :class:`EventBus` publishes synchronously to registered listeners
  (listener errors are logged, never propagated — a misbehaving listener
  must not fail a fit);
- :class:`EventLogSink` appends each event as one JSON line; setting
  ``MMLSPARK_TPU_EVENT_LOG=/path`` attaches it to the process-global bus;
- :func:`replay` reads a log back into events, and :func:`timeline`
  folds them into the summary the Spark UI would have drawn (per-stage
  durations, task retry/failure counts, request latency stats).

Publishing is engineered to be near-free when nobody listens: call sites
guard on ``bus.active`` so disabled runs don't even construct the event.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any, Callable, Dict, IO, Iterable, List, Optional, Type

from mmlspark_tpu.core.profiling import get_logger

logger = get_logger("mmlspark_tpu.observability")

_EVENT_TYPES: Dict[str, Type["Event"]] = {}


def _event(cls):
    """Register an event dataclass in the replay registry."""
    cls = dataclasses.dataclass(cls)
    _EVENT_TYPES[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class Event:
    """Base event: ``t`` is ``time.monotonic()`` at publish (durations and
    ordering within one process; wall-clock does not survive NTP steps)."""

    t: float = dataclasses.field(default=0.0, kw_only=True)

    def __post_init__(self) -> None:
        if not self.t:
            self.t = time.monotonic()

    def to_record(self) -> Dict[str, Any]:
        rec: Dict[str, Any] = {"event": type(self).__name__}
        rec.update(dataclasses.asdict(self))
        return rec


# -- pipeline ----------------------------------------------------------------


@_event
class StageStarted(Event):
    """``Pipeline.fit``/``transform`` entered a stage (SparkListenerStageSubmitted)."""

    job_id: int
    stage_id: int
    name: str
    phase: str = "fit"  # "fit" | "transform"


@_event
class StageCompleted(Event):
    """A stage finished (SparkListenerStageCompleted); ``status`` is "ok" or
    the exception class name."""

    job_id: int
    stage_id: int
    name: str
    duration: float
    phase: str = "fit"
    status: str = "ok"


# -- runtime scheduler -------------------------------------------------------


@_event
class TaskDispatched(Event):
    """The scheduler handed an attempt to the executor pool."""

    job_id: int
    task_id: int
    attempt: int
    queue_depth: int


@_event
class TaskRetried(Event):
    """An attempt failed within the retry budget; the task was re-queued."""

    job_id: int
    task_id: int
    failures: int
    reason: str


@_event
class TaskFailed(Event):
    """An attempt failed; ``permanent`` marks retry-budget exhaustion.
    ``worker``/``duration``/``speculative`` carry the structured attempt
    record (worker -1 = the attempt never reached a worker)."""

    job_id: int
    task_id: int
    reason: str
    permanent: bool = False
    worker: int = -1
    duration: float = 0.0
    speculative: bool = False
    attempt: int = 0


@_event
class TaskSpeculated(Event):
    """The scheduler launched a speculative duplicate of a running task
    whose age exceeded ``speculation_multiplier`` x the median run time
    (the ``spark.speculation`` re-launch)."""

    job_id: int
    task_id: int
    original_worker: int
    age: float
    median: float


@_event
class TaskRecovered(Event):
    """A task's result was restored from a journal checkpoint at job
    start — no dispatch, zero re-execution (RDD checkpoint recovery)."""

    job_id: int
    task_id: int


@_event
class WorkerQuarantined(Event):
    """The health tracker took a worker out of the dispatch pool after
    its rolling failure/straggle score crossed the threshold (the
    BlacklistTracker exclusion)."""

    worker: int
    score: float
    parole_s: float


@_event
class WorkerParoled(Event):
    """A quarantined worker's parole elapsed; it rejoins the pool with a
    clean history."""

    worker: int


# -- process group -----------------------------------------------------------


@_event
class ProcessStarted(Event):
    """The process-group supervisor spawned (or respawned) a member
    process for gang ``epoch`` (executor registration in the driver's
    worker-list rendezvous)."""

    member: int
    pid: int
    epoch: int


@_event
class ProcessLost(Event):
    """A member process died or went silent mid-epoch; ``reason`` is
    ``"exit:<code>"``, ``"signal:<sig>"`` or ``"heartbeat"`` (executor
    lost, the SparkListenerExecutorRemoved analogue)."""

    member: int
    pid: int
    reason: str
    epoch: int


@_event
class GroupReformed(Event):
    """Gang recovery completed: the group re-rendezvoused for ``epoch``
    with ``members`` live processes after losing ``lost``."""

    epoch: int
    members: int
    lost: int


@_event
class NetworkPartitioned(Event):
    """An epoch revoked with every process alive — a partitioned, lossy,
    or silent link stalled the collective past its io deadline. The
    supervisor resolved the gang's blame votes to ``member`` (the peer
    it killed so recovery can use the normal loss path); ``reason``
    concatenates each reporter's revocation message. Every onset must be
    followed by a ``GroupReformed`` recovery record
    (``check_eventlog.py --partition``)."""

    member: int
    epoch: int
    reason: str = ""


@_event
class PeerSlow(Event):
    """The collective's soft straggler detector: a round that succeeded
    but made a member wait at least the slow-peer threshold for
    ``member``'s frame. Booked as a health straggle, so a chronically
    slow peer is quarantined out of the next re-formation."""

    member: int
    epoch: int
    wait_s: float


# -- serving -----------------------------------------------------------------


@_event
class BatchFormed(Event):
    """The micro-batch loop gathered one batch (epoch = batch id)."""

    epoch: int
    size: int
    trace_id: str = ""


@_event
class RequestServed(Event):
    """One HTTP request was answered (status 499 = client disconnected
    before the reply could be written)."""

    rid: str
    status: int
    latency: float
    trace_id: str = ""


@_event
class ModelCommitted(Event):
    """A fitted model became current (end of ``fit`` / model swap)."""

    model: str
    version: int = 0
    detail: str = ""


# -- many-models sweep plane -------------------------------------------------


@_event
class SweepStarted(Event):
    """A hyperparameter sweep began: ``candidates`` param maps partitioned
    into ``buckets`` shape-buckets (each bucket = one compiled program).
    ``mode`` is "inline" or "gang" (ProcessGroup-sharded buckets)."""

    candidates: int
    buckets: int
    estimator: str = ""
    mode: str = "inline"


@_event
class CandidateBatchFitted(Event):
    """One shape-bucket finished fitting: ``size`` candidates trained in
    one vmapped program when ``batched`` (a singleton / non-batchable
    bucket fell back to the sequential fit)."""

    bucket: int
    size: int
    kind: str = ""
    batched: bool = True
    seconds: float = 0.0


@_event
class SweepCompleted(Event):
    """The sweep selected its best candidate (``best_index`` into the
    candidate list) and, when a checkpoint dir is configured, committed
    the refit best model as ModelStore ``version``."""

    candidates: int
    best_index: int
    best_metric: float
    version: int = -1
    seconds: float = 0.0


# -- serving fleet -----------------------------------------------------------


@_event
class FleetScaled(Event):
    """The autoscaler changed the fleet size: ``direction`` is "up" or
    "down", ``replicas`` the fleet size AFTER the action, ``replica`` the
    spawned/retired index, ``reason`` the signal that drove the decision
    (e.g. ``"inflight 9.5 > 8.0"``)."""

    direction: str
    replicas: int
    replica: int = -1
    reason: str = ""


@_event
class RequestRouted(Event):
    """The front-end router answered one request: ``replica`` is the
    endpoint that produced the final answer, ``hops`` the number of
    replica attempts it took (1 = first try; >1 means failovers the
    client never saw). ``trace_id`` is the id the router returned in
    ``X-Trace-Id`` — a user-quoted incident id joins directly against
    the event log."""

    rid: str
    replica: str
    hops: int
    status: int
    latency: float
    trace_id: str = ""


@_event
class RegistryUnavailable(Event):
    """A registry consumer (``source`` = "router" / "controller" /
    "replica") could not reach ``/services`` or heartbeat the
    :class:`RegistrationService`. Routers and controllers keep serving
    from their last-known-good table (``stale_replicas`` entries,
    stamped stale); replicas fall back to jittered re-registration.
    Published once per outage onset, not per failed poll."""

    source: str
    error: str
    stale_replicas: int = 0


@_event
class RegistryRecovered(Event):
    """The paired recovery for :class:`RegistryUnavailable`: the same
    consumer (``source``) reached the registry again and its routing
    table / heartbeat / steering snapshot is fresh. Published once per
    outage end, so the event log carries both edges of every registry
    outage and duration can be audited offline."""

    source: str
    replicas: int = 0


@_event
class LeaseRecovered(Event):
    """A restarted :class:`RegistrationService` recovered one journaled
    replica lease from disk (CRC-verified, ``age_s`` since it was
    journaled) — the fleet re-appears without any replica re-registering
    from scratch."""

    name: str
    url: str
    age_s: float = 0.0


# -- streaming ---------------------------------------------------------------


@_event
class StreamEpochStarted(Event):
    """The micro-batch engine planned epoch ``epoch`` over source offsets
    ``[start, end)`` and durably logged the plan (the offset-WAL write —
    Spark's ``StreamingQueryListener.QueryProgressEvent`` start edge)."""

    query: str
    epoch: int
    start: int
    end: int


@_event
class StreamSourceAdvanced(Event):
    """A source exposed new offsets that epoch planning consumed;
    ``units`` is the manifest length (files / blocks in the batch)."""

    query: str
    start: int
    end: int
    units: int = 0


@_event
class StreamEpochCommitted(Event):
    """Epoch ``epoch`` ran the sink and wrote its commit-log entry —
    the exactly-once boundary; a restart never re-plans this epoch."""

    query: str
    epoch: int
    rows: int
    duration: float = 0.0


@_event
class ModelSwapped(Event):
    """A serving listener hot-swapped its live model to ModelStore
    version ``version`` between requests — zero downtime, no restart."""

    name: str
    version: int
    server: str = ""


# -- profiler ----------------------------------------------------------------


@_event
class ProfileCompiled(Event):
    """The :class:`~mmlspark_tpu.observability.profiler.DeviceProfiler`
    saw a wrapped function compile a new executable (an executable-cache
    miss). ``seconds`` is the host wall time of the compiling call
    (trace + XLA compile + first execution); ``flops``/``bytes_accessed``
    are the XLA ``cost_analysis()`` estimates for one execution of the
    program, 0.0 when the backend declines to say."""

    name: str
    seconds: float
    flops: float = 0.0
    bytes_accessed: float = 0.0
    signature: str = ""


@_event
class ProfileExecuted(Event):
    """One profiled execution window: call through ``block_until_ready``
    on every output, against a warm executable cache."""

    name: str
    seconds: float


# -- gbdt histogram engine ---------------------------------------------------


@_event
class HistogramChunked(Event):
    """A GBDT fit's precomputed-U one-hot exceeded ``MMLSPARK_TPU_U_BUDGET``
    and the histogram pass was row-chunked instead of abandoning the MXU
    path (``lightgbm/train.py``): each pass streams ``num_chunks`` chunks
    of ``chunk_rows`` rows, rebuilding the chunk's one-hot in-trace and
    accumulating partial histograms. ``acc_dtype`` is the scan carry's
    accumulator dtype (narrow int on the quantized path) and
    ``bytes_saved`` the carry bytes that narrowing saved vs f32 — both
    recorded so incident bundles can tell this PLANNED optimization apart
    from the ``runtime/pressure.py`` degradation ladder's emergency
    re-chunking (``HistogramDegraded``)."""

    rows: int
    k_packed: int
    chunk_rows: int
    num_chunks: int
    budget_bytes: int
    acc_dtype: str = "float32"
    bytes_saved: int = 0


@_event
class HistogramSubtracted(Event):
    """A GBDT fit selected sibling histogram subtraction
    (``lightgbm/train.py``): each split's histogram pass builds only the
    SMALLER child and derives the sibling as parent - smaller, in packed
    (pre-EFB-expansion) space. ``children_per_split`` is 1 (vs 2 without
    subtraction), ``acc_dtype`` the cache/pass accumulator dtype (narrow
    int on the quantized path, where subtraction is integer-exact),
    ``cache_bytes`` the resident per-class leaf-histogram cache, and
    ``bytes_saved_per_tree`` the histogram-build bytes one tree avoids —
    the planned-optimization counterpart of ``HistogramDegraded``."""

    rows: int
    num_leaves: int
    packed_columns: int
    packed_bins: int
    acc_dtype: str
    cache_bytes: int
    bytes_saved_per_tree: int
    children_per_split: int = 1


@_event
class HistogramDegraded(Event):
    """A GBDT histogram launch hit ``RESOURCE_EXHAUSTED`` and the train
    loop stepped down the degradation ladder (halve the U budget ->
    chunked-U -> smaller leaf batch) before retrying the SAME iteration
    (``lightgbm/train.py``). ``stage`` is the dispatch path ("scan" or
    "loop"), ``retries`` the OOM retry count at this iteration, and the
    model text stays byte-identical to an undisturbed run."""

    rows: int
    budget_bytes: int
    chunk_rows: int
    stage: str
    iteration: int = 0
    retries: int = 1
    detail: str = ""


@_event
class FeatureBundled(Event):
    """Exclusive Feature Bundling fitted at binning time
    (``lightgbm/bundling.py``): ``k_before``/``k_after`` are Σ per-feature
    bin widths before/after packing — the HBM re-stream every histogram
    pass pays — and ``conflicts`` counts sampled rows where two bundled
    members were simultaneously non-default (bounded by
    ``max_conflict_rate`` x sample)."""

    num_features: int
    num_columns: int
    k_before: int
    k_after: int
    conflicts: int
    sample_rows: int


# -- tracing -----------------------------------------------------------------


@_event
class SpanRecorded(Event):
    """One finished tracer span, mirrored onto the bus so the event log
    carries the span stream (the history server's cross-process trace
    waterfall is rebuilt from these). ``parent_id`` is either a bare
    span id (same process) or ``<process>:<span_id>`` for a parent that
    lives across a wire hop; ``wall_start`` is ``time.time()`` at span
    start, the only clock comparable across processes."""

    name: str
    trace_id: str
    span_id: str
    parent_id: str = ""
    start: float = 0.0
    duration: float = 0.0
    wall_start: float = 0.0
    status: str = "ok"
    tags: Dict[str, Any] = dataclasses.field(default_factory=dict)


# -- incidents ---------------------------------------------------------------


@_event
class IncidentRecorded(Event):
    """The flight recorder dumped an incident bundle: ``trigger`` names
    the tripwire (``breaker_tripped`` / ``gang_failed`` / ``slo_budget``
    / ``worker_quarantined``), ``path`` the bundle directory, ``events``
    how many ring-buffer events it captured, ``trace_id`` the offending
    trace when one was known."""

    incident_id: str
    trigger: str
    path: str
    events: int = 0
    trace_id: str = ""
    detail: str = ""


@_event
class IncidentSkipped(Event):
    """The flight recorder hit a failure (ENOSPC, permissions) while
    dumping a bundle and dropped it instead of raising mid-incident —
    the observability plane must never make an outage worse."""

    trigger: str
    reason: str
    incident_id: str = ""


# -- resource pressure -------------------------------------------------------


@_event
class MemoryPressure(Event):
    """The resource watchdog (or an in-loop OOM catch) observed memory
    pressure: ``source`` is "hbm:<device>", "host", or "device" (an
    in-loop RESOURCE_EXHAUSTED); ``level`` is "warn"/"critical" at onset
    and "ok" on recovery, so every onset pairs with either a degradation
    event or a later "ok" record (``check_eventlog.py --pressure``)."""

    source: str
    level: str
    used_bytes: float
    limit_bytes: float
    detail: str = ""


@_event
class DiskPressure(Event):
    """Free space on a durable volume (checkpoint dir, event-log dir)
    crossed a watchdog threshold; ``level`` is "warn"/"critical" at
    onset and "ok" on recovery."""

    path: str
    level: str
    free_bytes: float
    total_bytes: float


# -- model quality -----------------------------------------------------------


@_event
class DriftDetected(Event):
    """A live-traffic drift statistic for one feature (or the score
    column) crossed its threshold against the served version's reference
    profile. Every onset pairs with a later :class:`DriftCleared` for the
    same feature once the rolling window recovers
    (``check_eventlog.py --quality``)."""

    feature: str
    stat: str  # "psi" | "ks"
    value: float
    threshold: float
    model: str = ""
    version: int = 0


@_event
class DriftCleared(Event):
    """The drift statistic for ``feature`` fell back under threshold —
    the recovery edge of :class:`DriftDetected`."""

    feature: str
    stat: str
    value: float
    threshold: float
    model: str = ""
    version: int = 0


@_event
class AlertFired(Event):
    """The multi-window burn-rate evaluator fired: the SLO named by
    ``alert`` is burning its error budget faster than ``threshold``x in
    BOTH windows. Pairs with a later :class:`AlertResolved` once the
    short window recovers."""

    alert: str  # "availability" | "latency"
    slo: str  # the judged objective, e.g. "p99<=50ms"
    burn_short: float
    burn_long: float
    window_short_s: float
    window_long_s: float
    threshold: float = 1.0
    detail: str = ""


@_event
class AlertResolved(Event):
    """The short-window burn rate for ``alert`` dropped back under
    threshold — the recovery edge of :class:`AlertFired`."""

    alert: str
    slo: str
    burn_short: float
    burn_long: float
    window_short_s: float
    window_long_s: float
    threshold: float = 1.0
    detail: str = ""


# -- resilience --------------------------------------------------------------


@_event
class BreakerTripped(Event):
    """A circuit breaker transitioned closed -> open: ``failures``
    failures inside ``window_s`` seconds (docs/resilience.md)."""

    breaker: str
    failures: int
    window_s: float


@_event
class RequestShed(Event):
    """Admission control rejected a request with 429 + Retry-After
    instead of queueing it (``reason`` names the exceeded bound)."""

    reason: str
    queue_depth: int
    retry_after: float = 0.0
    rid: str = ""


# -- dataguard ---------------------------------------------------------------


@_event
class RecordsDeadLettered(Event):
    """A read under ``mode=permissive`` (or a ``drop``-policy fit guard)
    quarantined ``count`` corrupt records into the dead-letter store for
    ``source`` under ``epoch``. Exactly one event per committed epoch —
    a replayed streaming epoch finds its DLQ manifest already present
    and publishes nothing (``check_eventlog.py --dataguard`` enforces
    the no-duplicate invariant)."""

    source: str
    epoch: int
    count: int
    reasons: str = ""


@_event
class PoisonClientBlocked(Event):
    """The per-client malformed-rate breaker tripped: ``client`` sent
    ``malformed`` malformed requests inside ``window_s`` seconds and is
    now shed with 429s. Pairs with a later :class:`PoisonClientReleased`."""

    client: str
    malformed: int
    window_s: float


@_event
class PoisonClientReleased(Event):
    """The poison breaker released ``client`` after ``blocked_s`` seconds
    — the recovery edge of :class:`PoisonClientBlocked`."""

    client: str
    blocked_s: float


# -- bus ---------------------------------------------------------------------


class EventBus:
    """Synchronous typed event bus (the ListenerBus analogue).

    Listeners are plain callables ``listener(event)``. ``publish`` runs
    them in registration order on the publishing thread; a listener that
    raises is logged at DEBUG and skipped — observability must never fail
    the observed workload.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._listeners: List[Callable[[Event], None]] = []

    @property
    def active(self) -> bool:
        """True when at least one listener is attached. Hot call sites
        guard event construction on this, so a quiet bus costs one
        attribute read."""
        return bool(self._listeners)

    def add_listener(self, listener: Callable[[Event], None]) -> None:
        with self._lock:
            if listener not in self._listeners:
                self._listeners = self._listeners + [listener]

    def remove_listener(self, listener: Callable[[Event], None]) -> None:
        # equality, not identity: a bound method (``obj.method``) is a new
        # object on every attribute access, but compares == to itself
        with self._lock:
            self._listeners = [l for l in self._listeners if l != listener]

    def publish(self, event: Event) -> None:
        for listener in self._listeners:  # snapshot semantics: list is replaced, not mutated
            try:
                listener(event)
            except Exception as e:  # noqa: BLE001 - listeners must not break the workload
                logger.debug("event listener %r failed: %s", listener, e)


#: process label pattern for per-process log suffixing; dots are excluded
#: so rotation suffixes (``.<seq>``) stay unambiguous
_PROCESS_SEP = "@"


def process_label() -> str:
    """This process's label in the federated event log: the value of
    ``MMLSPARK_TPU_EVENT_LOG_PROCESS`` (set by the spawner — replica
    supervisor, process group), or ``"driver"`` for the root process."""
    import os

    return os.environ.get("MMLSPARK_TPU_EVENT_LOG_PROCESS") or "driver"


def process_log_path(path: str, process: str) -> str:
    """The per-process event-log path for ``process`` under the shared
    base ``path``: ``<path>@<process>``. The base path itself belongs to
    the driver. Labels must not contain ``.``/``@``/path separators —
    rotation appends ``.<seq>`` and :func:`collect` parses it back off."""
    if any(c in process for c in (".", _PROCESS_SEP, "/", "\\")):
        raise ValueError(f"invalid process label {process!r}")
    return f"{path}{_PROCESS_SEP}{process}"


class EventLogSink:
    """JSON-lines event log: one ``{"event": <type>, ...}`` object per
    line, appended and flushed per event so a crash loses at most the
    in-flight record (the Spark event-log posture).

    The log is size-bounded (``spark.eventLog.rolling``): when a write
    would push the live file past ``max_bytes`` (default from
    ``MMLSPARK_TPU_EVENT_LOG_MAX_BYTES``; 0/unset = unbounded), the file
    rotates to ``<path>.<seq>`` with a monotonically increasing ``seq``
    and a fresh live file opens — a streaming/serving chaos run can no
    longer grow one file without limit. :func:`replay` reads the rotated
    segments oldest-first, then the live file, so the fold is unchanged.

    Every record is stamped with ``process`` (this process's federation
    label) and ``wt`` (``time.time()`` — the only clock comparable
    across processes); :func:`merge` orders the fleet stream by it.
    """

    def __init__(
        self,
        path: str,
        max_bytes: Optional[int] = None,
        process: Optional[str] = None,
    ):
        import os

        if max_bytes is None:
            max_bytes = int(
                os.environ.get("MMLSPARK_TPU_EVENT_LOG_MAX_BYTES", 0)
            ) or None
        self.path = path
        self.max_bytes = max_bytes
        self.process = process if process is not None else process_label()
        self._lock = threading.Lock()
        existing = [seq for seq, _ in _numbered_segments(path)]
        self._seq = max(existing) + 1 if existing else 1
        self._fh: Optional[IO[str]] = open(path, "a", encoding="utf-8")
        self._size = self._fh.tell()
        #: ENOSPC posture: failed writes are counted and dropped, never
        #: raised — losing event records must not fail the workload
        self.write_errors = 0
        self._warned_write_error = False

    def __call__(self, event: Event) -> None:
        rec = event.to_record()
        rec.setdefault("process", self.process)
        rec.setdefault("wt", time.time())
        line = json.dumps(rec) + "\n"
        with self._lock:
            if self._fh is None:
                return
            try:
                from mmlspark_tpu.runtime.faults import check_write

                check_write(self.path)
                # rotate BEFORE the write so a segment never exceeds the
                # bound; an empty live file always accepts (one oversized
                # event must not rotate forever)
                if (
                    self.max_bytes
                    and self._size
                    and self._size + len(line) > self.max_bytes
                ):
                    self._rotate()
                self._fh.write(line)
                self._fh.flush()
                self._size += len(line)
            except OSError as e:
                self.write_errors += 1
                self._count_write_error()
                if not self._warned_write_error:
                    self._warned_write_error = True
                    logger.warning(
                        "event log %s write failed (%s); dropping records "
                        "(counted in eventlog_write_errors_total)",
                        self.path, e,
                    )

    def _count_write_error(self) -> None:
        try:
            from mmlspark_tpu.observability.registry import get_registry

            get_registry().counter(
                "eventlog_write_errors_total",
                "Event-log records dropped because the write/rotation failed",
            ).inc()
        except Exception:  # noqa: BLE001 - metrics must not break the drop path
            pass

    def _rotate(self) -> None:
        """Close the live file and shelve it as the next numbered
        segment (caller holds ``_lock``)."""
        import os

        assert self._fh is not None
        self._fh.close()
        os.replace(self.path, f"{self.path}.{self._seq}")
        self._seq += 1
        self._fh = open(self.path, "a", encoding="utf-8")
        self._size = 0

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# -- process-global bus + env-driven sink ------------------------------------

_BUS = EventBus()
_ENV_SINK: Optional[EventLogSink] = None
_ENV_LOCK = threading.Lock()


def get_bus() -> EventBus:
    """The process-global bus. Each call re-syncs the env-driven sink:
    setting ``MMLSPARK_TPU_EVENT_LOG=/path`` before a component grabs the
    bus attaches the JSON-lines sink; unsetting it detaches. A child
    process additionally carrying ``MMLSPARK_TPU_EVENT_LOG_PROCESS=<label>``
    (set by its spawner) writes to the per-process sibling
    ``/path@<label>`` instead — two processes inheriting the same base
    path no longer clobber each other's live file and rotation sequence."""
    _sync_env_sink()
    return _BUS


def _sync_env_sink() -> None:
    global _ENV_SINK
    import os

    path = os.environ.get("MMLSPARK_TPU_EVENT_LOG")
    label = os.environ.get("MMLSPARK_TPU_EVENT_LOG_PROCESS") or "driver"
    if path and label != "driver":
        try:
            effective: Optional[str] = process_log_path(path, label)
        except ValueError:
            logger.warning(
                "MMLSPARK_TPU_EVENT_LOG_PROCESS=%s invalid; logging as driver",
                label,
            )
            effective, label = path, "driver"
    else:
        effective = path
    current = _ENV_SINK.path if _ENV_SINK is not None else None
    if effective == current:
        return
    with _ENV_LOCK:
        if _ENV_SINK is not None:
            _BUS.remove_listener(_ENV_SINK)
            _ENV_SINK.close()
            _ENV_SINK = None
        if effective:
            try:
                _ENV_SINK = EventLogSink(effective, process=label)
            except OSError as e:
                logger.warning("MMLSPARK_TPU_EVENT_LOG=%s unusable: %s", path, e)
                return
            _BUS.add_listener(_ENV_SINK)


# -- replay + timeline -------------------------------------------------------


def from_record(rec: Dict[str, Any]) -> Event:
    """Rebuild a typed event from one decoded JSON-lines record."""
    kind = rec.get("event")
    cls = _EVENT_TYPES.get(kind or "")
    if cls is None:
        raise ValueError(f"unknown event type {kind!r}")
    fields = {f.name for f in dataclasses.fields(cls)}
    return cls(**{k: v for k, v in rec.items() if k in fields})


def _numbered_segments(path: str) -> List[tuple]:
    """(seq, segment_path) pairs for the rotated segments of ``path``,
    unsorted; ``<path>.<digits>`` only, so unrelated siblings never
    count."""
    import glob
    import os

    out = []
    for p in glob.glob(glob.escape(path) + ".*"):
        suffix = p[len(path) + 1:]
        if suffix.isdigit() and os.path.isfile(p):
            out.append((int(suffix), p))
    return out


def log_segments(path: str) -> List[str]:
    """Every file of a (possibly rotated) event log in write order:
    numbered segments oldest-first, then the live file."""
    import os

    out = [p for _, p in sorted(_numbered_segments(path))]
    if os.path.exists(path) or not out:
        out.append(path)
    return out


def _stamp(ev: Event, rec: Dict[str, Any], process: str = "") -> Event:
    """Carry the sink-level federation stamps (``process``, ``wt``)
    through to the typed event as plain attributes — they are not
    dataclass fields, so single-process records and equality semantics
    are untouched."""
    ev.process = rec.get("process") or process  # type: ignore[attr-defined]
    ev.wt = float(rec.get("wt") or 0.0)  # type: ignore[attr-defined]
    return ev


def replay(path: str) -> List[Event]:
    """Read an event log back into typed events (skips blank lines).
    Rotated segments (``<path>.1``, ``<path>.2``, ...) are read in
    order before the live file, so a size-bounded log replays whole.
    Records carrying federation stamps (``process``/``wt``) surface them
    as event attributes, so replaying a merged fleet log keeps the
    process tags."""
    out: List[Event] = []
    for segment in log_segments(path):
        with open(segment, "r", encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if line:
                    rec = json.loads(line)
                    out.append(_stamp(from_record(rec), rec))
    return out


# -- fleet federation --------------------------------------------------------


def collect(path: str) -> Dict[str, List[str]]:
    """Discover every process's segments of a federated event log rooted
    at ``path``: the driver's own (possibly rotated) log plus every
    per-process sibling ``<path>@<label>`` written by child processes.
    Returns ``{label: [segment, ...]}`` in write order per process."""
    import glob
    import os

    out: Dict[str, List[str]] = {}
    if os.path.exists(path) or _numbered_segments(path):
        out["driver"] = log_segments(path)
    labels = set()
    for p in glob.glob(glob.escape(path) + _PROCESS_SEP + "*"):
        suffix = p[len(path) + 1:]
        # strip a rotation suffix (".<digits>") back off the live name
        stem, dot, tail = suffix.rpartition(".")
        if dot and tail.isdigit():
            suffix = stem
        if suffix:
            labels.add(suffix)
    for label in sorted(labels):
        out[label] = log_segments(process_log_path(path, label))
    return out


def _merged_records(path: str) -> List[Dict[str, Any]]:
    """Every process's records folded into one timestamp-ordered stream.
    Order is deterministic for a fixed set of files: sorted by the
    wall-clock stamp, ties broken by (process label, in-process order) —
    re-merging the same segments is byte-identical."""
    keyed: List[tuple] = []
    for process, segments in collect(path).items():
        idx = 0
        for segment in segments:
            with open(segment, "r", encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    rec = json.loads(line)
                    rec.setdefault("process", process)
                    keyed.append(
                        (float(rec.get("wt") or 0.0), process, idx, rec)
                    )
                    idx += 1
    keyed.sort(key=lambda item: item[:3])
    return [rec for _, _, _, rec in keyed]


def merge(path: str) -> List[Event]:
    """The federated replay: fold every process's segments (see
    :func:`collect`) into one timestamp-ordered, process-tagged event
    stream. Each event carries ``.process`` and ``.wt`` attributes;
    :func:`timeline`, :class:`~mmlspark_tpu.observability.slo.SLOReport`
    and the history server consume the stream unchanged."""
    return [
        _stamp(from_record(rec), rec, process=rec.get("process", ""))
        for rec in _merged_records(path)
    ]


def write_merged(path: str, out_path: str) -> int:
    """Materialize the merged fleet stream as one JSON-lines file (the
    artifact CI validates and the history server renders); returns the
    record count. The write is atomic (tmp + ``os.replace``)."""
    import os

    records = _merged_records(path)
    tmp = f"{out_path}.tmp-{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as fh:
        for rec in records:
            fh.write(json.dumps(rec) + "\n")
    os.replace(tmp, out_path)
    return len(records)


def timeline(events: Iterable[Event]) -> Dict[str, Any]:
    """Fold an event stream into the summary the Spark UI would draw:
    per-stage wall times, task dispatch/retry/failure counts, serving
    batch/request stats, committed models."""
    stages: Dict[Any, Dict[str, Any]] = {}
    tasks = {
        "dispatched": 0, "retried": 0, "failed": 0, "failed_permanent": 0,
        "speculated": 0, "recovered": 0,
    }
    retry_reasons: Dict[str, int] = {}
    #: per-task structured attempt history folded from TaskFailed events
    attempts: Dict[int, List[Dict[str, Any]]] = {}
    quarantines: Dict[int, int] = {}
    paroles = 0
    processes = {"started": 0, "lost": 0, "reformed": 0}
    loss_reasons: Dict[str, int] = {}
    batches = {"count": 0, "rows": 0}
    latencies: List[float] = []
    statuses: Dict[int, int] = {}
    models: List[str] = []
    shed = 0
    breaker_trips: Dict[str, int] = {}
    streaming = {"epochs": 0, "rows": 0, "source_units": 0}
    stream_epochs: Dict[str, List[int]] = {}
    swaps: List[Dict[str, Any]] = []
    fleet: List[Dict[str, Any]] = []
    routing = {"count": 0, "hops": 0, "failovers": 0}
    routed_statuses: Dict[int, int] = {}
    routed_by_replica: Dict[str, int] = {}
    #: per-function compile/execute fold from Profile* events
    profiler: Dict[str, Dict[str, Any]] = {}
    incidents: List[Dict[str, Any]] = []
    incidents_skipped = 0
    pressure: List[Dict[str, Any]] = []
    degradations: List[Dict[str, Any]] = []
    #: PLANNED histogram-engine optimizations (subtraction / chunking) —
    #: kept separate from `degradations` so incident bundles distinguish
    #: a configured byte-saving path from an emergency pressure response
    hist_optimizations: List[Dict[str, Any]] = []
    #: drift onsets/clears per feature (the model-quality plane)
    quality = {"detected": 0, "cleared": 0}
    drift_features: Dict[str, Dict[str, int]] = {}
    #: burn-rate alert history, in stream order
    alerts = {"fired": 0, "resolved": 0}
    alert_history: List[Dict[str, Any]] = []
    #: events per federation process label ("" = untagged single-process log)
    by_process: Dict[str, int] = {}
    for ev in events:
        proc = getattr(ev, "process", "")
        if proc:
            by_process[proc] = by_process.get(proc, 0) + 1
        if isinstance(ev, StageStarted):
            stages.setdefault(
                (ev.job_id, ev.stage_id, ev.phase),
                {"name": ev.name, "phase": ev.phase, "start": ev.t},
            )
        elif isinstance(ev, StageCompleted):
            rec = stages.setdefault(
                (ev.job_id, ev.stage_id, ev.phase),
                {"name": ev.name, "phase": ev.phase, "start": ev.t - ev.duration},
            )
            rec["duration"] = ev.duration
            rec["status"] = ev.status
        elif isinstance(ev, TaskDispatched):
            tasks["dispatched"] += 1
        elif isinstance(ev, TaskRetried):
            tasks["retried"] += 1
            retry_reasons[ev.reason] = retry_reasons.get(ev.reason, 0) + 1
        elif isinstance(ev, TaskFailed):
            tasks["failed"] += 1
            if ev.permanent:
                tasks["failed_permanent"] += 1
            attempts.setdefault(ev.task_id, []).append({
                "attempt": ev.attempt, "worker": ev.worker,
                "reason": ev.reason, "duration": ev.duration,
                "speculative": ev.speculative, "permanent": ev.permanent,
            })
        elif isinstance(ev, TaskSpeculated):
            tasks["speculated"] += 1
        elif isinstance(ev, TaskRecovered):
            tasks["recovered"] += 1
        elif isinstance(ev, WorkerQuarantined):
            quarantines[ev.worker] = quarantines.get(ev.worker, 0) + 1
        elif isinstance(ev, WorkerParoled):
            paroles += 1
        elif isinstance(ev, ProcessStarted):
            processes["started"] += 1
        elif isinstance(ev, ProcessLost):
            processes["lost"] += 1
            loss_reasons[ev.reason] = loss_reasons.get(ev.reason, 0) + 1
        elif isinstance(ev, GroupReformed):
            processes["reformed"] += 1
        elif isinstance(ev, BatchFormed):
            batches["count"] += 1
            batches["rows"] += ev.size
        elif isinstance(ev, RequestServed):
            latencies.append(ev.latency)
            statuses[ev.status] = statuses.get(ev.status, 0) + 1
        elif isinstance(ev, ModelCommitted):
            models.append(ev.model)
        elif isinstance(ev, StreamSourceAdvanced):
            streaming["source_units"] += ev.units
        elif isinstance(ev, StreamEpochCommitted):
            streaming["epochs"] += 1
            streaming["rows"] += ev.rows
            stream_epochs.setdefault(ev.query, []).append(ev.epoch)
        elif isinstance(ev, ModelSwapped):
            swaps.append({"name": ev.name, "version": ev.version,
                          "server": ev.server})
        elif isinstance(ev, FleetScaled):
            fleet.append({"direction": ev.direction, "replicas": ev.replicas,
                          "replica": ev.replica, "reason": ev.reason,
                          "t": ev.t})
        elif isinstance(ev, RequestRouted):
            routing["count"] += 1
            routing["hops"] += ev.hops
            if ev.hops > 1:
                routing["failovers"] += 1
            routed_statuses[ev.status] = routed_statuses.get(ev.status, 0) + 1
            routed_by_replica[ev.replica] = (
                routed_by_replica.get(ev.replica, 0) + 1
            )
        elif isinstance(ev, RequestShed):
            shed += 1
        elif isinstance(ev, BreakerTripped):
            breaker_trips[ev.breaker] = breaker_trips.get(ev.breaker, 0) + 1
        elif isinstance(ev, IncidentRecorded):
            incidents.append({
                "incident_id": ev.incident_id, "trigger": ev.trigger,
                "path": ev.path, "trace_id": ev.trace_id,
            })
        elif isinstance(ev, IncidentSkipped):
            incidents_skipped += 1
        elif isinstance(ev, MemoryPressure):
            pressure.append({
                "kind": "memory", "source": ev.source, "level": ev.level,
                "t": ev.t,
            })
        elif isinstance(ev, DiskPressure):
            pressure.append({
                "kind": "disk", "source": ev.path, "level": ev.level,
                "t": ev.t,
            })
        elif isinstance(ev, HistogramDegraded):
            degradations.append({
                "iteration": ev.iteration, "stage": ev.stage,
                "budget_bytes": ev.budget_bytes, "chunk_rows": ev.chunk_rows,
                "retries": ev.retries,
            })
        elif isinstance(ev, HistogramSubtracted):
            hist_optimizations.append({
                "kind": "subtraction", "rows": ev.rows,
                "num_leaves": ev.num_leaves, "acc_dtype": ev.acc_dtype,
                "cache_bytes": ev.cache_bytes,
                "bytes_saved_per_tree": ev.bytes_saved_per_tree,
            })
        elif isinstance(ev, HistogramChunked):
            hist_optimizations.append({
                "kind": "chunked", "rows": ev.rows,
                "chunk_rows": ev.chunk_rows, "num_chunks": ev.num_chunks,
                "acc_dtype": ev.acc_dtype, "bytes_saved": ev.bytes_saved,
            })
        elif isinstance(ev, (DriftDetected, DriftCleared)):
            detected = isinstance(ev, DriftDetected)
            quality["detected" if detected else "cleared"] += 1
            rec = drift_features.setdefault(
                ev.feature, {"detected": 0, "cleared": 0}
            )
            rec["detected" if detected else "cleared"] += 1
        elif isinstance(ev, (AlertFired, AlertResolved)):
            fired = isinstance(ev, AlertFired)
            alerts["fired" if fired else "resolved"] += 1
            alert_history.append({
                "alert": ev.alert, "slo": ev.slo,
                "state": "fired" if fired else "resolved",
                "burn_short": ev.burn_short, "burn_long": ev.burn_long,
                "t": ev.t,
            })
        elif isinstance(ev, (ProfileCompiled, ProfileExecuted)):
            rec = profiler.setdefault(ev.name, {
                "compiles": 0, "compile_seconds": 0.0,
                "executions": 0, "device_seconds": 0.0,
                "flops": 0.0, "bytes_accessed": 0.0,
            })
            if isinstance(ev, ProfileCompiled):
                rec["compiles"] += 1
                rec["compile_seconds"] += ev.seconds
                if ev.flops:
                    rec["flops"] = ev.flops
                if ev.bytes_accessed:
                    rec["bytes_accessed"] = ev.bytes_accessed
            else:
                rec["executions"] += 1
                rec["device_seconds"] += ev.seconds
    requests: Dict[str, Any] = {
        "count": len(latencies), "statuses": statuses, "shed": shed,
    }
    if latencies:
        ordered = sorted(latencies)
        requests["latency_p50"] = ordered[len(ordered) // 2]
        requests["latency_max"] = ordered[-1]
    return {
        "stages": [stages[k] for k in sorted(stages)],
        "tasks": dict(tasks, retry_reasons=retry_reasons, attempts=attempts),
        "batches": batches,
        "requests": requests,
        "models": models,
        "streaming": dict(streaming, queries=stream_epochs),
        "swaps": swaps,
        "fleet": fleet,
        "routing": dict(
            routing, statuses=routed_statuses, by_replica=routed_by_replica,
        ),
        "breaker_trips": breaker_trips,
        "quarantines": quarantines,
        "paroles": paroles,
        "processes": dict(processes, loss_reasons=loss_reasons),
        "profiler": profiler,
        "incidents": incidents,
        "incidents_skipped": incidents_skipped,
        "pressure": pressure,
        "degradations": degradations,
        "hist_optimizations": hist_optimizations,
        "quality": dict(quality, features=drift_features),
        "alerts": dict(alerts, history=alert_history),
        "by_process": by_process,
    }


def format_timeline(summary: Dict[str, Any]) -> str:
    """Render a :func:`timeline` summary as the one-screen text report."""
    lines = ["== stages =="]
    for s in summary["stages"]:
        dur = s.get("duration")
        lines.append(
            f"  [{s['phase']}] {s['name']}: "
            + (f"{dur:.4f}s" if dur is not None else "unfinished")
            + (f" ({s['status']})" if s.get("status", "ok") != "ok" else "")
        )
    t = summary["tasks"]
    lines.append(
        f"== tasks == dispatched={t['dispatched']} retried={t['retried']} "
        f"failed={t['failed']} permanent={t['failed_permanent']}"
        + (f" speculated={t['speculated']}" if t.get("speculated") else "")
        + (f" recovered={t['recovered']}" if t.get("recovered") else "")
    )
    # structured per-task attempt history (worker / reason / duration /
    # speculative flag) — the JobFailedError post-mortem view
    for task_id in sorted(t.get("attempts") or {}):
        parts = []
        for a in t["attempts"][task_id]:
            parts.append(
                f"attempt {a['attempt']}"
                + (" (spec)" if a.get("speculative") else "")
                + f" on w{a['worker']} {a['reason']} {a['duration']:.3f}s"
                + (" PERMANENT" if a.get("permanent") else "")
            )
        lines.append(f"   task {task_id}: " + "; ".join(parts))
    procs = summary.get("processes") or {}
    if procs.get("started") or procs.get("lost"):
        line = (
            f"== processes == started={procs.get('started', 0)} "
            f"lost={procs.get('lost', 0)} reformed={procs.get('reformed', 0)}"
        )
        reasons = procs.get("loss_reasons") or {}
        if reasons:
            line += " (" + ", ".join(
                f"{reason} x{n}" for reason, n in sorted(reasons.items())
            ) + ")"
        lines.append(line)
    quarantines = summary.get("quarantines") or {}
    if quarantines:
        lines.append("== quarantine == " + ", ".join(
            f"w{wid} x{n}" for wid, n in sorted(quarantines.items())
        ) + f" paroled={summary.get('paroles', 0)}")
    streaming = summary.get("streaming") or {}
    if streaming.get("epochs"):
        line = (
            f"== streaming == epochs={streaming['epochs']} "
            f"rows={streaming['rows']} "
            f"source_units={streaming.get('source_units', 0)}"
        )
        queries = streaming.get("queries") or {}
        if queries:
            line += " (" + ", ".join(
                f"{q}: epochs {min(eps)}..{max(eps)}"
                for q, eps in sorted(queries.items())
            ) + ")"
        lines.append(line)
    b, r = summary["batches"], summary["requests"]
    lines.append(f"== serving == batches={b['count']} rows={b['rows']} "
                 f"requests={r['count']} shed={r.get('shed', 0)}")
    routing = summary.get("routing") or {}
    if routing.get("count"):
        avg_hops = routing["hops"] / routing["count"]
        lines.append(
            f"== routing == requests={routing['count']} "
            f"failovers={routing['failovers']} avg_hops={avg_hops:.2f}"
            + (" (" + ", ".join(
                f"{name} x{n}"
                for name, n in sorted((routing.get("by_replica") or {}).items())
            ) + ")" if routing.get("by_replica") else "")
        )
    fleet = summary.get("fleet") or []
    if fleet:
        lines.append("== fleet == " + ", ".join(
            f"{f['direction']}->{f['replicas']}"
            + (f" ({f['reason']})" if f.get("reason") else "")
            for f in fleet
        ))
    trips = summary.get("breaker_trips") or {}
    if trips:
        lines.append("== breakers == " + ", ".join(
            f"{name} tripped x{n}" for name, n in sorted(trips.items())
        ))
    incidents = summary.get("incidents") or []
    if incidents:
        lines.append("== incidents == " + ", ".join(
            f"{i['trigger']} ({i['incident_id']})" for i in incidents
        ) + (
            f" skipped={summary['incidents_skipped']}"
            if summary.get("incidents_skipped") else ""
        ))
    pressure = summary.get("pressure") or []
    degradations = summary.get("degradations") or []
    if pressure or degradations:
        onsets = [p for p in pressure if p["level"] != "ok"]
        recoveries = [p for p in pressure if p["level"] == "ok"]
        line = (
            f"== pressure == onsets={len(onsets)} "
            f"recoveries={len(recoveries)} degradations={len(degradations)}"
        )
        if onsets:
            line += " (" + ", ".join(
                f"{p['kind']}:{p['source']} {p['level']}" for p in onsets
            ) + ")"
        lines.append(line)
        for d in degradations:
            lines.append(
                f"   iter {d['iteration']} [{d['stage']}] -> "
                f"budget={d['budget_bytes']} chunk_rows={d['chunk_rows']} "
                f"retry {d['retries']}"
            )
    hist_opts = summary.get("hist_optimizations") or []
    if hist_opts:
        # planned byte-saving paths — NOT the pressure ladder above
        lines.append("== histogram optimizations ==")
        for o in hist_opts:
            if o["kind"] == "subtraction":
                lines.append(
                    f"   subtraction: leaves={o['num_leaves']} "
                    f"acc={o['acc_dtype']} cache={o['cache_bytes']}B "
                    f"saves={o['bytes_saved_per_tree']}B/tree"
                )
            else:
                lines.append(
                    f"   chunked: chunks={o['num_chunks']}x"
                    f"{o['chunk_rows']} acc={o['acc_dtype']} "
                    f"saves={o['bytes_saved']}B"
                )
    quality = summary.get("quality") or {}
    if quality.get("detected") or quality.get("cleared"):
        lines.append(
            f"== quality == drift detected={quality['detected']} "
            f"cleared={quality['cleared']}"
            + (" (" + ", ".join(
                f"{feat} x{c['detected']}"
                for feat, c in sorted((quality.get("features") or {}).items())
                if c["detected"]
            ) + ")" if quality.get("features") else "")
        )
    alerts = summary.get("alerts") or {}
    if alerts.get("fired") or alerts.get("resolved"):
        lines.append(
            f"== alerts == fired={alerts['fired']} "
            f"resolved={alerts['resolved']}"
        )
        for a in alerts.get("history") or []:
            lines.append(
                f"   {a['alert']} [{a['slo']}] {a['state']} "
                f"burn short={a['burn_short']:.2f} long={a['burn_long']:.2f}"
            )
    by_process = summary.get("by_process") or {}
    if by_process:
        lines.append("== fleet log == " + ", ".join(
            f"{proc} x{n}" for proc, n in sorted(by_process.items())
        ))
    if "latency_p50" in r:
        lines.append(
            f"   latency p50={r['latency_p50'] * 1e3:.2f}ms "
            f"max={r['latency_max'] * 1e3:.2f}ms"
        )
    profiler = summary.get("profiler") or {}
    if profiler:
        lines.append("== profiler ==")
        for name in sorted(profiler):
            p = profiler[name]
            parts = []
            if p["compiles"]:
                parts.append(
                    f"compiles={p['compiles']} ({p['compile_seconds']:.3f}s)"
                )
            if p["executions"]:
                avg = p["device_seconds"] / p["executions"]
                parts.append(
                    f"execs={p['executions']} device={p['device_seconds']:.3f}s "
                    f"avg={avg * 1e3:.2f}ms"
                )
            if p.get("flops"):
                parts.append(f"flops={p['flops']:.3g}")
            lines.append(f"   {name}: " + " ".join(parts))
    if summary["models"]:
        lines.append("== models == " + ", ".join(summary["models"]))
    swaps = summary.get("swaps") or []
    if swaps:
        lines.append("== swaps == " + ", ".join(
            f"{s['name']} -> v{s['version']}"
            + (f" @{s['server']}" if s.get("server") else "")
            for s in swaps
        ))
    return "\n".join(lines)
