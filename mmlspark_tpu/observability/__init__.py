"""mmlspark_tpu.observability — the unified observability plane.

The reference framework leaned on Spark's ListenerBus/event-log/UI and
metrics system for every operational question; this package is the
self-owned replacement (``docs/observability.md``), three cooperating
pieces wired through core, runtime, serving, stages, and lightgbm fit:

- :mod:`~mmlspark_tpu.observability.events`  — typed event bus with a
  JSON-lines event-log sink (``MMLSPARK_TPU_EVENT_LOG=/path``), replayable
  into a timeline summary;
- :mod:`~mmlspark_tpu.observability.tracing` — Dapper-style Span/Tracer
  with ``contextvars`` propagation and deterministic span ids; serving
  propagates one trace id request -> batch -> model-apply across threads;
- :mod:`~mmlspark_tpu.observability.registry` — Prometheus-style
  counters/gauges/latency-histograms with text exposition, served live at
  ``GET /metrics`` (and ``GET /healthz``) on every serving endpoint;
- :mod:`~mmlspark_tpu.observability.profiler` — device-performance
  profiler (``MMLSPARK_TPU_PROFILE=1``): compile accounting,
  ``block_until_ready`` execution windows, XLA ``cost_analysis()``
  roofline attribution, HBM gauges, transfer counters;
- :mod:`~mmlspark_tpu.observability.slo` — :class:`SLOReport` folding
  the registry + event log into the serving-SLO verdict (JSON/markdown);
- :mod:`~mmlspark_tpu.observability.history` — the History-Server
  analogue: ``python -m mmlspark_tpu.observability.history <eventlog>``
  renders one self-contained HTML report.

Quick start::

    import os
    os.environ["MMLSPARK_TPU_EVENT_LOG"] = "/tmp/events.jsonl"

    model = pipeline.fit(table)          # stage events + spans recorded
    with ServingServer(model) as srv:    # GET /metrics, GET /healthz live
        ...

    from mmlspark_tpu import observability as obs
    print(obs.format_timeline(obs.timeline(obs.replay("/tmp/events.jsonl"))))
    print(obs.get_registry().exposition())
"""

from mmlspark_tpu.observability.alerts import AlertEvaluator
from mmlspark_tpu.observability.events import (
    AlertFired,
    AlertResolved,
    BatchFormed,
    BreakerTripped,
    CandidateBatchFitted,
    DriftCleared,
    DriftDetected,
    Event,
    EventBus,
    EventLogSink,
    FeatureBundled,
    FleetScaled,
    GroupReformed,
    HistogramChunked,
    HistogramSubtracted,
    IncidentRecorded,
    LeaseRecovered,
    ModelCommitted,
    ModelSwapped,
    NetworkPartitioned,
    PeerSlow,
    ProcessLost,
    ProcessStarted,
    ProfileCompiled,
    ProfileExecuted,
    RegistryRecovered,
    RegistryUnavailable,
    RequestRouted,
    RequestServed,
    RequestShed,
    SpanRecorded,
    StageCompleted,
    StageStarted,
    StreamEpochCommitted,
    StreamEpochStarted,
    StreamSourceAdvanced,
    SweepCompleted,
    SweepStarted,
    TaskDispatched,
    TaskFailed,
    TaskRecovered,
    TaskRetried,
    TaskSpeculated,
    WorkerParoled,
    WorkerQuarantined,
    collect,
    format_timeline,
    from_record,
    get_bus,
    log_segments,
    merge,
    process_label,
    process_log_path,
    replay,
    timeline,
    write_merged,
)
from mmlspark_tpu.observability.federation import (
    MetricsFederator,
    parse_exposition,
)
from mmlspark_tpu.observability.incidents import (
    FlightRecorder,
    get_recorder,
    maybe_record,
)
from mmlspark_tpu.observability.profiler import (
    DevicePeaks,
    DeviceProfiler,
    FunctionProfile,
    UNKNOWN_PLATFORM,
    device_peaks,
    get_profiler,
)
from mmlspark_tpu.observability.quality import (
    QualityMonitor,
    ReferenceProfile,
    capture_pipeline_reference,
    drift_table_from_summary,
    get_monitor,
    install_monitor,
    load_profile,
)
from mmlspark_tpu.observability.registry import (
    DEFAULT_BUCKETS,
    FIT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from mmlspark_tpu.observability.sketches import (
    ColumnSketch,
    QuantileCompactor,
    ks_statistic,
    merge_all,
    psi,
)
from mmlspark_tpu.observability.slo import SLOReport, SLOTargets, fleet_summary
from mmlspark_tpu.observability.tracing import (
    PARENT_HEADER,
    TRACE_HEADER,
    Span,
    TraceContext,
    Tracer,
    get_tracer,
)


def __getattr__(name):
    # lazy: importing history here eagerly would trip runpy's double-import
    # warning under ``python -m mmlspark_tpu.observability.history``
    if name == "render_report":
        from mmlspark_tpu.observability.history import render_report

        return render_report
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "AlertEvaluator",
    "AlertFired",
    "AlertResolved",
    "BatchFormed",
    "BreakerTripped",
    "CandidateBatchFitted",
    "ColumnSketch",
    "Counter",
    "DEFAULT_BUCKETS",
    "DevicePeaks",
    "DeviceProfiler",
    "DriftCleared",
    "DriftDetected",
    "Event",
    "EventBus",
    "EventLogSink",
    "FIT_BUCKETS",
    "FeatureBundled",
    "FleetScaled",
    "FlightRecorder",
    "FunctionProfile",
    "Gauge",
    "GroupReformed",
    "Histogram",
    "HistogramChunked",
    "HistogramSubtracted",
    "IncidentRecorded",
    "LeaseRecovered",
    "MetricsFederator",
    "MetricsRegistry",
    "ModelCommitted",
    "ModelSwapped",
    "NetworkPartitioned",
    "PARENT_HEADER",
    "PeerSlow",
    "ProcessLost",
    "ProcessStarted",
    "ProfileCompiled",
    "ProfileExecuted",
    "QualityMonitor",
    "QuantileCompactor",
    "ReferenceProfile",
    "RegistryRecovered",
    "RegistryUnavailable",
    "RequestRouted",
    "RequestServed",
    "RequestShed",
    "SLOReport",
    "SLOTargets",
    "Span",
    "SpanRecorded",
    "StageCompleted",
    "StageStarted",
    "StreamEpochCommitted",
    "StreamEpochStarted",
    "StreamSourceAdvanced",
    "SweepCompleted",
    "SweepStarted",
    "TRACE_HEADER",
    "TaskDispatched",
    "TaskFailed",
    "TaskRecovered",
    "TaskRetried",
    "TaskSpeculated",
    "TraceContext",
    "Tracer",
    "UNKNOWN_PLATFORM",
    "WorkerParoled",
    "WorkerQuarantined",
    "capture_pipeline_reference",
    "collect",
    "device_peaks",
    "drift_table_from_summary",
    "fleet_summary",
    "format_timeline",
    "from_record",
    "get_bus",
    "get_monitor",
    "get_profiler",
    "get_recorder",
    "get_registry",
    "get_tracer",
    "install_monitor",
    "ks_statistic",
    "load_profile",
    "log_segments",
    "maybe_record",
    "merge",
    "merge_all",
    "parse_exposition",
    "process_label",
    "process_log_path",
    "psi",
    "render_report",
    "replay",
    "timeline",
    "write_merged",
]
