"""mmlspark_tpu.observability — the unified observability plane.

The reference framework leaned on Spark's ListenerBus/event-log/UI and
metrics system for every operational question; this package is the
self-owned replacement (``docs/observability.md``), three cooperating
pieces wired through core, runtime, serving, stages, and lightgbm fit:

- :mod:`~mmlspark_tpu.observability.events`  — typed event bus with a
  JSON-lines event-log sink (``MMLSPARK_TPU_EVENT_LOG=/path``), replayable
  into a timeline summary;
- :mod:`~mmlspark_tpu.observability.tracing` — Dapper-style Span/Tracer
  with ``contextvars`` propagation and deterministic span ids; serving
  propagates one trace id request -> batch -> model-apply across threads;
- :mod:`~mmlspark_tpu.observability.registry` — Prometheus-style
  counters/gauges/latency-histograms with text exposition, served live at
  ``GET /metrics`` (and ``GET /healthz``) on every serving endpoint.

Quick start::

    import os
    os.environ["MMLSPARK_TPU_EVENT_LOG"] = "/tmp/events.jsonl"

    model = pipeline.fit(table)          # stage events + spans recorded
    with ServingServer(model) as srv:    # GET /metrics, GET /healthz live
        ...

    from mmlspark_tpu import observability as obs
    print(obs.format_timeline(obs.timeline(obs.replay("/tmp/events.jsonl"))))
    print(obs.get_registry().exposition())
"""

from mmlspark_tpu.observability.events import (
    BatchFormed,
    BreakerTripped,
    Event,
    EventBus,
    EventLogSink,
    GroupReformed,
    ModelCommitted,
    ModelSwapped,
    ProcessLost,
    ProcessStarted,
    RequestServed,
    RequestShed,
    StageCompleted,
    StageStarted,
    StreamEpochCommitted,
    StreamEpochStarted,
    StreamSourceAdvanced,
    TaskDispatched,
    TaskFailed,
    TaskRecovered,
    TaskRetried,
    TaskSpeculated,
    WorkerParoled,
    WorkerQuarantined,
    format_timeline,
    from_record,
    get_bus,
    replay,
    timeline,
)
from mmlspark_tpu.observability.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
)
from mmlspark_tpu.observability.tracing import Span, Tracer, get_tracer

__all__ = [
    "BatchFormed",
    "BreakerTripped",
    "Counter",
    "Event",
    "EventBus",
    "EventLogSink",
    "Gauge",
    "GroupReformed",
    "Histogram",
    "MetricsRegistry",
    "ModelCommitted",
    "ModelSwapped",
    "ProcessLost",
    "ProcessStarted",
    "RequestServed",
    "RequestShed",
    "Span",
    "StageCompleted",
    "StageStarted",
    "StreamEpochCommitted",
    "StreamEpochStarted",
    "StreamSourceAdvanced",
    "TaskDispatched",
    "TaskFailed",
    "TaskRecovered",
    "TaskRetried",
    "TaskSpeculated",
    "Tracer",
    "WorkerParoled",
    "WorkerQuarantined",
    "format_timeline",
    "from_record",
    "get_bus",
    "get_registry",
    "get_tracer",
    "replay",
    "timeline",
]
