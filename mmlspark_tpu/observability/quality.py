"""Live model-quality plane: reference profiles + drift monitoring.

The reference framework ships ``ComputeModelStatistics`` as a batch
evaluation transformer — quality is something you compute on a table you
already have. In production the table is the live request stream, and
the question is not "what is the AUC" (no labels yet) but "does today's
traffic still look like the data this model was fitted on". This module
is that production-time analogue (ISSUE 18, docs/observability.md
§ Model quality):

- **Reference profiles**: fit time streams the training columns (and
  the fitted model's scores) through the deterministic
  :class:`~mmlspark_tpu.observability.sketches.QuantileCompactor` to
  place near-equidepth bin edges, sketches each column over those fixed
  edges, and commits the result to the
  :class:`~mmlspark_tpu.runtime.journal.ModelStore` as a CRC-sidecar'd
  JSON artifact riding next to the model version
  (``<name>-<version>.quality.json``).
- **Live sketching**: :class:`QualityMonitor` keeps a rolling window of
  bin counts per feature, fed by ``PipelineModel.transform`` and the
  serving ``_BatchLoop`` behind the same ambient-gate pattern as tracing
  — an unconfigured process pays one env lookup per call, keeping the
  bare transform inside the perf-report <5% overhead guard.
- **Drift scoring**: every ``eval_every`` observations the monitor
  scores each feature's window against the served version's reference
  profile (PSI + KS), publishes ``quality_*`` gauges the
  ``MetricsFederator`` scrapes like any other series, and on threshold
  crossings publishes paired :class:`DriftDetected`/:class:`DriftCleared`
  events and trips the incident flight recorder.

Env-driven like the event sink and the profiler:
``MMLSPARK_TPU_QUALITY_STORE=/path`` (the ModelStore root) installs the
process-global monitor on first :func:`get_monitor` call;
``MMLSPARK_TPU_QUALITY_MODEL`` names the model (default ``model``).
"""

from __future__ import annotations

import bisect
import collections
import math
import os
import threading
from typing import Any, Deque, Dict, Iterable, List, Mapping, Optional, Tuple

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.observability.events import (
    DriftCleared,
    DriftDetected,
    get_bus,
)
from mmlspark_tpu.observability.sketches import (
    DEFAULT_BINS,
    PSI_EPS,
    ColumnSketch,
    QuantileCompactor,
    _is_missing,
)

logger = get_logger("observability.quality")

__all__ = [
    "QualityMonitor",
    "ReferenceProfile",
    "capture_pipeline_reference",
    "drift_table_from_summary",
    "get_monitor",
    "install_monitor",
    "load_profile",
]

#: artifact kind under which profiles ride next to the model version
PROFILE_KIND = "quality"

#: hysteresis: a drifted feature clears when its stats fall below this
#: fraction of the onset threshold, so a statistic hovering at the
#: threshold cannot flap detect/clear pairs
CLEAR_FRACTION = 0.8

#: hard cap on profiled features — quality must never explode the metric
#: cardinality a federated scrape carries
MAX_FEATURES = 64


def _iter_feature_values(
    column: str, values: Iterable[Any]
) -> Iterable[Tuple[str, Any]]:
    """Expand one column's rows into (feature, scalar) pairs: a vector
    row fans out to ``col[0]``, ``col[1]``, ...; scalar rows keep the
    bare column name."""
    for row in values:
        if isinstance(row, (list, tuple)) or (
            hasattr(row, "ndim") and getattr(row, "ndim", 0) >= 1
        ):
            for i, v in enumerate(row):
                yield f"{column}[{i}]", v
        else:
            yield column, row


class ReferenceProfile:
    """Per-feature + score distribution profile captured at fit time.

    ``features`` maps feature name (``input[0]``, ``prediction``, ...) to
    the exact :class:`ColumnSketch` of the fit-time data over bin edges
    the :class:`QuantileCompactor` placed. Serialization is canonical
    JSON, so the committed artifact is byte-stable for identical fits.
    """

    def __init__(
        self,
        model: str,
        version: int,
        features: Dict[str, ColumnSketch],
        bins: int = DEFAULT_BINS,
    ):
        self.model = model
        self.version = int(version)
        self.features = dict(features)
        self.bins = int(bins)

    @classmethod
    def capture(
        cls,
        model: str,
        version: int,
        columns: Mapping[str, Iterable[Any]],
        bins: int = DEFAULT_BINS,
    ) -> "ReferenceProfile":
        """Profile the given columns: place near-equidepth edges per
        expanded feature, then sketch every value over them. Vector
        columns fan out per index; at most :data:`MAX_FEATURES` features
        are kept (name order, so the cap is deterministic)."""
        grouped: Dict[str, List[Any]] = {}
        for col, values in columns.items():
            for feature, v in _iter_feature_values(col, values):
                grouped.setdefault(feature, []).append(v)
        features: Dict[str, ColumnSketch] = {}
        for feature in sorted(grouped)[:MAX_FEATURES]:
            values = grouped[feature]
            compactor = QuantileCompactor()
            compactor.extend(values)
            sketch = ColumnSketch(compactor.edges(bins))
            sketch.observe_many(values)
            features[feature] = sketch
        return cls(model, version, features, bins=bins)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "model": self.model,
            "version": self.version,
            "bins": self.bins,
            "features": {
                name: sketch.to_dict()
                for name, sketch in sorted(self.features.items())
            },
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ReferenceProfile":
        return cls(
            model=str(d.get("model", "model")),
            version=int(d.get("version", 0)),
            features={
                name: ColumnSketch.from_dict(rec)
                for name, rec in dict(d.get("features", {})).items()
            },
            bins=int(d.get("bins", DEFAULT_BINS)),
        )

    def commit(self, store) -> str:
        """Commit this profile as the model version's quality artifact
        (CRC sidecar, tmp+rename — :meth:`ModelStore.commit_artifact`)."""
        return store.commit_artifact(
            self.model, self.version, PROFILE_KIND, self.to_dict()
        )


def load_profile(store, model: str, version: int) -> Optional[ReferenceProfile]:
    """The verified profile artifact for ``<model>-<version>``, or None
    when absent/corrupt."""
    payload = store.read_artifact(model, version, PROFILE_KIND)
    if payload is None:
        return None
    try:
        return ReferenceProfile.from_dict(payload)
    except (ValueError, TypeError, KeyError) as e:
        logger.warning("quality profile %s-%s unreadable: %s", model, version, e)
        return None


class _Window:
    """Rolling bin-count window of one live feature: integer counts over
    the reference edges plus a ring of bin indices (-1 = missing) so an
    old observation's count leaves when it scrolls out."""

    __slots__ = ("counts", "missing", "ring", "limit")

    def __init__(self, num_bins: int, limit: int):
        self.counts = [0] * num_bins
        self.missing = 0
        self.ring: Deque[int] = collections.deque()
        self.limit = limit

    def push(self, idx: int) -> None:
        self.ring.append(idx)
        if idx < 0:
            self.missing += 1
        else:
            self.counts[idx] += 1
        if len(self.ring) > self.limit:
            old = self.ring.popleft()
            if old < 0:
                self.missing -= 1
            else:
                self.counts[old] -= 1

    @property
    def n(self) -> int:
        return len(self.ring) - self.missing


def _bin_index(edges: Tuple[float, ...], value: Any) -> int:
    """Clamped bin index over reference edges; -1 for missing."""
    if _is_missing(value):
        return -1
    v = float(value)
    return bisect.bisect_right(edges, v, 1, len(edges) - 1) - 1


def _window_psi(ref: ColumnSketch, counts: List[int], n: int) -> float:
    p = ref.probabilities(eps=PSI_EPS)
    total = n + PSI_EPS * len(counts)
    q = [(c + PSI_EPS) / total for c in counts]
    return float(sum((qi - pi) * math.log(qi / pi) for pi, qi in zip(p, q)))


def _window_ks(ref: ColumnSketch, counts: List[int], n: int) -> float:
    if n == 0:
        return 0.0
    ref_cdf = ref.cdf()
    worst = 0.0
    cum = 0
    for c, r in zip(counts, ref_cdf):
        cum += c
        worst = max(worst, abs(cum / n - r))
    return worst


class QualityMonitor:
    """Rolling-window drift scorer of live traffic vs a reference profile.

    Observations enter through :meth:`observe_columns` (the serving batch
    loop and ``PipelineModel.transform`` both feed it; the loop suppresses
    the inner transform's observation so a request is never sketched
    twice). Every ``eval_every`` observations the windows are scored:
    ``quality_psi``/``quality_ks`` gauges per feature and model version,
    a 0/1 ``quality_drift_active`` gauge, and paired
    :class:`DriftDetected`/:class:`DriftCleared` events with a
    flight-recorder trip on detection. All state transitions are computed
    under the monitor lock; events publish after it releases.
    """

    def __init__(
        self,
        profile: Optional[ReferenceProfile] = None,
        store=None,
        model: str = "model",
        registry=None,
        window: int = 512,
        eval_every: int = 64,
        min_window: int = 32,
        psi_threshold: float = 0.2,
        ks_threshold: float = 0.25,
    ):
        self.store = store
        self.model = profile.model if profile is not None else model
        self.window = int(window)
        self.eval_every = int(eval_every)
        self.min_window = int(min_window)
        self.psi_threshold = float(psi_threshold)
        self.ks_threshold = float(ks_threshold)
        self._lock = threading.Lock()
        self._profile: Optional[ReferenceProfile] = None
        self._bases: set = set()
        self._windows: Dict[str, _Window] = {}
        self._drifted: Dict[str, bool] = {}
        self._last_stats: Dict[str, Dict[str, float]] = {}
        self._since_eval = 0
        self._suppress_depth = 0
        self.version = 0
        if registry is None:
            from mmlspark_tpu.observability.registry import get_registry

            registry = get_registry()
        self.registry = registry
        self._g_psi = registry.gauge(
            "quality_psi",
            "Rolling-window PSI of live traffic vs the reference profile",
        )
        self._g_ks = registry.gauge(
            "quality_ks",
            "Rolling-window KS statistic vs the reference profile",
        )
        self._g_missing = registry.gauge(
            "quality_missing_rate", "Rolling-window missing-value rate"
        )
        self._g_drift = registry.gauge(
            "quality_drift_active", "1 while a feature is in drift"
        )
        self._c_obs = registry.counter(
            "quality_observations_total", "Values sketched by the quality plane"
        )
        if profile is not None:
            self._set_profile(profile)
        elif store is not None:
            current = store.current_version(self.model)
            if current:
                self._maybe_reload(int(current))

    # -- profile lifecycle ---------------------------------------------------

    @property
    def profile(self) -> Optional[ReferenceProfile]:
        return self._profile

    def _set_profile(self, profile: ReferenceProfile) -> None:
        self._profile = profile
        self.version = profile.version
        #: base column names the profile covers — unprofiled columns skip
        #: the per-row fan-out entirely
        self._bases = {name.partition("[")[0] for name in profile.features}
        self._windows = {
            name: _Window(len(sketch.counts), self.window)
            for name, sketch in profile.features.items()
        }
        self._drifted = {name: False for name in profile.features}
        self._last_stats = {}
        self._since_eval = 0

    def _maybe_reload(self, version: int) -> None:
        """Swap to ``version``'s profile when the store has one; fall back
        to the profile already loaded (the newest committed one) when the
        new version committed without a quality artifact. Version 0 means
        "untracked" (a loop that never hot-swapped) and never reloads."""
        if self.store is None or version <= 0 or version == self.version:
            return
        profile = load_profile(self.store, self.model, version)
        if profile is not None:
            with self._lock:
                self._set_profile(profile)
        else:
            # fallback: keep scoring against the previous reference, but
            # remember the served version so gauges/events carry it
            self.version = version

    def note_version(self, version: int) -> None:
        """The serving loop's hot-swap hook: the served model version
        changed, so drift must score against that version's profile."""
        try:
            self._maybe_reload(int(version))
        except Exception as e:  # noqa: BLE001 - quality must not fail serving
            logger.debug("quality profile reload failed: %s", e)

    # -- serving suppression -------------------------------------------------

    def suppress_transform(self) -> "_Suppress":
        """Context manager the serving batch loop wraps around its inner
        ``model.transform`` call: the loop observes the batch itself
        (inputs AND outputs), so the transform-level hook must not count
        the same rows again."""
        return _Suppress(self)

    @property
    def transform_suppressed(self) -> bool:
        with self._lock:
            return self._suppress_depth > 0

    # -- ingest --------------------------------------------------------------

    def observe_columns(
        self,
        columns: Mapping[str, Iterable[Any]],
        version: Optional[int] = None,
    ) -> None:
        """Sketch one batch of column values (vector rows fan out per
        index); only features present in the reference profile count.
        Never raises — quality must not fail the observed workload."""
        try:
            if version is not None:
                self.note_version(version)
            profile = self._profile
            if profile is None:
                return
            evaluate = False
            observed = 0
            with self._lock:
                for col, values in columns.items():
                    if col not in self._bases:
                        continue
                    for feature, v in _iter_feature_values(col, values):
                        win = self._windows.get(feature)
                        if win is None:
                            continue
                        ref = profile.features[feature]
                        win.push(_bin_index(ref.edges, v))
                        self._since_eval += 1
                        observed += 1
                if self._since_eval >= self.eval_every:
                    self._since_eval = 0
                    evaluate = True
            if observed:
                self._c_obs.inc(observed)
            if evaluate:
                self.evaluate()
        except Exception as e:  # noqa: BLE001 - quality must not fail serving
            logger.debug("quality observation failed: %s", e)

    # -- scoring -------------------------------------------------------------

    def evaluate(self) -> List[Dict[str, Any]]:
        """Score every feature window against the reference, update the
        ``quality_*`` gauges, and publish drift transitions. Returns the
        drift table (one row per feature)."""
        profile = self._profile
        if profile is None:
            return []
        transitions: List[Tuple[str, str, float, float, bool]] = []
        table: List[Dict[str, Any]] = []
        with self._lock:
            version = self.version
            for feature in sorted(profile.features):
                ref = profile.features[feature]
                win = self._windows[feature]
                n = win.n
                if n < self.min_window:
                    continue
                psi_v = _window_psi(ref, win.counts, n)
                ks_v = _window_ks(ref, win.counts, n)
                total = len(win.ring)
                missing_rate = win.missing / total if total else 0.0
                was = self._drifted[feature]
                if not was and (
                    psi_v > self.psi_threshold or ks_v > self.ks_threshold
                ):
                    self._drifted[feature] = True
                    if psi_v > self.psi_threshold:
                        transitions.append(
                            (feature, "psi", psi_v, self.psi_threshold, True)
                        )
                    else:
                        transitions.append(
                            (feature, "ks", ks_v, self.ks_threshold, True)
                        )
                elif was and (
                    psi_v <= CLEAR_FRACTION * self.psi_threshold
                    and ks_v <= CLEAR_FRACTION * self.ks_threshold
                ):
                    self._drifted[feature] = False
                    transitions.append(
                        (feature, "psi", psi_v, self.psi_threshold, False)
                    )
                stats = {
                    "psi": psi_v, "ks": ks_v, "n": float(n),
                    "missing_rate": missing_rate,
                    "drifted": self._drifted[feature],
                }
                self._last_stats[feature] = stats
                table.append(dict(stats, feature=feature, version=version))
        for feature, stats in list(self._last_stats.items()):
            labels = {
                "feature": feature,
                "model": self.model,
                "version": str(version),
            }
            self._g_psi.labels(**labels).set(stats["psi"])
            self._g_ks.labels(**labels).set(stats["ks"])
            self._g_missing.labels(feature=feature).set(stats["missing_rate"])
            self._g_drift.labels(feature=feature).set(
                1.0 if stats["drifted"] else 0.0
            )
        self._publish(transitions, version)
        return table

    def _publish(
        self,
        transitions: List[Tuple[str, str, float, float, bool]],
        version: int,
    ) -> None:
        if not transitions:
            return
        bus = get_bus()
        for feature, stat, value, threshold, detected in transitions:
            if bus.active:
                ctor = DriftDetected if detected else DriftCleared
                bus.publish(ctor(
                    feature=feature, stat=stat, value=value,
                    threshold=threshold, model=self.model, version=version,
                ))
            if detected:
                from mmlspark_tpu.observability.incidents import maybe_record

                maybe_record(
                    "drift_detected",
                    detail=f"{feature} {stat}={value:.3f} > {threshold:g}",
                )

    # -- export --------------------------------------------------------------

    def drifted_features(self) -> List[str]:
        with self._lock:
            return sorted(f for f, d in self._drifted.items() if d)

    def snapshot(self) -> Dict[str, Any]:
        """The drift table the flight recorder bundles as ``quality.json``
        and the SLO report folds into its quality section."""
        with self._lock:
            drift = [
                dict(self._last_stats[feature], feature=feature)
                for feature in sorted(self._last_stats)
            ]
            return {
                "model": self.model,
                "version": self.version,
                "psi_threshold": self.psi_threshold,
                "ks_threshold": self.ks_threshold,
                "window": self.window,
                "drift": drift,
            }


class _Suppress:
    __slots__ = ("_monitor",)

    def __init__(self, monitor: QualityMonitor):
        self._monitor = monitor

    def __enter__(self) -> "_Suppress":
        with self._monitor._lock:
            self._monitor._suppress_depth += 1
        return self

    def __exit__(self, *exc) -> None:
        with self._monitor._lock:
            self._monitor._suppress_depth -= 1


# -- process-global monitor (env-driven, like the sink and profiler) ---------

_MONITOR: Optional[QualityMonitor] = None
_MONITOR_LOCK = threading.Lock()


def install_monitor(monitor: Optional[QualityMonitor]) -> None:
    """Install (or clear, with None) the process-global monitor."""
    global _MONITOR
    with _MONITOR_LOCK:
        _MONITOR = monitor


def get_monitor() -> Optional[QualityMonitor]:
    """The process-global monitor, installing one from
    ``MMLSPARK_TPU_QUALITY_STORE``/``MMLSPARK_TPU_QUALITY_MODEL`` on
    first call; None when quality monitoring is unconfigured (the common
    case — call sites pay one env lookup)."""
    global _MONITOR
    if _MONITOR is not None:
        return _MONITOR
    root = os.environ.get("MMLSPARK_TPU_QUALITY_STORE", "")
    if not root:
        return None
    with _MONITOR_LOCK:
        if _MONITOR is None:
            try:
                from mmlspark_tpu.runtime.journal import ModelStore

                window = int(
                    os.environ.get("MMLSPARK_TPU_QUALITY_WINDOW", "512")
                )
                # a short same-distribution window reads high on PSI by
                # construction (E[PSI] ~ (bins-1)/n), so the env-installed
                # monitor refuses to score before the window is half full
                min_window = int(
                    os.environ.get(
                        "MMLSPARK_TPU_QUALITY_MIN_WINDOW",
                        str(max(32, window // 2)),
                    )
                )
                _MONITOR = QualityMonitor(
                    store=ModelStore(root),
                    model=os.environ.get("MMLSPARK_TPU_QUALITY_MODEL", "model"),
                    window=window,
                    eval_every=int(
                        os.environ.get("MMLSPARK_TPU_QUALITY_EVAL_EVERY", "64")
                    ),
                    min_window=min_window,
                )
            except Exception as e:  # noqa: BLE001 - never fail the workload
                logger.warning("quality monitor install failed: %s", e)
                return None
    return _MONITOR


# -- fit-time capture hook ---------------------------------------------------


def capture_pipeline_reference(model, table, version_hint: int = 0) -> None:
    """``Pipeline.fit``'s capture hook (env-gated by the caller): profile
    the numeric training columns plus the fitted model's score columns
    and commit the artifact next to the store's CURRENT version. Never
    raises — fit must succeed whether or not the profile lands."""
    try:
        root = os.environ.get("MMLSPARK_TPU_QUALITY_STORE", "")
        if not root:
            return
        from mmlspark_tpu.runtime.journal import ModelStore

        name = os.environ.get("MMLSPARK_TPU_QUALITY_MODEL", "model")
        store = ModelStore(root)
        columns: Dict[str, Any] = {}
        for col in table.columns:
            values = table.column(col)
            kind = getattr(getattr(values, "dtype", None), "kind", "")
            if kind in "fiub":
                columns[col] = list(values)
        out = model.transform(table)
        for col in out.columns:
            if col in table.columns:
                continue
            values = out.column(col)
            kind = getattr(getattr(values, "dtype", None), "kind", "")
            if kind in "fiub":
                columns[col] = list(values)
        version = store.current_version(name) or int(version_hint) or 1
        profile = ReferenceProfile.capture(name, version, columns)
        profile.commit(store)
        monitor = get_monitor()
        if monitor is not None and monitor.model == name:
            monitor.note_version(version)
    except Exception as e:  # noqa: BLE001 - fit must not fail on profiling
        logger.warning("reference-profile capture failed: %s", e)


# -- federated drift table ---------------------------------------------------


def drift_table_from_summary(summary: Mapping[str, Any]) -> List[Dict[str, Any]]:
    """Rebuild the per-feature drift table from a registry ``summary()``
    dict (local or federated — a ``replica`` label is carried through).
    This is what the SLO report and incident bundles use when the live
    monitor object is in another process."""
    psi_series = summary.get("quality_psi")
    if not isinstance(psi_series, dict):
        return []

    def _parse(key: str) -> Dict[str, str]:
        out: Dict[str, str] = {}
        for part in key.split(","):
            if "=" in part:
                k, _, v = part.partition("=")
                out[k] = v
        return out

    ks_by_key = (
        summary.get("quality_ks") if isinstance(summary.get("quality_ks"), dict)
        else {}
    )
    drift_series = (
        summary.get("quality_drift_active")
        if isinstance(summary.get("quality_drift_active"), dict)
        else {}
    )
    drift_by_feature: Dict[Tuple[str, str], float] = {}
    for key, value in drift_series.items():
        lbl = _parse(key)
        drift_by_feature[(lbl.get("feature", ""), lbl.get("replica", ""))] = value
    rows: List[Dict[str, Any]] = []
    for key in sorted(psi_series):
        lbl = _parse(key)
        feature = lbl.get("feature", "")
        replica = lbl.get("replica", "")
        row: Dict[str, Any] = {
            "feature": feature,
            "model": lbl.get("model", ""),
            "version": lbl.get("version", ""),
            "psi": float(psi_series[key]),
            "ks": float(ks_by_key.get(key, 0.0)),
            "drifted": bool(drift_by_feature.get((feature, replica), 0.0)),
        }
        if replica:
            row["replica"] = replica
        rows.append(row)
    return rows
