"""Multi-window, multi-burn-rate SLO alerting off the live registry.

``SLOReport`` is a post-hoc fold: somebody runs it after the campaign
and discovers the budget was blown an hour ago. This module is the live
edge (ISSUE 18): :class:`AlertEvaluator` samples cumulative counters and
the apply-latency histogram on a cadence, derives windowed **burn
rates** against the :class:`~mmlspark_tpu.observability.slo.SLOTargets`,
and applies the classic multi-window rule — fire only when BOTH a short
and a long window burn faster than ``threshold``x budget (the short
window gives fast onset, the long window keeps a transient blip from
paging), resolve as soon as the short window recovers.

Burn definitions per sample-window delta:

- **availability**: ``(bad / requests) / (1 - target.availability)`` —
  1.0 means errors are consuming budget exactly as fast as the SLO
  allots, N means N-times too fast;
- **latency**: windowed mean apply latency / ``target.p99_ms`` — the
  mean exceeding the tail target is an unambiguous storm signal and
  needs only the histogram ``sum``/``count`` deltas, which federate
  exactly.

Transitions publish paired
:class:`~mmlspark_tpu.observability.events.AlertFired` /
:class:`~mmlspark_tpu.observability.events.AlertResolved` events, trip
the incident flight recorder, and maintain an ``alerts_active`` gauge.
:meth:`AlertEvaluator.active_alerts` is the advisory hook the
``FleetController`` reads (an actively-burning SLO pins the fleet
"busy", blocking scale-down mid-incident). The evaluator runs anywhere a
registry summary can be read: pass ``source=`` a callable returning
either a local ``registry.summary()`` or a federated
``fleet_summary(federator.scrape())`` for the fleet-wide verdict.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.observability.events import (
    AlertFired,
    AlertResolved,
    get_bus,
)
from mmlspark_tpu.observability.slo import SLOTargets

logger = get_logger("observability.alerts")

__all__ = ["AlertEvaluator"]


def _scalar(summary: Mapping[str, Any], name: str) -> float:
    v = summary.get(name)
    if v is None:
        return 0.0
    if isinstance(v, dict):
        return float(sum(v.values()))
    return float(v)


def _hist_sum_count(summary: Mapping[str, Any], name: str) -> Tuple[float, float]:
    v = summary.get(name)
    if isinstance(v, dict) and "count" in v:
        return float(v.get("sum", 0.0)), float(v.get("count", 0.0))
    return 0.0, 0.0


class _Sample:
    __slots__ = ("t", "requests", "bad", "apply_sum", "apply_count")

    def __init__(
        self, t: float, requests: float, bad: float,
        apply_sum: float, apply_count: float,
    ):
        self.t = t
        self.requests = requests
        self.bad = bad
        self.apply_sum = apply_sum
        self.apply_count = apply_count


class AlertEvaluator:
    """Samples a registry summary into a ring and evaluates multi-window
    burn rates on every :meth:`tick` (call it yourself with an injectable
    clock for determinism, or :meth:`start` the background cadence)."""

    def __init__(
        self,
        targets: Optional[SLOTargets] = None,
        source: Optional[Callable[[], Mapping[str, Any]]] = None,
        registry=None,
        windows: Tuple[float, float] = (300.0, 3600.0),
        threshold: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if windows[0] >= windows[1]:
            raise ValueError("windows must be (short, long) with short < long")
        self.targets = targets or SLOTargets()
        self.windows = (float(windows[0]), float(windows[1]))
        self.threshold = float(threshold)
        self.clock = clock
        if registry is None:
            from mmlspark_tpu.observability.registry import get_registry

            registry = get_registry()
        self.registry = registry
        self.source = source if source is not None else registry.summary
        self._samples: List[_Sample] = []
        self._active: Dict[str, Dict[str, float]] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._g_active = registry.gauge(
            "alerts_active", "Currently-firing burn-rate alerts"
        )

    # -- sampling ------------------------------------------------------------

    def _read(self) -> Optional[_Sample]:
        try:
            summary = self.source()
        except Exception as e:  # noqa: BLE001 - a failed scrape skips a tick
            logger.debug("alert source read failed: %s", e)
            return None
        apply_sum, apply_count = _hist_sum_count(
            summary, "serving_apply_latency_seconds"
        )
        return _Sample(
            t=self.clock(),
            requests=_scalar(summary, "serving_requests_total"),
            bad=(
                _scalar(summary, "serving_replies_failed_total")
                + _scalar(summary, "serving_expired_total")
            ),
            apply_sum=apply_sum,
            apply_count=apply_count,
        )

    def _baseline(self, now: float, window: float) -> Optional[_Sample]:
        """The newest sample at least ``window`` old (the delta baseline);
        None until the ring spans the window — a window that cannot be
        evaluated yet never fires."""
        base = None
        for s in self._samples:
            if now - s.t >= window:
                base = s
            else:
                break
        return base

    def _burns(self, now: float, latest: _Sample) -> Optional[Dict[str, Tuple[float, float]]]:
        """{alert: (burn_short, burn_long)}, or None while the ring is
        too young to span the long window."""
        out: Dict[str, List[float]] = {"availability": [], "latency": []}
        for window in self.windows:
            base = self._baseline(now, window)
            if base is None:
                return None
            req = latest.requests - base.requests
            bad = latest.bad - base.bad
            budget = 1.0 - self.targets.availability
            avail_burn = (bad / req / budget) if req > 0 and budget > 0 else 0.0
            n = latest.apply_count - base.apply_count
            mean_ms = (
                (latest.apply_sum - base.apply_sum) / n * 1e3 if n > 0 else 0.0
            )
            lat_burn = mean_ms / self.targets.p99_ms if self.targets.p99_ms else 0.0
            out["availability"].append(avail_burn)
            out["latency"].append(lat_burn)
        return {k: (v[0], v[1]) for k, v in out.items()}

    # -- evaluation ----------------------------------------------------------

    def tick(self) -> Dict[str, Tuple[float, float]]:
        """One sample + evaluation pass. Returns the current burn rates
        (empty until the ring spans the long window). Never raises."""
        latest = self._read()
        if latest is None:
            return {}
        slo_names = {
            "availability": f"availability>={self.targets.availability:g}",
            "latency": f"p99<={self.targets.p99_ms:g}ms",
        }
        fired: List[Tuple[str, float, float]] = []
        resolved: List[Tuple[str, float, float]] = []
        with self._lock:
            self._samples.append(latest)
            horizon = latest.t - 2.0 * self.windows[1]
            while len(self._samples) > 2 and self._samples[1].t <= horizon:
                self._samples.pop(0)
            burns = self._burns(latest.t, latest)
            if burns is None:
                return {}
            for alert, (short, long_) in sorted(burns.items()):
                active = alert in self._active
                if not active and short > self.threshold and long_ > self.threshold:
                    self._active[alert] = {"short": short, "long": long_}
                    fired.append((alert, short, long_))
                elif active and short <= self.threshold:
                    del self._active[alert]
                    resolved.append((alert, short, long_))
            active_count = len(self._active)
        self._g_active.set(float(active_count))
        bus = get_bus()
        for transitions, is_fire in ((fired, True), (resolved, False)):
            for alert, short, long_ in transitions:
                if bus.active:
                    ctor = AlertFired if is_fire else AlertResolved
                    bus.publish(ctor(
                        alert=alert, slo=slo_names[alert],
                        burn_short=short, burn_long=long_,
                        window_short_s=self.windows[0],
                        window_long_s=self.windows[1],
                        threshold=self.threshold,
                    ))
                if is_fire:
                    from mmlspark_tpu.observability.incidents import maybe_record

                    maybe_record(
                        "alert_fired",
                        detail=(
                            f"{alert} burn {short:.2f}x/{long_:.2f}x over "
                            f"{self.windows[0]:g}s/{self.windows[1]:g}s"
                        ),
                    )
        return burns

    # -- advisory + lifecycle ------------------------------------------------

    def active_alerts(self) -> Tuple[str, ...]:
        """Currently-firing alert names — the FleetController's advisory
        hook (non-empty pins the fleet busy, deferring scale-down)."""
        with self._lock:
            return tuple(sorted(self._active))

    def alert_history(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {k: dict(v) for k, v in self._active.items()}

    def start(self, interval_s: float = 10.0) -> "AlertEvaluator":
        """Run :meth:`tick` on a daemon cadence until :meth:`stop`."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 - alerting must not die
                    logger.debug("alert tick failed: %s", e)

        self._thread = threading.Thread(
            target=_loop, name="alert-evaluator", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=5.0)
