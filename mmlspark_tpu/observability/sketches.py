"""Deterministic, mergeable streaming sketches for model-quality data.

The quality plane (``docs/observability.md`` § Model quality) watches
what the fleet *predicts*, and the fleet is many processes — so the
distribution summaries it keeps must federate the way the metrics plane
does: merge per-replica state into one fleet view with the SAME bytes no
matter which replica folded first. Floating-point summation is not
associative, so the mergeable state here is exact by construction:

- **histogram counts** are integers over FIXED bin edges (placed once,
  at reference-capture time, by the :class:`QuantileCompactor`);
- **moments** (sum, sum of squares) are :class:`fractions.Fraction` —
  every float converts to a Fraction exactly, and Fraction addition is
  exact and associative, so any merge order reproduces the identical
  state and therefore the identical serialization;
- **min/max/counts** are order-free by nature.

``merge(a, merge(b, c)) == merge(merge(a, b), c)`` byte-for-byte is
pinned by ``tests/test_quality.py``; a sketch folded across N replica
processes equals the single-process sketch over the concatenated stream
exactly. Drift statistics (PSI over the shared bins, KS over the bin
CDFs) are derived at read time and never feed back into sketch state.
"""

from __future__ import annotations

import bisect
import json
import math
from fractions import Fraction
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "ColumnSketch",
    "DEFAULT_BINS",
    "QuantileCompactor",
    "ks_statistic",
    "merge_all",
    "psi",
]

#: default number of (near-equidepth) bins a reference profile places —
#: the classic PSI bin count.
DEFAULT_BINS = 10

#: smoothing mass added to every bin before a PSI log-ratio, so an empty
#: bin on either side stays finite.
PSI_EPS = 1e-6


def _is_missing(value: Any) -> bool:
    if value is None:
        return True
    try:
        v = float(value)
    except (TypeError, ValueError):
        return True
    return math.isnan(v)


class QuantileCompactor:
    """Deterministic KLL-style quantile compactor for bin-edge placement.

    Fit time streams a column through this to place near-equidepth bin
    edges without holding the column; live sketches then count into those
    FIXED edges forever after. The classic KLL sketch flips a coin per
    compaction; this one alternates the survivor parity deterministically
    (compaction counter, not RNG), so the same stream always yields the
    same edges — which is what replay-based tests and journal recovery
    want. Weighted rank error stays O(1/k) per level, ample for placing
    ``DEFAULT_BINS`` edges.
    """

    def __init__(self, k: int = 256) -> None:
        if k < 8:
            raise ValueError("compactor capacity k must be >= 8")
        self.k = int(k)
        #: level -> buffer of values; an item at level L weighs 2**L
        self._levels: List[List[float]] = [[]]
        self._compactions = 0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf

    @property
    def count(self) -> int:
        return self._count

    def update(self, value: Any) -> None:
        if _is_missing(value):
            return
        v = float(value)
        self._count += 1
        self._min = min(self._min, v)
        self._max = max(self._max, v)
        self._levels[0].append(v)
        level = 0
        while len(self._levels[level]) >= self.k:
            buf = sorted(self._levels[level])
            offset = self._compactions % 2
            self._compactions += 1
            self._levels[level] = []
            if level + 1 == len(self._levels):
                self._levels.append([])
            self._levels[level + 1].extend(buf[offset::2])
            level += 1

    def extend(self, values: Iterable[Any]) -> None:
        for v in values:
            self.update(v)

    def _weighted_items(self) -> List[Tuple[float, int]]:
        items: List[Tuple[float, int]] = []
        for level, buf in enumerate(self._levels):
            weight = 1 << level
            items.extend((v, weight) for v in buf)
        items.sort(key=lambda vw: vw[0])
        return items

    def edges(self, bins: int = DEFAULT_BINS) -> List[float]:
        """Strictly-increasing bin edges (length <= bins + 1) placing
        near-equidepth interior cuts; degenerate streams (constant column,
        empty column) collapse to a single unit-wide bin."""
        if bins < 1:
            raise ValueError("bins must be >= 1")
        if self._count == 0:
            return [0.0, 1.0]
        if self._min == self._max:
            return [self._min - 0.5, self._min + 0.5]
        items = self._weighted_items()
        total = sum(w for _, w in items)
        edges = [self._min]
        cum = 0
        target_idx = 1
        for v, w in items:
            cum += w
            while target_idx < bins and cum >= target_idx * total / bins:
                if v > edges[-1]:
                    edges.append(v)
                target_idx += 1
        if self._max > edges[-1]:
            edges.append(self._max)
        else:
            edges.append(math.nextafter(edges[-1], math.inf))
        return edges


class ColumnSketch:
    """Mergeable distribution sketch of one feature (or score) column.

    State: integer counts over fixed ``edges`` (values clamp into the
    first/last bin, so out-of-reference-range live traffic is visible as
    edge-bin mass), exact Fraction sum/sumsq, min/max, and a missing
    counter (None/NaN/unparseable). All of it merges associatively;
    :meth:`to_json` is canonical (sorted keys, fixed separators), so
    equal state means equal bytes.
    """

    def __init__(self, edges: Sequence[float]) -> None:
        edges = [float(e) for e in edges]
        if len(edges) < 2 or any(b <= a for a, b in zip(edges, edges[1:])):
            raise ValueError(f"edges must be strictly increasing, got {edges}")
        self.edges: Tuple[float, ...] = tuple(edges)
        self.counts: List[int] = [0] * (len(edges) - 1)
        self.n = 0
        self.missing = 0
        self.sum = Fraction(0)
        self.sumsq = Fraction(0)
        self.min = math.inf
        self.max = -math.inf

    # -- ingest --------------------------------------------------------------

    def observe(self, value: Any) -> None:
        if _is_missing(value):
            self.missing += 1
            return
        v = float(value)
        # interior edges only: left of edges[1] -> bin 0, right of
        # edges[-2] -> last bin (the clamp that keeps shifted traffic
        # countable against the reference bins)
        idx = bisect.bisect_right(self.edges, v, 1, len(self.edges) - 1) - 1
        self.counts[idx] += 1
        self.n += 1
        f = Fraction(v)
        self.sum += f
        self.sumsq += f * f
        self.min = min(self.min, v)
        self.max = max(self.max, v)

    def observe_many(self, values: Iterable[Any]) -> None:
        for v in values:
            self.observe(v)

    # -- merge ---------------------------------------------------------------

    def merge(self, other: "ColumnSketch") -> "ColumnSketch":
        """Pure associative merge: a new sketch whose state is the exact
        sum of both operands (edges must match)."""
        if self.edges != other.edges:
            raise ValueError("cannot merge sketches with different edges")
        out = ColumnSketch(self.edges)
        out.counts = [a + b for a, b in zip(self.counts, other.counts)]
        out.n = self.n + other.n
        out.missing = self.missing + other.missing
        out.sum = self.sum + other.sum
        out.sumsq = self.sumsq + other.sumsq
        out.min = min(self.min, other.min)
        out.max = max(self.max, other.max)
        return out

    # -- derived -------------------------------------------------------------

    def mean(self) -> float:
        return float(self.sum / self.n) if self.n else 0.0

    def variance(self) -> float:
        if self.n < 2:
            return 0.0
        mean = self.sum / self.n
        return float(self.sumsq / self.n - mean * mean)

    def missing_rate(self) -> float:
        total = self.n + self.missing
        return self.missing / total if total else 0.0

    def probabilities(self, eps: float = 0.0) -> List[float]:
        """Per-bin mass fractions, optionally eps-smoothed (every bin gets
        ``eps`` extra mass before normalizing)."""
        total = self.n + eps * len(self.counts)
        if total <= 0:
            return [1.0 / len(self.counts)] * len(self.counts)
        return [(c + eps) / total for c in self.counts]

    def cdf(self) -> List[float]:
        """Cumulative mass at each interior edge + the upper edge."""
        out: List[float] = []
        cum = 0
        for c in self.counts:
            cum += c
            out.append(cum / self.n if self.n else 0.0)
        return out

    def quantile(self, q: float) -> float:
        """Quantile estimate by linear interpolation inside the owning
        bin (the registry histogram's ``percentile`` posture)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("q must be in [0, 1]")
        if self.n == 0:
            return 0.0
        rank = q * self.n
        cum = 0
        for i, c in enumerate(self.counts):
            prev, cum = cum, cum + c
            if cum >= rank and c > 0:
                lo, hi = self.edges[i], self.edges[i + 1]
                return lo + (hi - lo) * (rank - prev) / c
        return self.edges[-1]

    # -- serialization (canonical; byte-stable across merge orders) ----------

    def to_dict(self) -> Dict[str, Any]:
        return {
            "edges": list(self.edges),
            "counts": list(self.counts),
            "n": self.n,
            "missing": self.missing,
            # Fractions serialize exactly as "numerator/denominator"
            "sum": f"{self.sum.numerator}/{self.sum.denominator}",
            "sumsq": f"{self.sumsq.numerator}/{self.sumsq.denominator}",
            "min": None if self.n == 0 else self.min,
            "max": None if self.n == 0 else self.max,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True, separators=(",", ":"))

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ColumnSketch":
        out = cls(d["edges"])
        counts = [int(c) for c in d["counts"]]
        if len(counts) != len(out.counts):
            raise ValueError("counts length does not match edges")
        out.counts = counts
        out.n = int(d["n"])
        out.missing = int(d["missing"])
        out.sum = Fraction(d["sum"])
        out.sumsq = Fraction(d["sumsq"])
        out.min = math.inf if d.get("min") is None else float(d["min"])
        out.max = -math.inf if d.get("max") is None else float(d["max"])
        return out


# -- drift statistics (reference vs live, shared edges) ----------------------


def psi(
    reference: ColumnSketch,
    live: ColumnSketch,
    eps: float = PSI_EPS,
) -> float:
    """Population Stability Index over the shared bins:
    ``sum((q_i - p_i) * ln(q_i / p_i))`` with eps-smoothed masses so an
    empty bin on either side stays finite. Conventional reading: < 0.1
    stable, 0.1-0.2 moderate shift, > 0.2 significant shift."""
    if reference.edges != live.edges:
        raise ValueError("PSI requires sketches over the same edges")
    p = reference.probabilities(eps=eps)
    q = live.probabilities(eps=eps)
    return float(sum((qi - pi) * math.log(qi / pi) for pi, qi in zip(p, q)))


def ks_statistic(reference: ColumnSketch, live: ColumnSketch) -> float:
    """Two-sample Kolmogorov-Smirnov statistic evaluated at the bin
    edges: ``max_i |CDF_ref(e_i) - CDF_live(e_i)|``. A lower bound on the
    exact-sample KS (the CDFs are only compared where the bins cut), which
    is the right bias for an alerting statistic over fixed bins."""
    if reference.edges != live.edges:
        raise ValueError("KS requires sketches over the same edges")
    return float(
        max(
            (abs(a - b) for a, b in zip(reference.cdf(), live.cdf())),
            default=0.0,
        )
    )


def merge_all(sketches: Sequence[ColumnSketch]) -> Optional[ColumnSketch]:
    """Left fold of :meth:`ColumnSketch.merge` (associative, so any fold
    shape gives the same bytes); None for an empty sequence."""
    if not sketches:
        return None
    out = sketches[0]
    for s in sketches[1:]:
        out = out.merge(s)
    return out
