"""Prometheus-style metrics registry: counters, gauges, latency histograms.

Spark exposes its task/scheduler/streaming metrics through a registry the
UI and sinks scrape; the analogue here is a process-global
:class:`MetricsRegistry` whose text *exposition* is the Prometheus format
(``GET /metrics`` on :class:`~mmlspark_tpu.serving.ServingServer` serves
it directly):

    reg = get_registry()
    reg.counter("serving_requests_total", "Requests answered").inc()
    h = reg.histogram("serving_apply_latency_seconds", "Model apply time")
    h.observe(0.0021)
    print(reg.exposition())

Design constraints the implementation honors:

- **get-or-create**: registering the same (name, type) twice returns the
  same metric — many ``_BatchLoop``/``RuntimeMetrics`` instances feed the
  shared plane; a name collision across *types* is a hard error;
- **labels**: ``metric.labels(reason="timeout")`` binds label values to a
  child series (rendered ``name{reason="timeout"}``); the bare metric is
  the unlabeled series;
- **histograms** use fixed buckets (cumulative ``_bucket{le=...}`` series
  plus ``_sum``/``_count``) and answer ``p50/p95/p99`` by linear
  interpolation inside the owning bucket — the same estimate
  ``histogram_quantile`` computes server-side;
- every mutation is a few dict/float ops under a per-metric lock — safe
  from scheduler worker threads and HTTP handler threads alike, and cheap
  enough for the serving hot path.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: default latency buckets (seconds): 100us .. 10s, roughly log-spaced
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

#: fit/compile-scale buckets (seconds to half an hour). DEFAULT_BUCKETS
#: top out at 10 s, so fit-scale durations all land in +Inf and the
#: interpolated p99 clamps to 10.0 — meaningless for a multi-minute fit
#: or an XLA compile. Register fit and compile histograms with these;
#: keep DEFAULT_BUCKETS for serving-latency metrics.
FIT_BUCKETS: Tuple[float, ...] = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0, 120.0, 300.0, 600.0, 1800.0,
)


def _format_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _escape(value: str) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"')


def _render_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class _Metric:
    """Shared base: name/help/type plus the labeled-children table."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._children: Dict[Tuple[Tuple[str, str], ...], "_Metric"] = {}

    def labels(self, **labels: str) -> "_Metric":
        """Child series bound to these label values (created on first use)."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = type(self)(self.name, self.help)
                if isinstance(child, Histogram):
                    child.buckets = self.buckets  # type: ignore[attr-defined]
                    child._counts = [0] * (len(child.buckets) + 1)
                child._label_values = dict(key)  # type: ignore[attr-defined]
                self._children[key] = child
            return child

    def _series(self) -> Iterable[Tuple[Dict[str, str], "_Metric"]]:
        """(labels, series) pairs: the bare series when touched, then every
        labeled child."""
        with self._lock:
            children = list(self._children.values())
        if self._touched():
            yield getattr(self, "_label_values", {}), self
        for child in children:
            yield child._label_values, child  # type: ignore[attr-defined]

    def _touched(self) -> bool:
        return True

    def render(self) -> List[str]:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._value = 0.0
        self._used = False

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount
            self._used = True

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _touched(self) -> bool:
        return self._used or not self._children

    def render(self) -> List[str]:
        return [
            f"{self.name}{_render_labels(labels)} {_format_value(series._value)}"
            for labels, series in self._series()
        ]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._value = 0.0
        self._used = False

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)
            self._used = True

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount
            self._used = True

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_max(self, value: float) -> None:
        """Monotonic high-water update (max queue depth et al.)."""
        with self._lock:
            if value > self._value:
                self._value = float(value)
            self._used = True

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _touched(self) -> bool:
        return self._used or not self._children

    def render(self) -> List[str]:
        return [
            f"{self.name}{_render_labels(labels)} {_format_value(series._value)}"
            for labels, series in self._series()
        ]


class Histogram(_Metric):
    """Fixed-bucket latency histogram with Prometheus exposition and
    bucket-interpolated quantiles (``p50/p95/p99`` via :meth:`percentile`)."""

    kind = "histogram"

    def __init__(
        self,
        name: str,
        help: str = "",
        buckets: Optional[Sequence[float]] = None,
    ):
        super().__init__(name, help)
        self.buckets: Tuple[float, ...] = tuple(buckets or DEFAULT_BUCKETS)
        if list(self.buckets) != sorted(self.buckets):
            raise ValueError("histogram buckets must be sorted ascending")
        # _counts[i] observations <= buckets[i]; last slot is +Inf overflow
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        with self._lock:
            self._sum += value
            self._count += 1
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """Quantile estimate (q in [0, 1]) by linear interpolation within
        the owning bucket — ``histogram_quantile``'s estimate. Returns 0.0
        with no observations; observations beyond the last finite bucket
        clamp to its upper bound."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("q must be in [0, 1]")
        with self._lock:
            total = self._count
            counts = list(self._counts)
        if total == 0:
            return 0.0
        rank = q * total
        cum = 0
        for i, bound in enumerate(self.buckets):
            prev_cum, cum = cum, cum + counts[i]
            if cum >= rank and counts[i] > 0:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                frac = (rank - prev_cum) / counts[i]
                return lo + (bound - lo) * min(max(frac, 0.0), 1.0)
        return self.buckets[-1]

    def summary(self) -> Dict[str, float]:
        return {
            "count": self.count,
            "sum": self.sum,
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }

    def _touched(self) -> bool:
        return self._count > 0 or not self._children

    def render(self) -> List[str]:
        lines: List[str] = []
        for labels, series in self._series():
            with series._lock:
                counts = list(series._counts)  # type: ignore[attr-defined]
                total, ssum = series._count, series._sum  # type: ignore[attr-defined]
            cum = 0
            for bound, n in zip(series.buckets, counts):  # type: ignore[attr-defined]
                cum += n
                le = dict(labels, le=_format_value(bound))
                lines.append(f"{self.name}_bucket{_render_labels(le)} {cum}")
            le = dict(labels, le="+Inf")
            lines.append(f"{self.name}_bucket{_render_labels(le)} {total}")
            lines.append(
                f"{self.name}_sum{_render_labels(labels)} {repr(float(ssum))}"
            )
            lines.append(f"{self.name}_count{_render_labels(labels)} {total}")
        return lines


class MetricsRegistry:
    """Name -> metric table with get-or-create registration and Prometheus
    text exposition."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}"
                    )
                return existing
            metric = cls(name, help, **kwargs)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)  # type: ignore[return-value]

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)  # type: ignore[return-value]

    def histogram(
        self,
        name: str,
        help: str = "",
        buckets: Optional[Sequence[float]] = None,
    ) -> Histogram:
        return self._get_or_create(  # type: ignore[return-value]
            Histogram, name, help, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def exposition(self) -> str:
        """The Prometheus text format (version 0.0.4): ``# HELP``/``# TYPE``
        headers followed by every series, metrics in name order."""
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def summary(self) -> Dict[str, object]:
        """Plain-dict snapshot: scalar for unlabeled counters/gauges, a
        ``{"k=v": value}`` dict for labeled ones, count/sum/p50/p95/p99
        for histograms."""
        with self._lock:
            metrics = dict(self._metrics)
        out: Dict[str, object] = {}
        for name, m in sorted(metrics.items()):
            if isinstance(m, Histogram):
                out[name] = m.summary()
            elif isinstance(m, (Counter, Gauge)):
                labeled: Dict[str, float] = {
                    ",".join(f"{k}={v}" for k, v in lbl.items()): series.value  # type: ignore[attr-defined]
                    for lbl, series in m._series()
                }
                out[name] = labeled if set(labeled) - {""} else m.value
        return out


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry the serving ``/metrics`` endpoint
    exposes. Tests wanting isolation construct their own
    :class:`MetricsRegistry` and pass it to the instrumented component."""
    return _REGISTRY
