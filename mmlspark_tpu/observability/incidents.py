"""Incident flight recorder — atomic post-mortem bundles on tripwires.

When the fleet misbehaves (a circuit breaker trips, a worker gang
exhausts its epoch budget, a worker is quarantined, the SLO error budget
is breached), the evidence is scattered: the last events live in
per-process log segments, the metrics in each replica's registry, the
trace in the tracer ring, the device profile in the profiler. By the
time someone looks, most of it has rotated away. The
:class:`FlightRecorder` is the black box: it rides the event bus keeping
a bounded ring of recent events, and on a tripwire dumps one **atomic**
bundle directory:

- ``manifest.json`` — incident id, trigger, wall time, trace id, detail;
- ``events.jsonl``  — the last N events **across processes** (the merged
  fleet tail when ``MMLSPARK_TPU_EVENT_LOG`` is set, the in-memory ring
  otherwise);
- ``metrics.json``  — the federated fleet snapshot when a
  :class:`~mmlspark_tpu.observability.federation.MetricsFederator` is
  attached, else the local registry summary;
- ``trace.json``    — the offending trace's span tree (or the most
  recent finished spans when no trace id is known);
- ``profiler.json`` — the device profiler snapshot when profiling is on.

Bundles are written to a temp directory and ``os.replace``d into place,
then booked as an :class:`~mmlspark_tpu.observability.events.IncidentRecorded`
event so the history server lists them. A per-trigger cooldown stops an
event storm from writing a thousand identical bundles.

Like the event-log sink, the recorder is env-driven:
``MMLSPARK_TPU_INCIDENT_DIR=/path`` installs a process-global recorder
on first :func:`get_recorder` / :func:`maybe_record` call; subsystems
that raise (the process group's ``GangFailedError`` path) call
:func:`maybe_record` which is a no-op when no recorder is installed.
"""

from __future__ import annotations

import collections
import json
import os
import shutil
import threading
import time
from typing import Any, Dict, List, Optional

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.observability import events as _events
from mmlspark_tpu.observability.events import (
    BreakerTripped,
    Event,
    IncidentRecorded,
    IncidentSkipped,
    WorkerQuarantined,
)

logger = get_logger("mmlspark_tpu.observability")

#: the tripwire names a bundle's manifest carries
TRIGGERS = (
    "alert_fired",
    "breaker_tripped",
    "drift_detected",
    "gang_failed",
    "slo_budget",
    "worker_quarantined",
)


class FlightRecorder:
    """Bounded event ring + atomic incident bundles (see module doc).

    ``install()`` attaches the recorder to the process-global bus so it
    both fills its ring and auto-records on :class:`BreakerTripped` /
    :class:`WorkerQuarantined`; :meth:`record` is the manual tripwire
    (``gang_failed``, ``slo_budget``). ``clock`` is injectable so tests
    can step the cooldown deterministically."""

    def __init__(
        self,
        directory: str,
        capacity: int = 512,
        cooldown_s: float = 30.0,
        event_log: Optional[str] = None,
        federator: Optional[Any] = None,
        registry: Optional[Any] = None,
        tracer: Optional[Any] = None,
        clock=time.time,
    ):
        self.directory = directory
        self.capacity = int(capacity)
        self.cooldown_s = float(cooldown_s)
        self.event_log = (
            event_log
            if event_log is not None
            else os.environ.get("MMLSPARK_TPU_EVENT_LOG")
        )
        #: optional MetricsFederator — when set, ``metrics.json`` is the
        #: fleet snapshot instead of the local registry summary
        self.federator = federator
        self.registry = registry
        self.tracer = tracer
        self._clock = clock
        self._ring: "collections.deque[Event]" = collections.deque(
            maxlen=self.capacity
        )
        self._lock = threading.Lock()
        self._seq = 0
        self._last_at: Dict[str, float] = {}
        self.recorded: List[str] = []

    # -- bus integration -----------------------------------------------------

    def install(self) -> "FlightRecorder":
        _events.get_bus().add_listener(self._on_event)
        return self

    def uninstall(self) -> None:
        _events.get_bus().remove_listener(self._on_event)

    def _on_event(self, event: Event) -> None:
        if isinstance(event, (IncidentRecorded, IncidentSkipped)):
            return  # our own bookkeeping must not re-trip the recorder
        with self._lock:
            self._ring.append(event)
        if isinstance(event, BreakerTripped):
            self.record(
                "breaker_tripped",
                detail=f"{event.breaker}: {event.failures} failures "
                f"in {event.window_s}s",
            )
        elif isinstance(event, WorkerQuarantined):
            self.record(
                "worker_quarantined",
                detail=f"worker {event.worker} score {event.score:.2f}",
            )

    # -- the tripwire --------------------------------------------------------

    def record(
        self, trigger: str, trace_id: str = "", detail: str = ""
    ) -> Optional[str]:
        """Dump one bundle for ``trigger``; returns the bundle directory,
        or None when the trigger is inside its cooldown. Never raises —
        a flight recorder that crashes the plane is worse than none."""
        now = self._clock()
        with self._lock:
            last = self._last_at.get(trigger)
            if last is not None and now - last < self.cooldown_s:
                return None
            self._last_at[trigger] = now
            self._seq += 1
            seq = self._seq
        incident_id = f"{int(now)}-{trigger}-{seq:03d}"
        try:
            path = self._write_bundle(incident_id, trigger, trace_id, detail, now)
        except Exception as e:  # noqa: BLE001 - see docstring
            logger.warning("incident bundle %s failed: %s", incident_id, e)
            _events.get_bus().publish(IncidentSkipped(
                trigger=trigger,
                reason=str(e)[:200],
                incident_id=incident_id,
            ))
            return None
        self.recorded.append(path)
        _events.get_bus().publish(IncidentRecorded(
            incident_id=incident_id,
            trigger=trigger,
            path=path,
            events=len(self._ring),
            trace_id=trace_id,
            detail=detail,
        ))
        return path

    # -- bundle assembly -----------------------------------------------------

    def _recent_records(self) -> List[Dict[str, Any]]:
        """The last-N-events evidence: the merged fleet tail when an
        event log is federated on disk, the in-memory ring otherwise."""
        log = self.event_log or os.environ.get("MMLSPARK_TPU_EVENT_LOG")
        if log:
            try:
                merged = _events._merged_records(log)
                if merged:
                    return merged[-self.capacity:]
            except Exception as e:  # noqa: BLE001 - half-written segments
                logger.debug("incident merge failed, using ring: %s", e)
        with self._lock:
            ring = list(self._ring)
        out = []
        for ev in ring:
            rec = ev.to_record()
            rec.setdefault("process", _events.process_label())
            out.append(rec)
        return out

    def _metrics_snapshot(self) -> Dict[str, Any]:
        if self.federator is not None:
            try:
                return self.federator.snapshot()
            except Exception as e:  # noqa: BLE001
                logger.debug("incident fleet snapshot failed: %s", e)
        registry = self.registry
        if registry is None:
            from mmlspark_tpu.observability.registry import get_registry

            registry = get_registry()
        return {"metrics": registry.summary()}

    def _trace_snapshot(self, trace_id: str) -> Dict[str, Any]:
        tracer = self.tracer
        if tracer is None:
            from mmlspark_tpu.observability.tracing import get_tracer

            tracer = get_tracer()
        if trace_id:
            return tracer.span_tree(trace_id)
        return {"trace_id": "", "spans": tracer.export()[-64:]}

    def _write_bundle(
        self,
        incident_id: str,
        trigger: str,
        trace_id: str,
        detail: str,
        now: float,
    ) -> str:
        from mmlspark_tpu.runtime.faults import check_write

        records = self._recent_records()
        final = os.path.join(self.directory, incident_id)
        # injected-ENOSPC gate: a full incident volume skips the bundle
        # (record() books IncidentSkipped) instead of crashing the caller
        check_write(final)
        tmp = os.path.join(self.directory, f".tmp-{incident_id}-{os.getpid()}")
        os.makedirs(tmp, exist_ok=True)
        try:
            with open(os.path.join(tmp, "events.jsonl"), "w",
                      encoding="utf-8") as fh:
                for rec in records:
                    fh.write(json.dumps(rec) + "\n")
            metrics = self._metrics_snapshot()
            with open(os.path.join(tmp, "metrics.json"), "w",
                      encoding="utf-8") as fh:
                json.dump(metrics, fh, indent=2,
                          sort_keys=True, default=str)
            with open(os.path.join(tmp, "trace.json"), "w",
                      encoding="utf-8") as fh:
                json.dump(self._trace_snapshot(trace_id), fh, indent=2,
                          default=str)
            profile = self._profiler_snapshot()
            if profile is not None:
                with open(os.path.join(tmp, "profiler.json"), "w",
                          encoding="utf-8") as fh:
                    json.dump(profile, fh, indent=2, default=str)
            quality = self._quality_snapshot(metrics)
            if quality is not None:
                with open(os.path.join(tmp, "quality.json"), "w",
                          encoding="utf-8") as fh:
                    json.dump(quality, fh, indent=2, sort_keys=True,
                              default=str)
            with open(os.path.join(tmp, "manifest.json"), "w",
                      encoding="utf-8") as fh:
                json.dump({
                    "incident_id": incident_id,
                    "trigger": trigger,
                    "trace_id": trace_id,
                    "detail": detail,
                    "wall_time": now,
                    "process": _events.process_label(),
                    "events": len(records),
                }, fh, indent=2, sort_keys=True)
            os.replace(tmp, final)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        return final

    @staticmethod
    def _profiler_snapshot() -> Optional[Dict[str, Any]]:
        from mmlspark_tpu.observability.profiler import get_profiler

        profiler = get_profiler()
        if not profiler.active:
            return None
        return profiler.snapshot()

    @staticmethod
    def _quality_snapshot(metrics: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """The drift-table evidence (``quality.json``): the live monitor's
        snapshot when one runs in this process, else the per-feature table
        rebuilt from the (possibly federated) ``metrics.json`` summary;
        None when the quality plane left no trace."""
        from mmlspark_tpu.observability.quality import (
            drift_table_from_summary,
            get_monitor,
        )

        monitor = get_monitor()
        if monitor is not None:
            return monitor.snapshot()
        summary = metrics.get("metrics", {})
        rows = drift_table_from_summary(summary)
        if not rows:
            return None
        return {"drift": rows}


# -- process-global, env-driven recorder --------------------------------------

_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def get_recorder() -> Optional[FlightRecorder]:
    """The env-driven process-global recorder: setting
    ``MMLSPARK_TPU_INCIDENT_DIR=/path`` installs one (bus-attached) on
    first call; unsetting it uninstalls. Returns None when disabled."""
    global _RECORDER
    directory = os.environ.get("MMLSPARK_TPU_INCIDENT_DIR")
    current = _RECORDER.directory if _RECORDER is not None else None
    if directory == current:
        return _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is not None:
            _RECORDER.uninstall()
            _RECORDER = None
        if directory:
            try:
                os.makedirs(directory, exist_ok=True)
            except OSError as e:
                logger.warning(
                    "MMLSPARK_TPU_INCIDENT_DIR=%s unusable: %s", directory, e
                )
                return None
            _RECORDER = FlightRecorder(directory).install()
    return _RECORDER


def maybe_record(
    trigger: str, trace_id: str = "", detail: str = ""
) -> Optional[str]:
    """Record an incident iff a recorder is installed — the call
    subsystems make at their own tripwires (``gang_failed``,
    ``slo_budget``) without caring whether anyone is listening."""
    recorder = get_recorder()
    if recorder is None:
        return None
    return recorder.record(trigger, trace_id=trace_id, detail=detail)
