"""Device-performance profiler — the task-metrics/SQL-metrics half of the
observability plane (SURVEY.md §5).

Spark's UI attributes every stage to compile/deserialize/run/GC time and
every SQL operator to rows/bytes/time; nothing here could say the same
about a jitted hot path (ROADMAP items 2-4: the flat bench trajectory has
no compile-time accounting, no HBM/roofline attribution, no
registry-derived SLO for serving). :class:`DeviceProfiler` closes that by
wrapping the jitted callables the framework dispatches:

- **compile accounting**: an unseen input signature (an executable-cache
  miss, read from the jit cache itself when the function exposes it)
  books a :class:`~mmlspark_tpu.observability.events.ProfileCompiled`
  event with the compiling call's wall time;
- **device timing**: every call runs in a ``block_until_ready`` window
  and books :class:`~mmlspark_tpu.observability.events.ProfileExecuted`
  plus a ``profiler_device_seconds{fn=...}`` histogram observation;
- **roofline attribution**: XLA ``cost_analysis()`` FLOPs / bytes for
  the compiled program fold into achieved FLOP/s and bytes/s against the
  device's peak MXU / HBM numbers (``docs/perf_histogram.md`` uses the
  same v5e peaks), labelling each hot path compute- or memory-bound;
- **HBM gauges**: :meth:`sample_memory` reads ``Device.memory_stats()``
  into ``profiler_hbm_bytes_in_use``/``_limit`` gauges (absent on
  backends that do not report, e.g. CPU — sampling is always safe);
- **transfer counters**: :meth:`note_transfer` accumulates host<->device
  bytes into ``profiler_transfer_bytes_total{direction=...}``.

The process-global profiler (:func:`get_profiler`) is DISABLED by
default: wrapped call sites fall through with one attribute read, so the
serving hot path and the fit loop pay nothing until someone sets
``MMLSPARK_TPU_PROFILE=1`` or calls ``get_profiler().enable()`` (the
bench drivers and the perf-report CI smoke do).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.observability.events import (
    ProfileCompiled,
    ProfileExecuted,
    get_bus,
)
from mmlspark_tpu.observability.registry import (
    FIT_BUCKETS,
    MetricsRegistry,
    get_registry,
)

logger = get_logger("mmlspark_tpu.observability")

#: device_kind substring (lowercased) -> (peak FLOP/s, peak HBM bytes/s).
#: v5e numbers are the bf16 MXU peak and the HBM bandwidth the round-4
#: roofline case in docs/perf_histogram.md is argued against (670 GB/s
#: measured = 83% of peak). Unknown backends report (0, 0) with the
#: ``unknown-platform`` sentinel and roofline fractions stay None.
_DEVICE_PEAKS: Tuple[Tuple[str, Tuple[float, float]], ...] = (
    ("v5 lite", (1.97e14, 8.1e11)),
    ("v5e", (1.97e14, 8.1e11)),
    ("v5p", (4.59e14, 2.765e12)),
    ("v4", (2.75e14, 1.2e12)),
    ("v3", (1.23e14, 9.0e11)),
)

#: the platform label when no peak-table row (and no env override)
#: matched — CI CPU rigs land here. Bound classification is SKIPPED for
#: this sentinel: labelling a host CPU "compute-bound" against a TPU
#: machine-balance ridge is provenance-free noise (ISSUE 18 satellite).
UNKNOWN_PLATFORM = "unknown-platform"


class DevicePeaks(tuple):
    """``(peak FLOP/s, peak HBM bytes/s)`` that still unpacks like the
    bare 2-tuple it replaces, plus the ``platform`` label the peaks came
    from (``v5e``, ``env-override``, or :data:`UNKNOWN_PLATFORM`)."""

    def __new__(
        cls, peak_flops: float, peak_bw: float, platform: str
    ) -> "DevicePeaks":
        self = super().__new__(cls, (float(peak_flops), float(peak_bw)))
        self.platform = str(platform)
        return self

    @property
    def known(self) -> bool:
        return self.platform != UNKNOWN_PLATFORM


def device_peaks(device=None) -> DevicePeaks:
    """:class:`DevicePeaks` for ``device`` (default: the first jax
    device), overridable via ``MMLSPARK_TPU_PEAK_FLOPS`` /
    ``MMLSPARK_TPU_PEAK_HBM_BYTES`` for rigs the table doesn't know.
    A rig with no table row and no override gets ``(0, 0)`` labelled
    :data:`UNKNOWN_PLATFORM`, never a silently-zero TPU claim."""
    env_f = os.environ.get("MMLSPARK_TPU_PEAK_FLOPS")
    env_b = os.environ.get("MMLSPARK_TPU_PEAK_HBM_BYTES")
    if env_f or env_b:
        return DevicePeaks(
            float(env_f or 0.0), float(env_b or 0.0), "env-override"
        )
    if device is None:
        try:
            import jax

            device = jax.devices()[0]
        except Exception:  # noqa: BLE001 - no backend is a valid state
            return DevicePeaks(0.0, 0.0, UNKNOWN_PLATFORM)
    kind = str(getattr(device, "device_kind", "")).lower()
    for needle, peaks in _DEVICE_PEAKS:
        if needle in kind:
            return DevicePeaks(peaks[0], peaks[1], needle)
    return DevicePeaks(0.0, 0.0, UNKNOWN_PLATFORM)


@dataclasses.dataclass
class FunctionProfile:
    """Accumulated per-function profile (one row of the roofline table)."""

    name: str
    compiles: int = 0
    compile_seconds: float = 0.0
    cache_hits: int = 0
    executions: int = 0
    device_seconds: float = 0.0
    #: cost_analysis estimates for ONE execution of the compiled program
    flops: float = 0.0
    bytes_accessed: float = 0.0
    transfer_bytes: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def roofline(
        self,
        peak_flops: float = 0.0,
        peak_bw: float = 0.0,
        platform: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Achieved vs peak attribution for this function: FLOP/s and
        bytes/s over the mean execution window, the fraction of the MXU
        and HBM peaks they represent, and which wall the program leans
        on (``bound``). On an :data:`UNKNOWN_PLATFORM` rig the bound
        stays ``"unknown"`` — the intensity fallback argues against a
        TPU machine balance no unknown rig is known to have."""
        row: Dict[str, Any] = {
            "name": self.name,
            "executions": self.executions,
            "mean_ms": (
                self.device_seconds / self.executions * 1e3
                if self.executions else 0.0
            ),
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "achieved_flops_per_s": 0.0,
            "achieved_bytes_per_s": 0.0,
            "mxu_frac": None,
            "hbm_frac": None,
            "bound": "unknown",
        }
        if platform is not None:
            row["platform"] = platform
        if self.executions and self.device_seconds > 0:
            mean = self.device_seconds / self.executions
            row["achieved_flops_per_s"] = self.flops / mean
            row["achieved_bytes_per_s"] = self.bytes_accessed / mean
        if peak_flops > 0 and row["achieved_flops_per_s"]:
            row["mxu_frac"] = row["achieved_flops_per_s"] / peak_flops
        if peak_bw > 0 and row["achieved_bytes_per_s"]:
            row["hbm_frac"] = row["achieved_bytes_per_s"] / peak_bw
        if row["mxu_frac"] is not None and row["hbm_frac"] is not None:
            row["bound"] = (
                "memory" if row["hbm_frac"] >= row["mxu_frac"] else "compute"
            )
        elif platform != UNKNOWN_PLATFORM and (
            self.flops or self.bytes_accessed
        ):
            # no peak table but a KNOWN platform: still label by arithmetic
            # intensity against the classic ~10 FLOPs/byte machine-balance
            # ridge (division guarded — zero bytes_accessed clamps to 1)
            intensity = self.flops / max(self.bytes_accessed, 1.0)
            row["bound"] = "compute" if intensity > 10.0 else "memory"
        return row


def _signature(args, kwargs) -> str:
    """Shape/dtype signature of a call, mirroring what the jit cache
    keys on closely enough to detect retraces."""
    parts: List[str] = []
    for a in list(args) + sorted(kwargs.items()):
        if isinstance(a, tuple):
            a = a[1]
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None:
            parts.append(f"{dtype}{tuple(shape)}")
        else:
            parts.append(type(a).__name__)
    return ",".join(parts)


def _jit_cache_size(fn) -> Optional[int]:
    """The jitted function's in-process executable-cache size, when the
    jax version exposes it (the authoritative hit/miss signal)."""
    probe = getattr(fn, "_cache_size", None)
    if probe is None:
        return None
    try:
        return int(probe())
    except Exception:  # noqa: BLE001 - introspection only, never fatal
        return None


class DeviceProfiler:
    """Wraps jitted hot paths with compile/execute/roofline accounting.

    Pass an isolated ``registry``/``bus`` for tests; the process-global
    instance (:func:`get_profiler`) feeds the shared metrics plane and
    event bus. ``enabled=False`` makes every entry point a cheap no-op
    and :meth:`wrap` the identity."""

    def __init__(
        self,
        registry: Optional[MetricsRegistry] = None,
        bus=None,
        enabled: bool = True,
        cost_analysis: bool = True,
    ):
        self.registry = registry if registry is not None else get_registry()
        self._bus = bus
        self.enabled = bool(enabled)
        self.cost_analysis = bool(cost_analysis)
        self._lock = threading.Lock()
        self._profiles: Dict[str, FunctionProfile] = {}
        reg = self.registry
        self._reg_compiles = reg.counter(
            "profiler_compiles_total",
            "Executable compiles observed by the device profiler",
        )
        self._reg_cache_hits = reg.counter(
            "profiler_cache_hits_total",
            "Profiled calls answered from a warm executable cache",
        )
        self._reg_compile_s = reg.histogram(
            "profiler_compile_seconds",
            "Wall time of compiling calls (trace + XLA compile + first run)",
            buckets=FIT_BUCKETS,
        )
        self._reg_device_s = reg.histogram(
            "profiler_device_seconds",
            "Per-call device window (dispatch through block_until_ready)",
        )
        self._reg_transfer = reg.counter(
            "profiler_transfer_bytes_total",
            "Host<->device bytes moved through profiled call sites",
        )

    # -- plumbing ------------------------------------------------------------

    @property
    def bus(self):
        return self._bus if self._bus is not None else get_bus()

    @property
    def active(self) -> bool:
        return self.enabled

    def enable(self) -> "DeviceProfiler":
        self.enabled = True
        return self

    def disable(self) -> "DeviceProfiler":
        self.enabled = False
        return self

    def _profile(self, name: str) -> FunctionProfile:
        with self._lock:
            prof = self._profiles.get(name)
            if prof is None:
                prof = self._profiles[name] = FunctionProfile(name)
            return prof

    # -- recording -----------------------------------------------------------

    def note_compile(
        self,
        name: str,
        seconds: float,
        flops: float = 0.0,
        bytes_accessed: float = 0.0,
        signature: str = "",
    ) -> None:
        prof = self._profile(name)
        with self._lock:
            prof.compiles += 1
            prof.compile_seconds += seconds
            if flops:
                prof.flops = flops
            if bytes_accessed:
                prof.bytes_accessed = bytes_accessed
        self._reg_compiles.labels(fn=name).inc()
        self._reg_compile_s.observe(seconds)
        bus = self.bus
        if bus.active:
            bus.publish(ProfileCompiled(
                name=name, seconds=seconds, flops=flops,
                bytes_accessed=bytes_accessed, signature=signature,
            ))

    def note_execute(self, name: str, seconds: float) -> None:
        prof = self._profile(name)
        with self._lock:
            prof.executions += 1
            prof.device_seconds += seconds
        self._reg_device_s.labels(fn=name).observe(seconds)
        bus = self.bus
        if bus.active:
            bus.publish(ProfileExecuted(name=name, seconds=seconds))

    def note_cache_hit(self, name: str) -> None:
        prof = self._profile(name)
        with self._lock:
            prof.cache_hits += 1
        self._reg_cache_hits.labels(fn=name).inc()

    def note_transfer(
        self, nbytes: float, direction: str = "h2d", name: str = ""
    ) -> None:
        """Book host->device (``h2d``) or device->host (``d2h``) bytes."""
        if nbytes <= 0:
            return
        self._reg_transfer.labels(direction=direction).inc(float(nbytes))
        if name:
            prof = self._profile(name)
            with self._lock:
                prof.transfer_bytes += float(nbytes)

    def merge(
        self,
        name: str,
        executions: int = 0,
        device_seconds: float = 0.0,
        compiles: int = 0,
        compile_seconds: float = 0.0,
    ) -> None:
        """Fold externally measured totals into the profile table — the
        per-member fold for process-spanning fits, where each worker
        times its own collectives and the driver merges the summaries.
        Counters update; histograms don't (the per-call distribution
        never crossed the process boundary). Only the profile table (and
        so roofline/snapshot) updates — histograms and hit counters stay
        the driver's own observations."""
        prof = self._profile(name)
        with self._lock:
            prof.executions += int(executions)
            prof.device_seconds += float(device_seconds)
            prof.compiles += int(compiles)
            prof.compile_seconds += float(compile_seconds)
        if compiles:
            self._reg_compiles.labels(fn=name).inc(int(compiles))

    def note_program_cache(self, hit: bool, size: int) -> None:
        """Accounting for callers that manage their own compiled-program
        cache (the GBDT fit's LRU of jitted step/scan programs): hit/miss
        counters plus a live size gauge."""
        reg = self.registry
        if hit:
            reg.counter(
                "profiler_program_cache_hits_total",
                "Jitted-program cache hits (no retrace/lower)",
            ).inc()
        else:
            reg.counter(
                "profiler_program_cache_misses_total",
                "Jitted-program cache misses (program built + traced)",
            ).inc()
        reg.gauge(
            "profiler_program_cache_size",
            "Compiled programs resident in the fit program cache",
        ).set(size)

    @contextmanager
    def measure(self, name: str):
        """Time a host-side window as one execution of ``name`` (the
        caller is responsible for any device sync inside the block)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.note_execute(name, time.perf_counter() - t0)

    # -- the wrapper ---------------------------------------------------------

    def wrap(
        self,
        fn: Callable[..., Any],
        name: Optional[str] = None,
        cost_analysis: Optional[bool] = None,
    ) -> Callable[..., Any]:
        """Profile a (jitted) callable. Each call runs in a
        ``block_until_ready`` window; a call that grows the executable
        cache (or presents an unseen shape/dtype signature when the
        cache is not introspectable) books a compile with the program's
        ``cost_analysis()`` FLOPs/bytes, every call books an execution.
        Returns ``fn`` unchanged when the profiler is disabled."""
        if not self.enabled:
            return fn
        label = name or getattr(fn, "__name__", None) or repr(fn)
        do_cost = self.cost_analysis if cost_analysis is None else cost_analysis
        seen: Dict[str, bool] = {}
        profiler = self

        def profiled(*args, **kwargs):
            if not profiler.enabled:
                return fn(*args, **kwargs)
            import jax

            sig = _signature(args, kwargs)
            before = _jit_cache_size(fn)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
            dt = time.perf_counter() - t0
            after = _jit_cache_size(fn)
            if after is not None and before is not None:
                missed = after > before
            else:
                missed = sig not in seen
            seen[sig] = True
            if missed:
                cost = (
                    profiler._cost(fn, args, kwargs) if do_cost else {}
                )
                profiler.note_compile(label, dt, signature=sig, **cost)
            else:
                profiler.note_cache_hit(label)
            profiler.note_execute(label, dt)
            return out

        profiled.__name__ = f"profiled_{label}"
        profiled.__wrapped__ = fn  # type: ignore[attr-defined]
        return profiled

    def wrap_host(
        self, fn: Callable[..., Any], name: str
    ) -> Callable[..., Any]:
        """Time a host-side callable (collective hooks, host folds) as
        executions of ``name`` — no device sync, no compile accounting.
        Returns ``fn`` unchanged when the profiler is disabled."""
        if not self.enabled:
            return fn
        profiler = self

        def timed(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                profiler.note_execute(name, time.perf_counter() - t0)

        timed.__name__ = f"profiled_{name}"
        timed.__wrapped__ = fn  # type: ignore[attr-defined]
        return timed

    def _cost(self, fn, args, kwargs) -> Dict[str, float]:
        """XLA cost_analysis FLOPs/bytes for this call's program; {} when
        the function can't lower or the backend declines to estimate."""
        lower = getattr(fn, "lower", None)
        if lower is None:
            return {}
        try:
            lowered = lower(*args, **kwargs)
        except Exception:  # noqa: BLE001 - profiling must never fail the call
            return {}
        analysis = None
        try:
            analysis = lowered.cost_analysis()
        except Exception:  # noqa: BLE001
            analysis = None
        if not analysis:
            try:
                analysis = lowered.compile().cost_analysis()
            except Exception:  # noqa: BLE001
                return {}
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        if not isinstance(analysis, dict):
            return {}
        return {
            "flops": float(analysis.get("flops", 0.0) or 0.0),
            "bytes_accessed": float(
                analysis.get("bytes accessed", 0.0) or 0.0
            ),
        }

    # -- gauges + reports ----------------------------------------------------

    def sample_memory(self) -> Dict[str, Dict[str, float]]:
        """Read ``Device.memory_stats()`` into per-device HBM gauges.
        Backends that don't report (CPU returns None) yield {} and set
        nothing — always safe to call."""
        try:
            import jax

            devices = jax.devices()
        except Exception:  # noqa: BLE001 - no backend is a valid state
            return {}
        out: Dict[str, Dict[str, float]] = {}
        g_use = self.registry.gauge(
            "profiler_hbm_bytes_in_use", "Device memory in use (memory_stats)"
        )
        g_lim = self.registry.gauge(
            "profiler_hbm_bytes_limit", "Device memory limit (memory_stats)"
        )
        g_peak = self.registry.gauge(
            "profiler_hbm_bytes_peak", "Peak device memory (memory_stats)"
        )
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001
                stats = None
            if not stats:
                continue
            key = str(d)
            rec: Dict[str, float] = {}
            in_use = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit")
            peak = stats.get("peak_bytes_in_use")
            if in_use is not None:
                g_use.labels(device=key).set(float(in_use))
                rec["bytes_in_use"] = float(in_use)
            if limit is not None:
                g_lim.labels(device=key).set(float(limit))
                rec["bytes_limit"] = float(limit)
            if peak is not None:
                g_peak.labels(device=key).set(float(peak))
                rec["peak_bytes_in_use"] = float(peak)
            if rec:
                out[key] = rec
        return out

    def roofline(self) -> List[Dict[str, Any]]:
        """One attribution row per profiled function, hottest first."""
        peaks = device_peaks()
        with self._lock:
            profiles = list(self._profiles.values())
        rows = [
            p.roofline(peaks[0], peaks[1], platform=peaks.platform)
            for p in profiles
        ]
        rows.sort(key=lambda r: -(r["mean_ms"] * r["executions"]))
        return rows

    def snapshot(self) -> Dict[str, Any]:
        """The JSON-safe profiler section for BENCH artifacts: device
        identity + peaks, per-function compile/execute totals, roofline
        rows, and the latest memory sample."""
        try:
            import jax

            device = jax.devices()[0]
            dev = {
                "backend": jax.default_backend(),
                "kind": str(getattr(device, "device_kind", "")),
                "count": len(jax.devices()),
            }
        except Exception:  # noqa: BLE001
            dev = {"backend": "none", "kind": "", "count": 0}
        peaks = device_peaks()
        with self._lock:
            functions = {
                name: p.to_dict() for name, p in self._profiles.items()
            }
        return {
            "device": dev,
            "platform": peaks.platform,
            "peak_flops_per_s": peaks[0],
            "peak_hbm_bytes_per_s": peaks[1],
            "functions": functions,
            "roofline": self.roofline(),
            "memory": self.sample_memory(),
        }

    def clear(self) -> None:
        with self._lock:
            self._profiles.clear()


# -- process-global profiler --------------------------------------------------

_PROFILER: Optional[DeviceProfiler] = None
_PROFILER_LOCK = threading.Lock()


def _env_enabled() -> Optional[bool]:
    raw = os.environ.get("MMLSPARK_TPU_PROFILE")
    if raw is None:
        return None
    return raw.strip().lower() not in ("", "0", "false", "off", "no")


def get_profiler() -> DeviceProfiler:
    """The process-global profiler, DISABLED unless
    ``MMLSPARK_TPU_PROFILE=1`` (re-checked per call, like the event-log
    sink) or a caller ran ``enable()``. Instrumented hot paths guard on
    ``profiler.active`` so the quiet default costs one attribute read."""
    global _PROFILER
    with _PROFILER_LOCK:
        if _PROFILER is None:
            _PROFILER = DeviceProfiler(enabled=bool(_env_enabled()))
    env = _env_enabled()
    if env is not None:
        _PROFILER.enabled = env
    return _PROFILER
