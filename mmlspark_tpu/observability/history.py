"""History server: render an event log into one static HTML report.

Spark's History Server replays ``spark.eventLog.dir`` into the full web
UI after the application is gone; the analogue here folds the JSON-lines
event log (rotated segments included) plus an optional metrics snapshot
into one *self-contained* HTML file — no server process, no assets, open
it from a CI artifact tab:

    python -m mmlspark_tpu.observability.history /tmp/events.jsonl \
        -o report.html --metrics metrics.json

The report shows what the Spark UI's Jobs/Stages/SQL tabs would: the
stage timeline (relative offsets as CSS bars), per-task attempt history
with speculation markers, process-group losses, breaker trips, model
swaps, streaming epochs, the profiler's roofline attribution table, and
the serving SLO verdict (:class:`~mmlspark_tpu.observability.slo.SLOReport`
folded from the same events + snapshot).
"""

from __future__ import annotations

import argparse
import html
import json
import sys
from typing import Any, Dict, Iterable, List, Optional

from mmlspark_tpu.observability.events import (
    Event,
    SpanRecorded,
    collect,
    merge,
    replay,
    timeline,
)
from mmlspark_tpu.observability.profiler import FunctionProfile, device_peaks
from mmlspark_tpu.observability.slo import SLOReport

_CSS = """
body { font-family: -apple-system, 'Segoe UI', Helvetica, Arial, sans-serif;
       margin: 2em auto; max-width: 70em; color: #1a1a2e; }
h1 { font-size: 1.5em; border-bottom: 2px solid #2b6cb0; padding-bottom: .3em; }
h2 { font-size: 1.15em; margin-top: 1.8em; color: #2b6cb0; }
table { border-collapse: collapse; margin: .6em 0; font-size: .92em; }
th, td { border: 1px solid #cbd5e0; padding: .3em .7em; text-align: left; }
th { background: #edf2f7; }
.cards { display: flex; flex-wrap: wrap; gap: .8em; margin: 1em 0; }
.card { border: 1px solid #cbd5e0; border-radius: 6px; padding: .6em 1em;
        min-width: 7em; background: #f7fafc; }
.card .num { font-size: 1.4em; font-weight: 600; }
.card .label { font-size: .8em; color: #4a5568; }
.bar-row { display: flex; align-items: center; margin: 2px 0; font-size: .85em; }
.bar-label { width: 22em; overflow: hidden; text-overflow: ellipsis;
             white-space: nowrap; }
.bar-track { flex: 1; background: #edf2f7; height: 14px; position: relative; }
.bar { position: absolute; height: 100%; background: #4299e1; min-width: 2px; }
.bar.failed { background: #e53e3e; }
.bar.p0 { background: #4299e1; } .bar.p1 { background: #48bb78; }
.bar.p2 { background: #ed8936; } .bar.p3 { background: #9f7aea; }
.bar.p4 { background: #38b2ac; } .bar.p5 { background: #d69e2e; }
.lane-label { width: 22em; font-weight: 600; }
.ok { color: #2f855a; font-weight: 600; }
.missed { color: #c53030; font-weight: 600; }
.muted { color: #718096; }
"""


def _esc(v: Any) -> str:
    return html.escape(str(v))


def _card(label: str, value: Any) -> str:
    return (
        f'<div class="card"><div class="num">{_esc(value)}</div>'
        f'<div class="label">{_esc(label)}</div></div>'
    )


def _table(headers: List[str], rows: List[List[Any]]) -> str:
    head = "".join(f"<th>{_esc(h)}</th>" for h in headers)
    body = "".join(
        "<tr>" + "".join(f"<td>{c}</td>" for c in row) + "</tr>"
        for row in rows
    )
    return f"<table><tr>{head}</tr>{body}</table>"


def _stage_timeline(stages: List[Dict[str, Any]]) -> str:
    """CSS-bar gantt of the stage fold: one row per stage, offset and
    width proportional to the run's wall-clock span."""
    if not stages:
        return '<p class="muted">no stage events</p>'
    t0 = min(s["start"] for s in stages)
    t1 = max(s["start"] + s.get("duration", 0.0) for s in stages)
    span = max(t1 - t0, 1e-9)
    rows = []
    for s in stages:
        dur = s.get("duration", 0.0)
        left = 100.0 * (s["start"] - t0) / span
        width = max(100.0 * dur / span, 0.5)
        cls = "bar failed" if s.get("status", "ok") != "ok" else "bar"
        label = f"[{s['phase']}] {s['name']}"
        rows.append(
            f'<div class="bar-row"><div class="bar-label" '
            f'title="{_esc(label)}">{_esc(label)}</div>'
            f'<div class="bar-track"><div class="{cls}" '
            f'style="left:{left:.2f}%;width:{width:.2f}%"></div></div>'
            f'<div style="width:6em;text-align:right">{dur * 1e3:.1f} ms</div>'
            f"</div>"
        )
    return "".join(rows)


def _attempts_table(tasks: Dict[str, Any]) -> str:
    attempts = tasks.get("attempts") or {}
    if not attempts:
        return '<p class="muted">no failed attempts recorded</p>'
    rows = []
    for task_id in sorted(attempts):
        for a in attempts[task_id]:
            rows.append([
                _esc(task_id),
                _esc(a["attempt"]) + (" (spec)" if a.get("speculative") else ""),
                f"w{_esc(a['worker'])}",
                _esc(a["reason"]),
                f"{a['duration'] * 1e3:.1f} ms",
                '<span class="missed">permanent</span>'
                if a.get("permanent") else "retried",
            ])
    return _table(
        ["task", "attempt", "worker", "reason", "duration", "outcome"], rows
    )


def _roofline_table(profiler: Dict[str, Dict[str, Any]]) -> str:
    if not profiler:
        return '<p class="muted">no profiler events (set MMLSPARK_TPU_PROFILE=1)</p>'
    peaks = device_peaks()
    peak_f, peak_b = peaks
    rows = []
    for name in sorted(profiler):
        p = profiler[name]
        fp = FunctionProfile(
            name=name,
            compiles=int(p.get("compiles", 0)),
            compile_seconds=float(p.get("compile_seconds", 0.0)),
            executions=int(p.get("executions", 0)),
            device_seconds=float(p.get("device_seconds", 0.0)),
            flops=float(p.get("flops", 0.0)),
            bytes_accessed=float(p.get("bytes_accessed", 0.0)),
        )
        r = fp.roofline(peak_f, peak_b, platform=peaks.platform)
        rows.append([
            _esc(name),
            f"{fp.compiles} ({fp.compile_seconds:.3f} s)",
            _esc(fp.executions),
            f"{r['mean_ms']:.3f} ms",
            f"{r['flops']:.3g}",
            f"{r['achieved_flops_per_s']:.3g}",
            f"{r['achieved_bytes_per_s']:.3g}",
            f"{r['mxu_frac']:.1%}" if r["mxu_frac"] is not None else "&mdash;",
            f"{r['hbm_frac']:.1%}" if r["hbm_frac"] is not None else "&mdash;",
            _esc(r["bound"]),
        ])
    provenance = (
        f'<p class="muted">peaks: {_esc(peaks.platform)}'
        + ("" if peaks.known else " &mdash; bound classification skipped")
        + "</p>"
    )
    return provenance + _table(
        ["function", "compiles", "execs", "mean", "flops",
         "FLOP/s", "bytes/s", "MXU %", "HBM %", "bound"],
        rows,
    )


def _span_key(process: str, span_id: str) -> str:
    return f"{process}:{span_id}"


def _parent_key(process: str, parent_id: str) -> str:
    """Wire-crossing parents arrive already qualified (``proc:span``);
    bare parent ids are same-process by construction."""
    return parent_id if ":" in parent_id else f"{process}:{parent_id}"


def _gather_traces(events: Iterable[Event]) -> Dict[str, List[Dict[str, Any]]]:
    """trace_id -> span dicts, each carrying the process stamp the merged
    fleet log attached (empty for a single-process log)."""
    traces: Dict[str, List[Dict[str, Any]]] = {}
    for ev in events:
        if not isinstance(ev, SpanRecorded):
            continue
        process = str(getattr(ev, "process", "") or "")
        traces.setdefault(ev.trace_id, []).append({
            "name": ev.name,
            "process": process,
            "key": _span_key(process, ev.span_id),
            "parent": _parent_key(process, ev.parent_id)
            if ev.parent_id else "",
            "start": float(ev.wall_start),
            "duration": float(ev.duration),
            "status": ev.status,
        })
    return traces


def _walk_trace(spans: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Depth-first tree order with a ``depth`` per span — roots are spans
    whose (qualified) parent never appears in this trace."""
    by_key = {s["key"]: s for s in spans}
    children: Dict[str, List[Dict[str, Any]]] = {}
    roots: List[Dict[str, Any]] = []
    for s in spans:
        parent = s["parent"]
        if parent and parent in by_key:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    ordered: List[Dict[str, Any]] = []

    def visit(span: Dict[str, Any], depth: int) -> None:
        ordered.append(dict(span, depth=depth))
        for child in sorted(
            children.get(span["key"], []), key=lambda c: c["start"]
        ):
            visit(child, depth + 1)

    for root in sorted(roots, key=lambda s: s["start"]):
        visit(root, 0)
    return ordered


def _trace_waterfall(
    events: Iterable[Event], max_traces: int = 5
) -> str:
    """The cross-process trace view: for each of the most interesting
    traces (most processes involved, then most spans), a gantt where every
    span is offset on the shared wall clock and colored by process —
    router hop, replica queue/batch/apply, and gang workers on one axis —
    followed by one collapsed lane per process."""
    traces = _gather_traces(events)
    if not traces:
        return (
            '<p class="muted">no spans in this log '
            "(spans are published when the event bus is active)</p>"
        )
    ranked = sorted(
        traces.items(),
        key=lambda kv: (
            -len({s["process"] for s in kv[1]}),
            -len(kv[1]),
            kv[0],
        ),
    )[:max_traces]
    out: List[str] = []
    for trace_id, spans in ranked:
        ordered = _walk_trace(spans)
        processes = sorted({s["process"] for s in ordered})
        palette = {p: i % 6 for i, p in enumerate(processes)}
        t0 = min(s["start"] for s in ordered)
        t1 = max(s["start"] + s["duration"] for s in ordered)
        span_s = max(t1 - t0, 1e-9)
        out.append(
            f"<h3>trace <code>{_esc(trace_id)}</code> "
            f'<span class="muted">({len(ordered)} spans, '
            f"{len(processes)} processes)</span></h3>"
        )
        for s in ordered:
            left = 100.0 * (s["start"] - t0) / span_s
            width = max(100.0 * s["duration"] / span_s, 0.5)
            cls = f'bar p{palette[s["process"]]}'
            if s["status"] != "ok":
                cls = "bar failed"
            indent = "&nbsp;" * (2 * s["depth"])
            label = f'{s["process"] or "local"} &middot; {_esc(s["name"])}'
            out.append(
                f'<div class="bar-row"><div class="bar-label" '
                f'title="{_esc(s["name"])}">{indent}{label}</div>'
                f'<div class="bar-track"><div class="{cls}" '
                f'style="left:{left:.2f}%;width:{width:.2f}%"></div></div>'
                f'<div style="width:6em;text-align:right">'
                f'{s["duration"] * 1e3:.1f} ms</div></div>'
            )
        if len(processes) > 1:
            # one collapsed lane per process: where each process spent the
            # trace's wall clock, side by side
            for proc in processes:
                bars = []
                for s in ordered:
                    if s["process"] != proc:
                        continue
                    left = 100.0 * (s["start"] - t0) / span_s
                    width = max(100.0 * s["duration"] / span_s, 0.5)
                    bars.append(
                        f'<div class="bar p{palette[proc]}" '
                        f'title="{_esc(s["name"])}" '
                        f'style="left:{left:.2f}%;width:{width:.2f}%"></div>'
                    )
                out.append(
                    f'<div class="bar-row"><div class="lane-label">'
                    f'lane: {_esc(proc or "local")}</div>'
                    f'<div class="bar-track">{"".join(bars)}</div>'
                    f'<div style="width:6em"></div></div>'
                )
    return "".join(out)


def _incidents_table(incidents: List[Dict[str, Any]]) -> str:
    if not incidents:
        return '<p class="muted">no incidents recorded</p>'
    return _table(
        ["incident", "trigger", "trace", "bundle", "detail"],
        [[
            _esc(i.get("incident_id", "")),
            _esc(i.get("trigger", "")),
            f'<code>{_esc(i["trace_id"])}</code>'
            if i.get("trace_id") else "&mdash;",
            f'<code>{_esc(i.get("path", ""))}</code>',
            _esc(i.get("detail", "")),
        ] for i in incidents],
    )


def render_report(
    events: Iterable[Event],
    metrics: Optional[Dict[str, Any]] = None,
    title: str = "mmlspark-tpu run",
) -> str:
    """One self-contained HTML page for an event stream + optional
    ``registry.summary()`` snapshot."""
    events = list(events)
    summary = timeline(events)
    slo = SLOReport.fold(metrics or {}, events=events)
    tasks = summary["tasks"]
    req = summary["requests"]
    procs = summary["processes"]
    streaming = summary["streaming"]

    cards = [
        _card("events", len(events)),
        _card("stages", len(summary["stages"])),
        _card("tasks dispatched", tasks["dispatched"]),
        _card("task failures", tasks["failed"]),
        _card("requests", req["count"]),
        _card("requests shed", req.get("shed", 0)),
        _card("models committed", len(summary["models"])),
    ]
    if procs.get("started"):
        cards.append(_card("processes lost", procs.get("lost", 0)))
    if streaming.get("epochs"):
        cards.append(_card("stream epochs", streaming["epochs"]))
    by_process = summary.get("by_process") or {}
    if by_process:
        cards.append(_card("fleet processes", len(by_process)))
    if summary.get("incidents"):
        cards.append(_card("incidents", len(summary["incidents"])))
    quality = summary.get("quality") or {}
    alerts = summary.get("alerts") or {}
    if quality.get("detected"):
        cards.append(_card("drift onsets", quality["detected"]))
    if alerts.get("fired"):
        cards.append(_card("alerts fired", alerts["fired"]))

    sections = [
        f"<h1>{_esc(title)}</h1>",
        f'<div class="cards">{"".join(cards)}</div>',
        "<h2>Stage timeline</h2>",
        _stage_timeline(summary["stages"]),
        "<h2>Task attempts</h2>",
        "<p>dispatched={d} retried={r} failed={f} permanent={p} "
        "speculated={s} recovered={rec}</p>".format(
            d=tasks["dispatched"], r=tasks["retried"], f=tasks["failed"],
            p=tasks["failed_permanent"], s=tasks["speculated"],
            rec=tasks["recovered"],
        ),
        _attempts_table(tasks),
    ]

    if procs.get("started") or procs.get("lost"):
        reasons = ", ".join(
            f"{_esc(k)} &times;{v}"
            for k, v in sorted((procs.get("loss_reasons") or {}).items())
        )
        sections += [
            "<h2>Process groups</h2>",
            f"<p>started={procs['started']} lost={procs['lost']} "
            f"reformed={procs['reformed']}"
            + (f" ({reasons})" if reasons else "") + "</p>",
        ]

    sections += ["<h2>Serving SLO</h2>"]
    if req["count"] or metrics:
        md = slo.to_markdown()
        sections.append(_markdown_tables(md))
    else:
        sections.append('<p class="muted">no serving traffic in this log</p>')

    fleet = summary.get("fleet") or []
    routing = summary.get("routing") or {}
    if fleet or routing.get("count"):
        sections.append("<h2>Fleet</h2>")
        if routing.get("count"):
            avg = routing["hops"] / routing["count"]
            sections.append(
                f"<p>routed={routing['count']} "
                f"failovers={routing['failovers']} avg_hops={avg:.2f}</p>"
            )
            by_replica = routing.get("by_replica") or {}
            if by_replica:
                sections.append(_table(
                    ["replica", "requests"],
                    [[_esc(k), v] for k, v in sorted(by_replica.items())],
                ))
        if fleet:
            sections.append(_table(
                ["direction", "fleet size", "replica", "reason"],
                [[_esc(f["direction"]), f["replicas"], f.get("replica", -1),
                  _esc(f.get("reason", ""))] for f in fleet],
            ))

    if by_process:
        sections += [
            "<h2>Fleet event log</h2>",
            "<p>merged per-process segments "
            "(<code>events.jsonl@&lt;process&gt;</code>)</p>",
            _table(
                ["process", "events"],
                [[_esc(p), n] for p, n in sorted(by_process.items())],
            ),
        ]

    sections += [
        "<h2>Distributed traces</h2>",
        _trace_waterfall(events),
    ]

    if (
        quality.get("detected") or quality.get("cleared")
        or alerts.get("fired") or alerts.get("resolved")
    ):
        sections += [
            "<h2>Model quality</h2>",
            f"<p>drift detected={quality.get('detected', 0)} "
            f"cleared={quality.get('cleared', 0)} &middot; "
            f"alerts fired={alerts.get('fired', 0)} "
            f"resolved={alerts.get('resolved', 0)}</p>",
        ]
        features = quality.get("features") or {}
        if features:
            sections.append(_table(
                ["feature", "drift onsets", "cleared", "status"],
                [[
                    _esc(feat),
                    rec.get("detected", 0),
                    rec.get("cleared", 0),
                    '<span class="ok">recovered</span>'
                    if rec.get("cleared", 0) >= rec.get("detected", 0)
                    else '<span class="missed">drifting</span>',
                ] for feat, rec in sorted(features.items())],
            ))
        history = alerts.get("history") or []
        if history:
            sections.append(_table(
                ["alert", "slo", "transition", "burn short", "burn long"],
                [[
                    _esc(a.get("alert", "")),
                    _esc(a.get("slo", "")),
                    '<span class="missed">fired</span>'
                    if a.get("state") == "fired"
                    else '<span class="ok">resolved</span>',
                    f"{a.get('burn_short', 0.0):.2f}x",
                    f"{a.get('burn_long', 0.0):.2f}x",
                ] for a in history],
            ))

    if summary.get("incidents"):
        sections += [
            "<h2>Incidents</h2>",
            _incidents_table(summary["incidents"]),
        ]

    breakers = summary["breaker_trips"]
    swaps = summary["swaps"]
    if breakers or swaps:
        sections.append("<h2>Resilience</h2>")
        if breakers:
            sections.append(_table(
                ["breaker", "trips"],
                [[_esc(k), v] for k, v in sorted(breakers.items())],
            ))
        if swaps:
            sections.append(_table(
                ["model", "version", "server"],
                [[_esc(s["name"]), s["version"], _esc(s.get("server", ""))]
                 for s in swaps],
            ))

    if streaming.get("epochs"):
        queries = ", ".join(
            f"{_esc(q)}: epochs {min(eps)}&ndash;{max(eps)}"
            for q, eps in sorted((streaming.get("queries") or {}).items())
        )
        sections += [
            "<h2>Streaming</h2>",
            f"<p>epochs={streaming['epochs']} rows={streaming['rows']} "
            f"source_units={streaming.get('source_units', 0)}"
            + (f" ({queries})" if queries else "") + "</p>",
        ]

    sections += [
        "<h2>Profiler roofline</h2>",
        _roofline_table(summary["profiler"]),
    ]
    if summary["models"]:
        sections += [
            "<h2>Models</h2>",
            "<p>" + ", ".join(_esc(m) for m in summary["models"]) + "</p>",
        ]

    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        f"<title>{_esc(title)}</title><style>{_CSS}</style></head>"
        "<body>" + "".join(sections) + "</body></html>"
    )


def _markdown_tables(md: str) -> str:
    """Inline conversion of the SLOReport markdown (pipe tables and bare
    paragraphs only) to HTML — keeps the report dependency-free."""
    out: List[str] = []
    rows: List[List[str]] = []

    def flush():
        if rows:
            out.append(_table(
                rows[0], [[_esc(c) for c in r] for r in rows[1:]]
            ))
            rows.clear()

    for line in md.splitlines():
        line = line.strip()
        if line.startswith("|"):
            cells = [c.strip() for c in line.strip("|").split("|")]
            if all(set(c) <= {"-", ":", " "} and c for c in cells):
                continue  # separator row
            rows.append(cells)
        else:
            flush()
            if line:
                out.append(f"<p>{_esc(line)}</p>")
    flush()
    return "".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m mmlspark_tpu.observability.history",
        description="Render an event log into a self-contained HTML report.",
    )
    parser.add_argument("eventlog", help="JSON-lines event log path")
    parser.add_argument(
        "-o", "--output", default=None,
        help="output HTML path (default: <eventlog>.html)",
    )
    parser.add_argument(
        "--metrics", default=None,
        help="optional registry.summary() JSON snapshot to fold in",
    )
    parser.add_argument("--title", default=None, help="report title")
    args = parser.parse_args(argv)

    # a base path with per-process siblings (events.jsonl@replica-0, ...)
    # renders the federated fleet view; a plain log (including an
    # already-merged file, whose records carry process stamps) replays
    segments = collect(args.eventlog)
    if len(segments) > 1:
        events = merge(args.eventlog)
        print(
            f"federating {len(segments)} process logs: "
            + ", ".join(sorted(segments)),
            file=sys.stderr,
        )
    else:
        events = replay(args.eventlog)
    metrics = None
    if args.metrics:
        with open(args.metrics) as fh:
            metrics = json.load(fh)
    out_path = args.output or (args.eventlog + ".html")
    doc = render_report(
        events, metrics=metrics, title=args.title or args.eventlog
    )
    with open(out_path, "w") as fh:
        fh.write(doc)
    print(out_path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
