"""Fleet metrics federation — one labeled registry over every replica.

The serving fleet's replicas are separate processes, each exposing its
own process-global :class:`~mmlspark_tpu.observability.registry.MetricsRegistry`
at ``GET /metrics``. Until now the control plane steered on heartbeat
metadata (the three load fields replicas self-report into ``/services``);
this module gives it the real thing:

- :func:`parse_exposition` reads the Prometheus text format (version
  0.0.4) back into typed samples — the exact inverse of
  :meth:`MetricsRegistry.exposition`;
- :class:`MetricsFederator` discovers live replicas via the registry's
  ``GET /services``, scrapes each one's ``/metrics``, and folds the
  samples into ONE registry where every series carries a
  ``replica="<name>"`` label — the Spark "metrics from every executor in
  the driver UI" view. Histograms are reconstructed bucket-for-bucket,
  so fleet-wide ``p99`` interpolation works on the federated registry
  exactly as it does on a local one;
- :meth:`MetricsFederator.fleet_signals` derives the autoscaler's
  steering signals (inflight, cumulative sheds, queue-wait p99) per
  replica from the scrape, replacing heartbeat lag with live truth;
- :meth:`MetricsFederator.snapshot` is the JSON-able fleet state the
  incident flight recorder bundles.

A scrape failure (replica died between ``/services`` and ``/metrics``)
is recorded in ``last_errors`` and skipped — federation must never take
down the control loop that consumes it.
"""

from __future__ import annotations

import json
import math
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from mmlspark_tpu.core.profiling import get_logger
from mmlspark_tpu.observability.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

logger = get_logger("mmlspark_tpu.observability")

#: one ``name="value"`` pair inside an exposition label set
_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')

Sample = Tuple[str, Dict[str, str], float]


def _unescape(value: str) -> str:
    return value.replace('\\"', '"').replace("\\\\", "\\")


def _parse_value(text: str) -> float:
    if text == "+Inf":
        return math.inf
    if text == "-Inf":
        return -math.inf
    return float(text)


def parse_exposition(text: str) -> Tuple[Dict[str, str], List[Sample]]:
    """Prometheus text format -> (``{name: kind}``, ``[(name, labels,
    value), ...]``) — the inverse of :meth:`MetricsRegistry.exposition`.
    Unparseable lines are skipped (scrapes must be best-effort)."""
    kinds: Dict[str, str] = {}
    samples: List[Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                kinds[parts[2]] = parts[3].strip()
            continue
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                labels_str, sep, value_str = rest.rpartition("}")
                if not sep:
                    continue
                labels = {
                    k: _unescape(v) for k, v in _LABEL_RE.findall(labels_str)
                }
            else:
                name, sep, value_str = line.rpartition(" ")
                if not sep:
                    continue
                labels = {}
            samples.append((name.strip(), labels, _parse_value(value_str)))
        except ValueError:
            continue
    return kinds, samples


def _base_name(name: str, kinds: Dict[str, str]) -> Tuple[str, str]:
    """(metric base name, series role) for one sample name: histograms
    expose ``_bucket``/``_sum``/``_count`` series under their base."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if kinds.get(base) == "histogram":
                return base, suffix[1:]
    return name, "value"


def _bucket_percentile(
    bounds: List[float], cumulative: List[float], q: float
) -> float:
    """The same bucket-interpolated quantile :meth:`Histogram.percentile`
    computes, over scraped cumulative bucket counts (finite bounds only;
    the +Inf overflow is ``cumulative[-1]``)."""
    total = cumulative[-1] if cumulative else 0.0
    if total <= 0:
        return 0.0
    rank = q * total
    prev = 0.0
    for i, bound in enumerate(bounds):
        cum = cumulative[i]
        in_bucket = cum - prev
        if cum >= rank and in_bucket > 0:
            lo = bounds[i - 1] if i > 0 else 0.0
            frac = (rank - prev) / in_bucket
            return lo + (bound - lo) * min(max(frac, 0.0), 1.0)
        prev = cum
    return bounds[-1] if bounds else 0.0


def _default_fetch(url: str, timeout_s: float) -> str:
    from urllib.request import urlopen

    with urlopen(url, timeout=timeout_s) as resp:
        return resp.read().decode("utf-8")


class MetricsFederator:
    """Scrape every live replica's ``/metrics`` into one labeled registry.

    ``fetch(url, timeout_s) -> str`` is injectable for tests; the default
    is a plain ``urllib`` GET. ``scrape()`` returns a **fresh**
    federated :class:`MetricsRegistry` each call — federation is a
    snapshot, not an accumulator, so a retired replica's series vanish
    with it."""

    def __init__(
        self,
        registry_url: str,
        timeout_s: float = 2.0,
        fetch: Optional[Callable[[str, float], str]] = None,
    ):
        self.registry_url = registry_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self._fetch = fetch or _default_fetch
        self._lock = threading.Lock()
        #: replica name -> error string from the last scrape round
        self.last_errors: Dict[str, str] = {}
        #: replica name -> (kinds, samples) from the last scrape round
        self._last: Dict[str, Tuple[Dict[str, str], List[Sample]]] = {}
        self.last_scrape_at: Optional[float] = None

    # -- discovery -----------------------------------------------------------

    def services(self) -> List[Dict[str, Any]]:
        """The registry's ``GET /services`` list (empty on error)."""
        try:
            body = self._fetch(self.registry_url + "/services", self.timeout_s)
            payload = json.loads(body)
            # the registration service serves a bare JSON list; accept the
            # {"services": [...]} envelope too for other control planes
            services = (
                payload.get("services", [])
                if isinstance(payload, dict) else payload
            )
            return [s for s in services if s.get("host") and s.get("port")]
        except Exception as e:  # noqa: BLE001 - control plane may be mid-restart
            logger.debug("federator: /services unreadable: %s", e)
            return []

    # -- scrape --------------------------------------------------------------

    def poll(
        self, services: Optional[List[Dict[str, Any]]] = None
    ) -> Dict[str, Tuple[Dict[str, str], List[Sample]]]:
        """One scrape round: fetch + parse every replica's ``/metrics``.
        Returns ``{replica: (kinds, samples)}``; failures land in
        ``last_errors`` and the replica is skipped."""
        if services is None:
            services = self.services()
        scraped: Dict[str, Tuple[Dict[str, str], List[Sample]]] = {}
        errors: Dict[str, str] = {}
        for svc in services:
            name = str(svc.get("name") or f"{svc['host']}:{svc['port']}")
            url = f"http://{svc['host']}:{svc['port']}/metrics"
            try:
                scraped[name] = parse_exposition(
                    self._fetch(url, self.timeout_s)
                )
            except Exception as e:  # noqa: BLE001 - replica may have just died
                errors[name] = str(e)
        with self._lock:
            self._last = scraped
            self.last_errors = errors
            self.last_scrape_at = time.time()
        return scraped

    def scrape(
        self, services: Optional[List[Dict[str, Any]]] = None
    ) -> MetricsRegistry:
        """Poll the fleet and fold every sample into one fresh registry
        with a ``replica`` label per series — ``registry.summary()`` /
        ``exposition()`` /  histogram ``percentile()`` then answer
        fleet-wide questions directly."""
        scraped = self.poll(services)
        reg = MetricsRegistry()
        for replica, (kinds, samples) in sorted(scraped.items()):
            self._fold(reg, replica, kinds, samples)
        return reg

    def _fold(
        self,
        reg: MetricsRegistry,
        replica: str,
        kinds: Dict[str, str],
        samples: List[Sample],
    ) -> None:
        # histograms first: gather each series' bucket/sum/count parts
        hists: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], Dict[str, Any]] = {}
        for name, labels, value in samples:
            base, role = _base_name(name, kinds)
            if role == "value":
                kind = kinds.get(base, "")
                if kind == "counter" or (not kind and base.endswith("_total")):
                    reg.counter(base).labels(replica=replica, **labels).inc(value)
                else:
                    reg.gauge(base).labels(replica=replica, **labels).set(value)
                continue
            key_labels = {k: v for k, v in labels.items() if k != "le"}
            rec = hists.setdefault(
                (base, tuple(sorted(key_labels.items()))),
                {"buckets": {}, "sum": 0.0, "count": 0, "labels": key_labels},
            )
            if role == "bucket":
                rec["buckets"][_parse_value(labels.get("le", "+Inf"))] = value
            elif role == "sum":
                rec["sum"] = value
            else:
                rec["count"] = int(value)
        for (base, _), rec in sorted(hists.items()):
            bounds = sorted(b for b in rec["buckets"] if b != math.inf)
            parent = reg.histogram(base, buckets=bounds or None)
            child = parent.labels(replica=replica, **rec["labels"])
            # load the scraped cumulative counts back into per-bucket
            # occupancy (the +Inf overflow is count minus the last bound)
            with child._lock:
                prev = 0.0
                counts = []
                for b in child.buckets:
                    cum = rec["buckets"].get(b, prev)
                    counts.append(int(cum - prev))
                    prev = cum
                counts.append(max(int(rec["count"] - prev), 0))
                child._counts = counts
                child._sum = float(rec["sum"])
                child._count = int(rec["count"])

    # -- derived views -------------------------------------------------------

    def fleet_signals(
        self,
        services: Optional[List[Dict[str, Any]]] = None,
        scraped: Optional[
            Dict[str, Tuple[Dict[str, str], List[Sample]]]
        ] = None,
    ) -> Dict[str, Dict[str, float]]:
        """Per-replica autoscaler signals from a live scrape:
        ``{replica: {inflight, shed_total, p99_ms}}`` — what the
        heartbeat load metadata approximates, read at the source."""
        if scraped is None:
            scraped = self.poll(services)
        out: Dict[str, Dict[str, float]] = {}
        for replica, (kinds, samples) in scraped.items():
            inflight = shed = 0.0
            bounds: List[float] = []
            cumulative: List[float] = []
            inf_cum = 0.0
            for name, labels, value in samples:
                if name == "serving_inflight" and not labels:
                    inflight = value
                elif name == "serving_shed_total" and not labels:
                    shed = value
                elif name == "serving_queue_wait_seconds_bucket":
                    le = _parse_value(labels.get("le", "+Inf"))
                    if le == math.inf:
                        inf_cum = value
                    else:
                        bounds.append(le)
                        cumulative.append(value)
            pairs = sorted(zip(bounds, cumulative))
            bounds = [b for b, _ in pairs]
            cumulative = [c for _, c in pairs] + [inf_cum]
            out[replica] = {
                "inflight": inflight,
                "shed_total": shed,
                "p99_ms": _bucket_percentile(bounds, cumulative, 0.99) * 1e3,
            }
        return out

    def snapshot(
        self, services: Optional[List[Dict[str, Any]]] = None
    ) -> Dict[str, Any]:
        """JSON-able fleet state: the federated registry summary, the
        per-replica signals, and any scrape errors — what the incident
        flight recorder bundles as ``metrics.json``."""
        if services is None:
            services = self.services()
        registry = self.scrape(services)
        with self._lock:
            scraped = dict(self._last)
        return {
            "services": services,
            "metrics": registry.summary(),
            "signals": self.fleet_signals(services, scraped=scraped),
            "errors": dict(self.last_errors),
        }
