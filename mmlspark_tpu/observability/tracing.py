"""Request/stage tracing — Dapper-style spans with ``contextvars`` propagation.

Spark's UI reconstructs "what ran inside what" from listener events; a
serving stack needs the stronger form: a trace id minted at the request
edge that survives thread hops (HTTP handler -> micro-batch loop ->
model apply) so one request's full span tree can be read back. This
module is that layer:

- :class:`Span` — name, ids, monotonic start/end, tags, status;
- :class:`Tracer` — ``with tracer.span("stage"):`` opens a child of the
  ambient span (a ``contextvars.ContextVar``, so nesting follows the
  call stack and is async/thread-correct); ``start_span``/``finish``
  are the manual form for spans that cross threads (the scheduler's
  attempts, the serving batch loop);
- ids are **deterministic**: process-wide counters, not random — two
  identical single-threaded runs produce identical span ids, which is
  what replay-based tests want;
- :class:`TraceContext` carries a trace across the **wire**
  (``X-Trace-Id`` / ``X-Parent-Span-Id`` headers, or a plain dict in a
  process-group epoch spec); ``start_span(..., context=ctx)`` opens a
  span whose trace id came from another process. Wire parent ids are
  qualified ``<process>:<span_id>`` so the merged fleet log can resolve
  parents unambiguously even though every process mints span ids from
  its own counter;
- every span entered through the context manager is bridged into
  :func:`mmlspark_tpu.core.profiling.annotate`, so an active xprof
  device trace shows the same names as the exported span tree.

Finished spans accumulate in a bounded ring (default 4096) and export
to JSON via :meth:`Tracer.export`. When the event bus has listeners
(``MMLSPARK_TPU_EVENT_LOG`` set), every finished span is also published
as a :class:`~mmlspark_tpu.observability.events.SpanRecorded` event, so
the per-process event-log segments carry the span stream the history
server's cross-process waterfall is rebuilt from.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import dataclasses
import json
import threading
import time
from typing import Any, Dict, Iterator, List, Optional


@dataclasses.dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start: float
    end: Optional[float] = None
    status: str = "ok"
    tags: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def to_record(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "duration": self.duration,
            "status": self.status,
            "tags": dict(self.tags),
        }


#: wire headers a :class:`TraceContext` rides in (HTTP hop or epoch spec)
TRACE_HEADER = "X-Trace-Id"
PARENT_HEADER = "X-Parent-Span-Id"


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """A trace's identity off the wire: enough to parent a local span
    under a span minted in another process.

    ``parent_span_id`` is **qualified** as ``<process>:<span_id>`` when it
    crosses a process boundary (see :meth:`from_span`) — span-id counters
    are per-process, so the bare id alone is ambiguous in a merged fleet
    log. In-process parent ids stay bare; the history server resolves a
    bare id within the owning process first.
    """

    trace_id: str
    parent_span_id: str = ""

    def to_headers(self) -> Dict[str, str]:
        """The HTTP carrier: ``X-Trace-Id`` (+ ``X-Parent-Span-Id``)."""
        headers = {TRACE_HEADER: self.trace_id}
        if self.parent_span_id:
            headers[PARENT_HEADER] = self.parent_span_id
        return headers

    @classmethod
    def from_headers(cls, headers: Any) -> Optional["TraceContext"]:
        """Parse the carrier headers (any ``.get``-able mapping, e.g.
        ``BaseHTTPRequestHandler.headers``); None when no trace rode in."""
        if headers is None:
            return None
        trace_id = headers.get(TRACE_HEADER)
        if not trace_id:
            return None
        return cls(
            trace_id=str(trace_id),
            parent_span_id=str(headers.get(PARENT_HEADER) or ""),
        )

    @classmethod
    def from_span(cls, span: Span) -> "TraceContext":
        """The context to ship when ``span`` is the remote parent; the
        parent id is qualified with this process's event-log label."""
        from mmlspark_tpu.observability.events import process_label

        return cls(
            trace_id=span.trace_id,
            parent_span_id=f"{process_label()}:{span.span_id}",
        )

    def to_dict(self) -> Dict[str, str]:
        """JSON-able form for non-HTTP carriers (epoch specs)."""
        return {"trace_id": self.trace_id, "parent_span_id": self.parent_span_id}

    @classmethod
    def from_dict(cls, rec: Optional[Dict[str, Any]]) -> Optional["TraceContext"]:
        if not rec or not rec.get("trace_id"):
            return None
        return cls(
            trace_id=str(rec["trace_id"]),
            parent_span_id=str(rec.get("parent_span_id") or ""),
        )


class Tracer:
    """Span factory + ambient-span propagation + finished-span ring.

    ``xprof=True`` (the default) mirrors context-managed spans into
    ``core.profiling.annotate`` so device traces carry the same names;
    the bridge is skipped silently when jax is unavailable.
    """

    def __init__(self, max_spans: int = 4096, xprof: bool = True):
        self._lock = threading.Lock()
        self._trace_seq = 0
        self._span_seq = 0
        self._finished: "collections.deque[Span]" = collections.deque(
            maxlen=max_spans
        )
        self._current: "contextvars.ContextVar[Optional[Span]]" = (
            contextvars.ContextVar("mmlspark_tpu_span", default=None)
        )
        self._xprof = xprof

    # -- ids (deterministic: counters, not random) ---------------------------

    def _next_ids(self, parent: Optional[Span]) -> tuple:
        with self._lock:
            self._span_seq += 1
            span_id = f"{self._span_seq:08x}"
            if parent is not None:
                return parent.trace_id, span_id
            self._trace_seq += 1
            return f"t{self._trace_seq:08x}", span_id

    # -- ambient span --------------------------------------------------------

    def current(self) -> Optional[Span]:
        return self._current.get()

    @contextlib.contextmanager
    def attach(self, span: Optional[Span]) -> Iterator[None]:
        """Make ``span`` ambient for the body — how a worker thread joins
        a trace started elsewhere (pass the parent captured at submit)."""
        token = self._current.set(span)
        try:
            yield
        finally:
            self._current.reset(token)

    # -- manual spans (cross-thread lifecycles) ------------------------------

    def start_span(
        self,
        name: str,
        parent: Optional[Span] = None,
        context: Optional[TraceContext] = None,
        **tags: Any,
    ) -> Span:
        """Open a span without making it ambient. ``parent=None`` uses the
        ambient span; a detached root needs an explicit ``parent`` of a
        fresh trace (or no ambient span). ``context`` adopts a trace that
        arrived over the wire: the span joins the remote trace id with the
        (qualified) remote span as its parent — a local ``parent`` wins
        when both are given."""
        parent = parent if parent is not None else self.current()
        if parent is None and context is not None:
            with self._lock:
                self._span_seq += 1
                span_id = f"{self._span_seq:08x}"
            return Span(
                name=name,
                trace_id=context.trace_id,
                span_id=span_id,
                parent_id=context.parent_span_id or None,
                start=time.monotonic(),
                tags=dict(tags),
            )
        trace_id, span_id = self._next_ids(parent)
        return Span(
            name=name,
            trace_id=trace_id,
            span_id=span_id,
            parent_id=parent.span_id if parent is not None else None,
            start=time.monotonic(),
            tags=dict(tags),
        )

    def finish(self, span: Span, status: str = "ok", **tags: Any) -> Span:
        span.end = time.monotonic()
        span.status = status
        if tags:
            span.tags.update(tags)
        with self._lock:
            self._finished.append(span)
        self._publish(span)
        return span

    def _publish(self, span: Span) -> None:
        """Mirror a finished span onto the event bus (SpanRecorded) so the
        per-process event-log segments carry the span stream; free when
        nobody listens."""
        from mmlspark_tpu.observability import events as _events

        bus = _events.get_bus()
        if not bus.active:
            return
        duration = span.duration or 0.0
        bus.publish(_events.SpanRecorded(
            name=span.name,
            trace_id=span.trace_id,
            span_id=span.span_id,
            parent_id=span.parent_id or "",
            start=span.start,
            duration=duration,
            wall_start=time.time() - duration,
            status=span.status,
            tags={
                k: v for k, v in span.tags.items()
                if isinstance(v, (str, int, float, bool))
            },
        ))

    # -- context-managed spans (the common form) -----------------------------

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        parent: Optional[Span] = None,
        context: Optional[TraceContext] = None,
        **tags: Any,
    ) -> Iterator[Span]:
        """Open a span as a child of ``parent`` (default: the ambient
        span; ``context`` joins a wire-propagated trace), make it ambient
        for the body, finish it on exit (status = exception class name on
        error), and mirror the name into any active xprof trace."""
        sp = self.start_span(name, parent=parent, context=context, **tags)
        token = self._current.set(sp)
        try:
            with self._annotate(name):
                yield sp
        except BaseException as e:
            self.finish(sp, status=type(e).__name__)
            raise
        else:
            self.finish(sp)
        finally:
            self._current.reset(token)

    @contextlib.contextmanager
    def _annotate(self, name: str) -> Iterator[None]:
        if not self._xprof:
            yield
            return
        try:
            from mmlspark_tpu.core.profiling import annotate
        except ImportError:  # pragma: no cover - jax is a hard dep in practice
            yield
            return
        with annotate(name):
            yield

    # -- export --------------------------------------------------------------

    def export(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        """Finished spans as JSON-able records, oldest first; optionally
        filtered to one trace."""
        with self._lock:
            spans = list(self._finished)
        return [
            s.to_record()
            for s in spans
            if trace_id is None or s.trace_id == trace_id
        ]

    def to_json(self, trace_id: Optional[str] = None) -> str:
        return json.dumps(self.export(trace_id), indent=2)

    def span_tree(self, trace_id: str) -> Dict[str, Any]:
        """One trace as a nested dict (children under "children"), the
        shape the acceptance check reads: request -> batch -> apply."""
        records = self.export(trace_id)
        by_id = {r["span_id"]: dict(r, children=[]) for r in records}
        roots = []
        for r in by_id.values():
            parent = by_id.get(r["parent_id"])
            if parent is not None:
                parent["children"].append(r)
            else:
                roots.append(r)
        return {"trace_id": trace_id, "roots": roots}

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process-global tracer every instrumented layer shares."""
    return _TRACER
