"""Request/stage tracing — Dapper-style spans with ``contextvars`` propagation.

Spark's UI reconstructs "what ran inside what" from listener events; a
serving stack needs the stronger form: a trace id minted at the request
edge that survives thread hops (HTTP handler -> micro-batch loop ->
model apply) so one request's full span tree can be read back. This
module is that layer:

- :class:`Span` — name, ids, monotonic start/end, tags, status;
- :class:`Tracer` — ``with tracer.span("stage"):`` opens a child of the
  ambient span (a ``contextvars.ContextVar``, so nesting follows the
  call stack and is async/thread-correct); ``start_span``/``finish``
  are the manual form for spans that cross threads (the scheduler's
  attempts, the serving batch loop);
- ids are **deterministic**: process-wide counters, not random — two
  identical single-threaded runs produce identical span ids, which is
  what replay-based tests want;
- every span entered through the context manager is bridged into
  :func:`mmlspark_tpu.core.profiling.annotate`, so an active xprof
  device trace shows the same names as the exported span tree.

Finished spans accumulate in a bounded ring (default 4096) and export
to JSON via :meth:`Tracer.export`.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import dataclasses
import json
import threading
import time
from typing import Any, Dict, Iterator, List, Optional


@dataclasses.dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start: float
    end: Optional[float] = None
    status: str = "ok"
    tags: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def to_record(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "start": self.start,
            "end": self.end,
            "duration": self.duration,
            "status": self.status,
            "tags": dict(self.tags),
        }


class Tracer:
    """Span factory + ambient-span propagation + finished-span ring.

    ``xprof=True`` (the default) mirrors context-managed spans into
    ``core.profiling.annotate`` so device traces carry the same names;
    the bridge is skipped silently when jax is unavailable.
    """

    def __init__(self, max_spans: int = 4096, xprof: bool = True):
        self._lock = threading.Lock()
        self._trace_seq = 0
        self._span_seq = 0
        self._finished: "collections.deque[Span]" = collections.deque(
            maxlen=max_spans
        )
        self._current: "contextvars.ContextVar[Optional[Span]]" = (
            contextvars.ContextVar("mmlspark_tpu_span", default=None)
        )
        self._xprof = xprof

    # -- ids (deterministic: counters, not random) ---------------------------

    def _next_ids(self, parent: Optional[Span]) -> tuple:
        with self._lock:
            self._span_seq += 1
            span_id = f"{self._span_seq:08x}"
            if parent is not None:
                return parent.trace_id, span_id
            self._trace_seq += 1
            return f"t{self._trace_seq:08x}", span_id

    # -- ambient span --------------------------------------------------------

    def current(self) -> Optional[Span]:
        return self._current.get()

    @contextlib.contextmanager
    def attach(self, span: Optional[Span]) -> Iterator[None]:
        """Make ``span`` ambient for the body — how a worker thread joins
        a trace started elsewhere (pass the parent captured at submit)."""
        token = self._current.set(span)
        try:
            yield
        finally:
            self._current.reset(token)

    # -- manual spans (cross-thread lifecycles) ------------------------------

    def start_span(
        self,
        name: str,
        parent: Optional[Span] = None,
        **tags: Any,
    ) -> Span:
        """Open a span without making it ambient. ``parent=None`` uses the
        ambient span; a detached root needs an explicit ``parent`` of a
        fresh trace (or no ambient span)."""
        parent = parent if parent is not None else self.current()
        trace_id, span_id = self._next_ids(parent)
        return Span(
            name=name,
            trace_id=trace_id,
            span_id=span_id,
            parent_id=parent.span_id if parent is not None else None,
            start=time.monotonic(),
            tags=dict(tags),
        )

    def finish(self, span: Span, status: str = "ok", **tags: Any) -> Span:
        span.end = time.monotonic()
        span.status = status
        if tags:
            span.tags.update(tags)
        with self._lock:
            self._finished.append(span)
        return span

    # -- context-managed spans (the common form) -----------------------------

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        parent: Optional[Span] = None,
        **tags: Any,
    ) -> Iterator[Span]:
        """Open a span as a child of ``parent`` (default: the ambient
        span), make it ambient for the body, finish it on exit (status =
        exception class name on error), and mirror the name into any
        active xprof trace."""
        sp = self.start_span(name, parent=parent, **tags)
        token = self._current.set(sp)
        try:
            with self._annotate(name):
                yield sp
        except BaseException as e:
            self.finish(sp, status=type(e).__name__)
            raise
        else:
            self.finish(sp)
        finally:
            self._current.reset(token)

    @contextlib.contextmanager
    def _annotate(self, name: str) -> Iterator[None]:
        if not self._xprof:
            yield
            return
        try:
            from mmlspark_tpu.core.profiling import annotate
        except ImportError:  # pragma: no cover - jax is a hard dep in practice
            yield
            return
        with annotate(name):
            yield

    # -- export --------------------------------------------------------------

    def export(self, trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
        """Finished spans as JSON-able records, oldest first; optionally
        filtered to one trace."""
        with self._lock:
            spans = list(self._finished)
        return [
            s.to_record()
            for s in spans
            if trace_id is None or s.trace_id == trace_id
        ]

    def to_json(self, trace_id: Optional[str] = None) -> str:
        return json.dumps(self.export(trace_id), indent=2)

    def span_tree(self, trace_id: str) -> Dict[str, Any]:
        """One trace as a nested dict (children under "children"), the
        shape the acceptance check reads: request -> batch -> apply."""
        records = self.export(trace_id)
        by_id = {r["span_id"]: dict(r, children=[]) for r in records}
        roots = []
        for r in by_id.values():
            parent = by_id.get(r["parent_id"])
            if parent is not None:
                parent["children"].append(r)
            else:
                roots.append(r)
        return {"trace_id": trace_id, "roots": roots}

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


_TRACER = Tracer()


def get_tracer() -> Tracer:
    """The process-global tracer every instrumented layer shares."""
    return _TRACER
