"""Model evaluation metrics as transformers
(reference ``train/ComputeModelStatistics.scala:56``,
``ComputePerInstanceStatistics.scala:42``).

Classification: accuracy, per-class/micro precision & recall, AUC (rank
statistic), confusion matrix. Regression: mse, rmse, r², mae. All computed
as whole-column numpy reductions — one pass, no per-row UDFs.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.params import HasLabelCol, Param, one_of, to_str
from mmlspark_tpu.core.pipeline import Transformer
from mmlspark_tpu.data.table import Table


def binary_auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """AUC via the Mann-Whitney rank statistic, fully vectorized
    (tied scores get their group's average rank)."""
    labels = np.asarray(labels, dtype=np.float64)
    scores = np.asarray(scores, dtype=np.float64)
    n_pos = float((labels == 1).sum())
    n_neg = float((labels == 0).sum())
    if n_pos == 0 or n_neg == 0:
        return float("nan")
    order = np.argsort(scores, kind="stable")
    sorted_scores = scores[order]
    boundary = np.concatenate([[True], sorted_scores[1:] != sorted_scores[:-1]])
    group = np.cumsum(boundary) - 1
    counts = np.bincount(group)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    avg_rank = starts + (counts + 1) / 2.0  # 1-based average rank per group
    ranks = np.empty(len(scores), dtype=np.float64)
    ranks[order] = avg_rank[group]
    rank_sum = ranks[labels == 1].sum()
    return float((rank_sum - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg))


def prob_class_index(values: np.ndarray) -> Optional[np.ndarray]:
    """Class-column indices into a probability matrix: the raw numeric values
    when they are non-negative integers (the learners' native class coding —
    column j of ``probability`` is P(class j)). Returns None for string or
    non-integral labels, where no alignment is derivable. This is distinct
    from :func:`remap_classes`, whose dense ids are ordered by *observed*
    distinct value and misalign with model class columns whenever the eval
    table sees only a subset of classes."""
    if values.dtype == object:
        return None
    v = values.astype(np.float64)
    if np.isnan(v).any() or not np.allclose(v, np.rint(v)) or v.min(initial=0) < 0:
        return None
    return np.rint(v).astype(np.int64)


def remap_classes(labels: np.ndarray, pred: np.ndarray):
    """Map label/prediction columns (numeric or string) onto dense class ids
    [0, k) ordered by sorted distinct value — the convention both metric
    stages share, so 1-D probability columns always mean P(highest class)."""
    if labels.dtype == object or pred.dtype == object:
        l_str = np.array([str(v) for v in labels])
        p_str = np.array([str(v) for v in pred])
        classes = np.unique(np.concatenate([l_str, p_str]))
        lookup = {c: i for i, c in enumerate(classes)}
        li = np.array([lookup[v] for v in l_str], dtype=np.int64)
        pi = np.array([lookup[v] for v in p_str], dtype=np.int64)
    else:
        l_num = labels.astype(np.float64)
        p_num = pred.astype(np.float64)
        classes = np.unique(np.concatenate([l_num, p_num]))
        lookup = {c: i for i, c in enumerate(classes)}
        li = np.array([lookup[v] for v in l_num], dtype=np.int64)
        pi = np.array([lookup[v] for v in p_num], dtype=np.int64)
    return li, pi, classes


class ComputeModelStatistics(HasLabelCol, Transformer):
    """Scored table -> one-row metrics table."""

    scoresCol = Param("Prediction column", default="prediction", converter=to_str)
    scoredProbabilitiesCol = Param(
        "Probability column (binary AUC)", default="probability", converter=to_str
    )
    evaluationMetric = Param(
        "classification | regression | auto",
        default="auto",
        converter=to_str,
        validator=one_of("classification", "regression", "auto"),
    )

    def _kind(self, table: Table) -> str:
        metric = self.getEvaluationMetric()
        if metric != "auto":
            return metric
        labels = table.column(self.getLabelCol())
        if labels.dtype == object:
            return "classification"
        labels = labels.astype(np.float64)
        uniq = np.unique(labels[~np.isnan(labels)])
        if len(uniq) <= max(20, int(np.sqrt(len(labels)))) and np.allclose(
            uniq, np.rint(uniq)
        ):
            return "classification"
        return "regression"

    def transform(self, table: Table) -> Table:
        labels = table.column(self.getLabelCol())
        pred = table.column(self.getScoresCol())
        if self._kind(table) == "classification":
            li, pi, classes = remap_classes(labels, pred)
            k = len(classes)
            confusion = np.zeros((k, k), dtype=np.int64)
            np.add.at(confusion, (li, pi), 1)
            accuracy = float((li == pi).mean())
            tp = np.diag(confusion).astype(np.float64)
            col_sums = confusion.sum(axis=0).astype(np.float64)
            row_sums = confusion.sum(axis=1).astype(np.float64)
            with np.errstate(invalid="ignore", divide="ignore"):
                precision = np.where(col_sums > 0, tp / col_sums, 0.0)
                recall = np.where(row_sums > 0, tp / row_sums, 0.0)
            weights = row_sums / row_sums.sum()
            metrics: Dict[str, float] = {
                "accuracy": accuracy,
                "precision": float((precision * weights).sum()),
                "recall": float((recall * weights).sum()),
            }
            if self.getScoredProbabilitiesCol() in table:
                probs = table.column(self.getScoredProbabilitiesCol())
                if probs.ndim == 1 and k == 2:
                    # 1-D probability = P(higher observed class): dense ids.
                    metrics["AUC"] = binary_auc(li, probs.astype(np.float64))
                elif probs.ndim == 2 and probs.shape[1] == 2:
                    # Columns are model class ids — use that coding when the
                    # labels fit it. A 2-column matrix cannot be a slice of a
                    # multiclass model, so otherwise (string labels, or
                    # reindexed codings like {1,2}) the sorted dense remap is
                    # the trainers' level indexing and is correct when two
                    # classes are observed.
                    li_raw = prob_class_index(labels)
                    if li_raw is not None and li_raw.max(initial=0) <= 1:
                        metrics["AUC"] = binary_auc(li_raw, probs[:, 1])
                    elif k == 2:
                        metrics["AUC"] = binary_auc(li, probs[:, 1])
            out = Table({name: np.array([value]) for name, value in metrics.items()})
            return out.with_column(
                "confusion_matrix", confusion.reshape(1, k * k).astype(np.float64)
            )
        labels = labels.astype(np.float64)
        pred = pred.astype(np.float64)
        err = pred - labels
        mse = float((err**2).mean())
        denom = float(((labels - labels.mean()) ** 2).sum())
        metrics = {
            "mean_squared_error": mse,
            "root_mean_squared_error": float(np.sqrt(mse)),
            "mean_absolute_error": float(np.abs(err).mean()),
            "R^2": float(1.0 - (err**2).sum() / denom) if denom > 0 else float("nan"),
        }
        return Table({name: np.array([value]) for name, value in metrics.items()})


class ComputePerInstanceStatistics(HasLabelCol, Transformer):
    """Appends per-row metrics (``ComputePerInstanceStatistics.scala:42``):
    regression -> L1/L2 loss; classification -> log-loss + correctness."""

    scoresCol = Param("Prediction column", default="prediction", converter=to_str)
    scoredProbabilitiesCol = Param(
        "Probability column", default="probability", converter=to_str
    )
    evaluationMetric = Param(
        "classification | regression | auto",
        default="auto",
        converter=to_str,
        validator=one_of("classification", "regression", "auto"),
    )

    _kind = ComputeModelStatistics._kind

    def transform(self, table: Table) -> Table:
        labels = table.column(self.getLabelCol())
        pred = table.column(self.getScoresCol())
        if self._kind(table) == "regression":
            err = pred.astype(np.float64) - labels.astype(np.float64)
            return table.with_columns(
                {"L1_loss": np.abs(err), "L2_loss": err**2}
            )
        # Same dense-id remap as ComputeModelStatistics: a 1-D probability
        # column means P(highest class) regardless of raw label coding.
        li, pi, _ = remap_classes(labels, pred)
        out = table.with_column("correct", (li == pi).astype(np.float64))
        if self.getScoredProbabilitiesCol() in table:
            probs = table.column(self.getScoredProbabilitiesCol())
            if probs.ndim == 2:
                # Index probability columns by the model's class coding (raw
                # integer labels) when the labels fit the column count; for
                # reindexed codings (e.g. {1,2} on a 2-column model) fall
                # back to the sorted dense remap the trainers index by.
                li_raw = prob_class_index(labels)
                if li_raw is not None and li_raw.max(initial=0) < probs.shape[1]:
                    li_prob = li_raw
                else:
                    li_prob = li
                idx = np.clip(li_prob, 0, probs.shape[1] - 1)
                p_true = probs[np.arange(len(li_prob)), idx]
            else:
                # 1-D probability = P(higher observed class): dense ids.
                p = probs.astype(np.float64)
                p_true = np.where(li == 1, p, 1.0 - p)
            out = out.with_column(
                "log_loss", -np.log(np.clip(p_true, 1e-15, 1.0))
            )
        return out
