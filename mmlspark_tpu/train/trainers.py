"""TrainClassifier / TrainRegressor — auto-featurize + fit any learner.

Re-design of ``train/TrainClassifier.scala:53`` / ``train/TrainRegressor.scala:24``:
wraps any Estimator, auto-featurizes non-vector inputs via
:class:`~mmlspark_tpu.featurize.Featurize`, reindexes string labels, and
returns a model carrying the featurization chain
(``TrainedClassifierModel:276`` keeps the pipeline the same way).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from mmlspark_tpu.core.params import (
    HasFeaturesCol,
    HasLabelCol,
    Param,
    gt,
    to_bool,
    to_int,
    to_str,
)
from mmlspark_tpu.core.pipeline import Estimator, Model, Transformer
from mmlspark_tpu.data.table import Table
from mmlspark_tpu.featurize.featurize import Featurize


class _TrainBase(HasLabelCol, HasFeaturesCol, Estimator):
    model = Param("The learner to fit", is_complex=True)
    featuresCol = Param(
        "Assembled features column", default="TrainedFeatures", converter=to_str
    )
    numFeatures = Param(
        "Text hash dimensions during featurization",
        default=1 << 8,
        converter=to_int,
        validator=gt(0),
    )

    def _feature_columns(self, table: Table) -> List[str]:
        label = self.getLabelCol()
        return [c for c in table.columns if c != label]

    def _prepare(self, table: Table):
        cols = self._feature_columns(table)
        featurizer = None
        feat_col = self.getFeaturesCol()
        if len(cols) == 1 and table.column(cols[0]).ndim == 2:
            # Already a single assembled vector column.
            feat_col = cols[0]
        else:
            from mmlspark_tpu.data.table import find_unused_column_name

            feat_col = find_unused_column_name(feat_col, table)
            featurizer = Featurize(
                inputCols=cols,
                outputCol=feat_col,
                numberOfFeatures=self.getNumFeatures(),
            ).fit(table)
            table = featurizer.transform(table)
        return table, featurizer, feat_col


class TrainClassifier(_TrainBase):
    """Featurize + reindex labels + fit a classifier."""

    reindexLabel = Param("Index string/sparse labels", default=True, converter=to_bool)

    def _fit(self, table: Table) -> "TrainedClassifierModel":
        work, featurizer, feat_col = self._prepare(table)
        label_col = self.getLabelCol()
        labels_raw = work.column(label_col)
        levels: Optional[List[Any]] = None
        if self.getReindexLabel():
            if labels_raw.dtype == object:
                levels = sorted({str(v) for v in labels_raw})
                lookup = {v: i for i, v in enumerate(levels)}
                y = np.array([lookup[str(v)] for v in labels_raw], dtype=np.float64)
            else:
                uniq = np.unique(labels_raw)
                if not np.array_equal(uniq, np.arange(len(uniq))):
                    levels = [v.item() for v in uniq]
                    lookup = {v: i for i, v in enumerate(levels)}
                    y = np.array(
                        [lookup[v.item()] for v in labels_raw], dtype=np.float64
                    )
                else:
                    y = labels_raw.astype(np.float64)
            work = work.with_column(label_col, y)
        learner = self.getModel().copy(
            {"featuresCol": feat_col, "labelCol": label_col}
        )
        fitted = learner.fit(work)
        model = TrainedClassifierModel(
            fittedModel=fitted,
            featurizerModel=featurizer,
            labelCol=label_col,
            featuresCol=feat_col,
            labelLevels=levels,
        )
        model.parent = self
        return model


class TrainRegressor(_TrainBase):
    def _fit(self, table: Table) -> "TrainedRegressorModel":
        work, featurizer, feat_col = self._prepare(table)
        label_col = self.getLabelCol()
        work = work.with_column(label_col, work.column(label_col).astype(np.float64))
        learner = self.getModel().copy(
            {"featuresCol": feat_col, "labelCol": label_col}
        )
        fitted = learner.fit(work)
        model = TrainedRegressorModel(
            fittedModel=fitted,
            featurizerModel=featurizer,
            labelCol=label_col,
            featuresCol=feat_col,
        )
        model.parent = self
        return model


class _TrainedBase(HasLabelCol, HasFeaturesCol, Model):
    fittedModel = Param("The fitted learner", is_complex=True)
    featurizerModel = Param("The fitted featurizer (None = passthrough)",
                            default=None, is_complex=True)

    def _featurize(self, table: Table) -> Table:
        featurizer = self.getFeaturizerModel()
        if featurizer is not None:
            table = featurizer.transform(table)
        return table


class TrainedClassifierModel(_TrainedBase):
    labelLevels = Param("Original label values (None = already indexed)",
                        default=None)

    def transform(self, table: Table) -> Table:
        fitted = self.getFittedModel()
        out = fitted.transform(self._featurize(table))
        levels = self.getLabelLevels()
        pred_col = (
            fitted.getPredictionCol()
            if fitted.hasParam("predictionCol")
            else "prediction"
        )
        if levels is not None and pred_col in out:
            from mmlspark_tpu.featurize.indexers import decode_levels

            out = out.with_column(
                pred_col, decode_levels(out.column(pred_col), levels)
            )
        return out


class TrainedRegressorModel(_TrainedBase):
    def transform(self, table: Table) -> Table:
        return self.getFittedModel().transform(self._featurize(table))
