"""Simplified train/eval API (reference ``train/`` — SURVEY.md §2.12)."""

from mmlspark_tpu.train.statistics import (
    ComputeModelStatistics,
    ComputePerInstanceStatistics,
)
from mmlspark_tpu.train.trainers import (
    TrainClassifier,
    TrainRegressor,
    TrainedClassifierModel,
    TrainedRegressorModel,
)

__all__ = [
    "ComputeModelStatistics",
    "ComputePerInstanceStatistics",
    "TrainClassifier",
    "TrainRegressor",
    "TrainedClassifierModel",
    "TrainedRegressorModel",
]
