"""Columnar data substrate: Table, readers, partitioning."""

from mmlspark_tpu.data.table import Table

__all__ = ["Table"]
