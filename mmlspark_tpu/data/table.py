"""Columnar, immutable Table — the framework's DataFrame.

TPU-native replacement for Spark DataFrames: instead of row-wise JVM objects
crossed per-row into native code (the reference's UDF pattern, e.g.
``opencv/ImageTransformer.scala``), a Table holds whole columns as host numpy
arrays. Stages transform entire columns at once, so device work is a handful
of large HBM transfers + one jitted XLA program — the layout the MXU wants.

Columns may be:
- 1-D numpy arrays (numeric, bool, or object dtype for strings),
- 2-D numpy arrays (fixed-width "vector" columns, like SparkML VectorUDT),
- object arrays of variable-length sequences (ragged; e.g. token lists).

``num_partitions`` is a logical hint mapping rows onto mesh data-parallel
shards — the analogue of Spark partitioning consumed by
``ClusterUtil.getNumExecutorCores`` / coalesce in the reference
(``lightgbm/LightGBMBase.scala:94-130``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Mapping, Optional, Sequence, Tuple, Union

import numpy as np

from mmlspark_tpu.data.sparse import SparseRows

ColumnLike = Union[np.ndarray, Sequence[Any]]


def _as_column(values: ColumnLike) -> np.ndarray:
    if isinstance(values, (np.ndarray, SparseRows)):
        return values
    try:
        import jax

        if isinstance(values, jax.Array):
            return np.asarray(values)
    except ImportError:  # pragma: no cover
        pass
    values = list(values)
    if values and isinstance(values[0], str):
        return np.array(values, dtype=object)
    if values and isinstance(values[0], (list, tuple, np.ndarray)):
        lengths = {len(v) for v in values}
        if len(lengths) == 1:
            arr = np.asarray(values)
            if arr.dtype != object:
                return arr
        out = np.empty(len(values), dtype=object)
        for i, v in enumerate(values):
            out[i] = v
        return out
    return np.asarray(values)


class Table:
    """An immutable, ordered collection of named columns of equal length."""

    __slots__ = ("_columns", "_num_rows", "_metadata", "num_partitions", "_partition_sizes")

    def __init__(
        self,
        columns: Mapping[str, ColumnLike],
        metadata: Optional[Dict[str, Dict[str, Any]]] = None,
        num_partitions: int = 1,
    ):
        cols: Dict[str, np.ndarray] = {}
        n: Optional[int] = None
        for name, values in columns.items():
            arr = _as_column(values)
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(
                    f"column {name!r} has length {len(arr)}, expected {n}"
                )
            cols[name] = arr
        self._columns = cols
        self._num_rows = n or 0
        self._metadata = dict(metadata or {})
        self.num_partitions = max(1, int(num_partitions))
        self._partition_sizes: Optional[List[int]] = None

    # -- construction -------------------------------------------------------

    @staticmethod
    def from_pandas(df: Any, num_partitions: int = 1) -> "Table":
        cols = {}
        for name in df.columns:
            s = df[name]
            if s.dtype == object:
                cols[name] = s.to_numpy(dtype=object)
            else:
                cols[name] = s.to_numpy()
        return Table(cols, num_partitions=num_partitions)

    @staticmethod
    def from_rows(rows: Sequence[Mapping[str, Any]], num_partitions: int = 1) -> "Table":
        if not rows:
            return Table({})
        names = list(rows[0].keys())
        return Table(
            {n: [r[n] for r in rows] for n in names}, num_partitions=num_partitions
        )

    # -- basic properties ----------------------------------------------------

    @property
    def columns(self) -> List[str]:
        return list(self._columns.keys())

    @property
    def num_rows(self) -> int:
        return self._num_rows

    def __len__(self) -> int:
        return self._num_rows

    def __contains__(self, name: str) -> bool:
        return name in self._columns

    def __getitem__(self, name: str) -> np.ndarray:
        return self._columns[name]

    def column(self, name: str) -> np.ndarray:
        if name not in self._columns:
            raise KeyError(
                f"no column {name!r}; available: {sorted(self._columns)}"
            )
        return self._columns[name]

    @property
    def schema(self) -> Dict[str, np.dtype]:
        return {k: v.dtype for k, v in self._columns.items()}

    def metadata(self, name: str) -> Dict[str, Any]:
        return self._metadata.get(name, {})

    # -- functional updates (all return new Tables) --------------------------

    def _derive(
        self,
        columns: Dict[str, np.ndarray],
        metadata: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> "Table":
        t = Table.__new__(Table)
        t._columns = columns
        t._num_rows = len(next(iter(columns.values()))) if columns else 0
        t._metadata = metadata if metadata is not None else dict(self._metadata)
        t.num_partitions = self.num_partitions
        # Explicit partition sizes survive only row-preserving derivations.
        t._partition_sizes = (
            self._partition_sizes if t._num_rows == self._num_rows else None
        )
        return t

    def with_column(
        self, name: str, values: ColumnLike, metadata: Optional[Dict[str, Any]] = None
    ) -> "Table":
        arr = _as_column(values)
        if self._columns and len(arr) != self._num_rows:
            raise ValueError(
                f"column {name!r} has length {len(arr)}, expected {self._num_rows}"
            )
        cols = dict(self._columns)
        cols[name] = arr
        meta = dict(self._metadata)
        if metadata is not None:
            meta[name] = metadata
        return self._derive(cols, meta)

    def with_columns(self, updates: Mapping[str, ColumnLike]) -> "Table":
        out = self
        for k, v in updates.items():
            out = out.with_column(k, v)
        return out

    def with_metadata(self, name: str, metadata: Dict[str, Any]) -> "Table":
        meta = dict(self._metadata)
        meta[name] = metadata
        return self._derive(dict(self._columns), meta)

    def select(self, *names: str) -> "Table":
        missing = [n for n in names if n not in self._columns]
        if missing:
            raise KeyError(f"no columns {missing}; available: {sorted(self._columns)}")
        return self._derive({n: self._columns[n] for n in names})

    def drop(self, *names: str) -> "Table":
        return self._derive(
            {k: v for k, v in self._columns.items() if k not in set(names)}
        )

    def rename(self, old: str, new: str) -> "Table":
        if old not in self._columns:
            raise KeyError(old)
        cols = {(new if k == old else k): v for k, v in self._columns.items()}
        meta = dict(self._metadata)
        if old in meta:
            meta[new] = meta.pop(old)
        return self._derive(cols, meta)

    def filter(self, mask: ColumnLike) -> "Table":
        mask = np.asarray(mask, dtype=bool)
        return self._derive({k: v[mask] for k, v in self._columns.items()})

    def take(self, indices: ColumnLike) -> "Table":
        idx = np.asarray(indices)
        return self._derive({k: v[idx] for k, v in self._columns.items()})

    def head(self, n: int = 5) -> "Table":
        return self._derive({k: v[:n] for k, v in self._columns.items()})

    def slice(self, start: int, stop: int) -> "Table":
        return self._derive({k: v[start:stop] for k, v in self._columns.items()})

    def sort_by(self, name: str, ascending: bool = True) -> "Table":
        """Stable sort by one column (ties keep row order, both directions)."""
        col = self.column(name)
        if ascending:
            order = np.argsort(col, kind="stable")
        else:
            # Stable descending: stable-ascending argsort of the reversed
            # column, mapped back to original indices, then reversed.
            n = len(col)
            order = (n - 1 - np.argsort(col[::-1], kind="stable"))[::-1]
        return self.take(order)

    def sample(self, fraction: float, seed: int = 0) -> "Table":
        rng = np.random.default_rng(seed)
        mask = rng.random(self._num_rows) < fraction
        return self.filter(mask)

    def random_split(
        self, weights: Sequence[float], seed: int = 0
    ) -> List["Table"]:
        rng = np.random.default_rng(seed)
        w = np.asarray(weights, dtype=float)
        w = w / w.sum()
        assignment = rng.choice(len(w), size=self._num_rows, p=w)
        return [self.filter(assignment == i) for i in range(len(w))]

    @staticmethod
    def concat(tables: Sequence["Table"]) -> "Table":
        tables = [t for t in tables if t.num_rows > 0] or list(tables[:1])
        if not tables:
            return Table({})
        names = tables[0].columns
        cols = {}
        for n in names:
            parts = [t.column(n) for t in tables]
            if any(isinstance(p, SparseRows) for p in parts):
                if all(isinstance(p, SparseRows) for p in parts):
                    cols[n] = SparseRows.concat(parts)
                    continue
                # mixed with legacy tuple columns: fall back to object merge
                parts = [
                    p.to_object_column() if isinstance(p, SparseRows) else p
                    for p in parts
                ]
            if any(p.dtype == object for p in parts):
                merged = np.empty(sum(len(p) for p in parts), dtype=object)
                i = 0
                for p in parts:
                    if p.ndim > 1:
                        # Dense multi-dim part into object slots: element-wise
                        # so each row keeps its array payload.
                        for row in p:
                            merged[i] = row
                            i += 1
                        continue
                    merged[i : i + len(p)] = p
                    i += len(p)
                cols[n] = merged
            else:
                cols[n] = np.concatenate(parts)
        out = Table(cols, metadata=dict(tables[0]._metadata))
        out.num_partitions = tables[0].num_partitions
        return out

    # -- partitioning (Spark-partition analogue) -----------------------------

    def repartition(self, n: int) -> "Table":
        out = self._derive(dict(self._columns))
        out.num_partitions = max(1, int(n))
        out._partition_sizes = None
        return out

    def with_partition_sizes(self, sizes: Sequence[int]) -> "Table":
        """Pin explicit contiguous partition sizes (must sum to num_rows) —
        used by partition-aware stages like StratifiedRepartition whose
        groups are not the default balanced split."""
        sizes = [int(s) for s in sizes]
        if sum(sizes) != self._num_rows:
            raise ValueError(
                f"partition sizes {sizes} sum to {sum(sizes)}, "
                f"expected {self._num_rows}"
            )
        out = self._derive(dict(self._columns))
        out.num_partitions = len(sizes)
        out._partition_sizes = sizes
        return out

    def coalesce(self, n: int) -> "Table":
        return self.repartition(min(self.num_partitions, n))

    def partition_bounds(self) -> List[Tuple[int, int]]:
        """Row ranges of each logical partition: explicit sizes when pinned,
        else a balanced contiguous split."""
        if self._partition_sizes is not None:
            edges = np.concatenate([[0], np.cumsum(self._partition_sizes)])
        else:
            edges = np.linspace(0, self._num_rows, self.num_partitions + 1).astype(int)
        return [
            (int(edges[i]), int(edges[i + 1])) for i in range(len(edges) - 1)
        ]

    def partitions(self) -> Iterator["Table"]:
        for lo, hi in self.partition_bounds():
            yield self.slice(lo, hi)

    # -- export --------------------------------------------------------------

    def to_pandas(self) -> Any:
        import pandas as pd

        return pd.DataFrame(
            {
                k: list(v) if (v.ndim > 1 or isinstance(v, SparseRows)) else v
                for k, v in self._columns.items()
            }
        )

    def to_dict(self) -> Dict[str, np.ndarray]:
        return dict(self._columns)

    def rows(self) -> Iterator[Dict[str, Any]]:
        names = self.columns
        for i in range(self._num_rows):
            yield {n: self._columns[n][i] for n in names}

    def __repr__(self) -> str:
        parts = ", ".join(
            f"{k}: {v.dtype}{list(v.shape[1:]) if v.ndim > 1 else ''}"
            for k, v in self._columns.items()
        )
        return f"Table[{self._num_rows} rows, {self.num_partitions} partitions]({parts})"


def row_as_json_dict(
    table: Table, row: int, exclude: Sequence[str] = ()
) -> Dict[str, Any]:
    """One row as a JSON-serializable dict (ndarray -> list, numpy scalar ->
    Python scalar) — the shared converter for REST writers (AddDocuments,
    PowerBIWriter)."""
    out: Dict[str, Any] = {}
    for name in table.columns:
        if name in exclude:
            continue
        v = table.column(name)[row]
        if isinstance(v, np.ndarray):
            v = v.tolist()
        elif isinstance(v, np.generic):
            v = v.item()
        out[name] = v
    return out


def find_unused_column_name(prefix: str, table: Table) -> str:
    """Analogue of ``DatasetExtensions.findUnusedColumnName``
    (``core/schema/DatasetExtensions.scala:71``)."""
    name = prefix
    i = 1
    while name in table:
        name = f"{prefix}_{i}"
        i += 1
    return name
